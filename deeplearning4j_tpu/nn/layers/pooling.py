"""GlobalPoolingLayer.

Reference parity: `nn/conf/layers/GlobalPoolingLayer.java` +
`nn/layers/pooling/GlobalPoolingLayer.java` — pools over time ([B,T,F]→[B,F])
or spatial dims (NHWC [B,H,W,C]→[B,C]), with masking support for variable-
length sequences (reference uses `util/MaskedReductionUtil.java`).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax.numpy as jnp

from ..conf.base import LayerConf, register_layer
from ..conf.input_type import InputType
from .convolution import PoolingType

__all__ = ["GlobalPoolingLayer"]


@register_layer
@dataclass
class GlobalPoolingLayer(LayerConf):
    input_kind = "any"

    pooling_type: str = PoolingType.MAX
    pnorm: int = 2
    collapse_dimensions: bool = True
    eps: float = 1e-8

    def output_type(self, it: InputType) -> InputType:
        if it.kind in ("rnn", "cnn1d"):
            return InputType.feed_forward(it.size)
        if it.kind == "cnn":
            return InputType.feed_forward(it.channels)
        return it

    def output_mask(self, mask):
        return None  # pooled axes collapsed: per-step mask no longer applies

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if x.ndim == 3:       # [B, T, F] over time
            axes = (1,)
        elif x.ndim == 4:     # [B, H, W, C] over space
            axes = (1, 2)
        else:
            raise ValueError(f"GlobalPooling expects 3-D/4-D input, got {x.ndim}-D")

        pt = self.pooling_type
        if mask is not None and x.ndim == 3:
            m = mask.astype(x.dtype)[:, :, None]  # [B, T, 1]
            if pt == PoolingType.MAX:
                neg = jnp.where(m > 0, x, -jnp.inf)
                out = jnp.max(neg, axis=1)
            elif pt == PoolingType.SUM:
                out = jnp.sum(x * m, axis=1)
            elif pt == PoolingType.AVG:
                out = jnp.sum(x * m, axis=1) / jnp.maximum(
                    jnp.sum(m, axis=1), 1.0)
            elif pt == PoolingType.PNORM:
                p = float(self.pnorm)
                out = (jnp.sum((jnp.abs(x) ** p) * m, axis=1) + self.eps) ** (1 / p)
            else:
                raise ValueError(f"Unknown pooling type '{pt}'")
            return out, state

        if pt == PoolingType.MAX:
            out = jnp.max(x, axis=axes)
        elif pt == PoolingType.SUM:
            out = jnp.sum(x, axis=axes)
        elif pt == PoolingType.AVG:
            out = jnp.mean(x, axis=axes)
        elif pt == PoolingType.PNORM:
            p = float(self.pnorm)
            out = (jnp.sum(jnp.abs(x) ** p, axis=axes) + self.eps) ** (1 / p)
        else:
            raise ValueError(f"Unknown pooling type '{pt}'")
        return out, state
