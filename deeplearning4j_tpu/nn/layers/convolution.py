"""Convolution + pooling + padding layers.

Reference parity:
  * ConvolutionLayer — `nn/conf/layers/ConvolutionLayer.java` +
    `nn/layers/convolution/ConvolutionLayer.java:52` (im2col-based) and the
    cuDNN helper `deeplearning4j-cuda/.../CudnnConvolutionHelper.java:49`.
    TPU-native: one `lax.conv_general_dilated` call in NHWC/HWIO layout —
    XLA tiles it straight onto the MXU; no im2col, no helper SPI, no
    algorithm selection (XLA picks).
  * Convolution1DLayer — `nn/conf/layers/Convolution1DLayer.java`
  * SubsamplingLayer (+1D) — `nn/conf/layers/SubsamplingLayer.java`,
    `nn/layers/convolution/subsampling/SubsamplingLayer.java`,
    `CudnnSubsamplingHelper.java` → `lax.reduce_window`.
  * ZeroPaddingLayer — `nn/conf/layers/ZeroPaddingLayer.java`
  * ConvolutionMode — `nn/conf/ConvolutionMode.java` (Strict/Truncate/Same)

Data layout is NHWC ([batch, height, width, channels]) vs the reference's
NCHW — the TPU-preferred layout.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..conf.base import LayerConf, register_layer
from ..conf.input_type import InputType

__all__ = [
    "ConvolutionMode", "PoolingType", "ConvolutionLayer", "Convolution1DLayer",
    "SubsamplingLayer", "Subsampling1DLayer", "ZeroPaddingLayer",
]


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _conv_stored(x, w, strides, padding, rhs_dilation, store_dtype):
    """conv_general_dilated whose saved-for-backward input is stored in
    `store_dtype` (e.g. float8_e4m3fn) instead of the compute dtype: the
    backward casts it back up and re-derives dx/dw through jax.vjp (the
    dead primal recompute is DCE'd by XLA, leaving only the transposed
    convs). Halves the conv-input residual HBM write+read for bf16
    compute at reduced weight-gradient precision."""
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        rhs_dilation=rhs_dilation,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _conv_stored_fwd(x, w, strides, padding, rhs_dilation, store_dtype):
    y = _conv_stored(x, w, strides, padding, rhs_dilation, store_dtype)
    return y, (x.astype(jnp.dtype(store_dtype)), w)


def _conv_stored_bwd(strides, padding, rhs_dilation, store_dtype, res, g):
    x_s, w = res
    x = x_s.astype(w.dtype)
    _, vjp = jax.vjp(
        lambda x_, w_: lax.conv_general_dilated(
            x_, w_, window_strides=strides, padding=padding,
            rhs_dilation=rhs_dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC")), x, w)
    return vjp(g)


_conv_stored.defvjp(_conv_stored_fwd, _conv_stored_bwd)


class ConvolutionMode:
    STRICT = "strict"
    TRUNCATE = "truncate"
    SAME = "same"


class PoolingType:
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def conv_output_size(size: int, k: int, s: int, mode: str, dilation: int = 1) -> int:
    """Output spatial extent (reference `util/ConvolutionUtils.java`)."""
    eff_k = k + (k - 1) * (dilation - 1)
    if mode == ConvolutionMode.SAME:
        return int(math.ceil(size / s))
    if mode == ConvolutionMode.STRICT:
        if (size - eff_k) % s != 0:
            raise ValueError(
                f"ConvolutionMode.STRICT: (size={size} - kernel={eff_k}) not "
                f"divisible by stride={s}. Use TRUNCATE or SAME.")
        return (size - eff_k) // s + 1
    # TRUNCATE
    return (size - eff_k) // s + 1


def _xla_padding(mode: str):
    return "SAME" if mode == ConvolutionMode.SAME else "VALID"


@register_layer
@dataclass
class ConvolutionLayer(LayerConf):
    """2-D convolution, NHWC. W: [kh, kw, c_in, n_out]."""

    input_kind = "cnn"

    n_in: Optional[int] = None          # input channels (inferred)
    n_out: int = 0                      # filters
    kernel_size: Sequence[int] = (5, 5)
    stride: Sequence[int] = (1, 1)
    padding: Sequence[int] = (0, 0)     # explicit padding (used when mode != SAME)
    dilation: Sequence[int] = (1, 1)
    convolution_mode: str = ConvolutionMode.TRUNCATE
    has_bias: bool = True

    def fill_from_input_type(self, it: InputType):
        if it.kind == "cnn" and not self.n_in:
            return {"n_in": it.channels}
        return {}

    def n_in_from(self, it: InputType) -> int:
        return it.channels if it.kind == "cnn" else it.flat_size()

    def _dims(self):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        dh, dw = _pair(self.dilation)
        return kh, kw, sh, sw, ph, pw, dh, dw

    def output_type(self, it: InputType) -> InputType:
        kh, kw, sh, sw, ph, pw, dh, dw = self._dims()
        if self.convolution_mode == ConvolutionMode.SAME:
            oh = conv_output_size(it.height, kh, sh, ConvolutionMode.SAME, dh)
            ow = conv_output_size(it.width, kw, sw, ConvolutionMode.SAME, dw)
        else:
            oh = conv_output_size(it.height + 2 * ph, kh, sh,
                                  self.convolution_mode, dh)
            ow = conv_output_size(it.width + 2 * pw, kw, sw,
                                  self.convolution_mode, dw)
        return InputType.convolutional(oh, ow, self.n_out)

    @property
    def has_params(self) -> bool:
        return True

    def init_params(self, rng, it: InputType):
        kh, kw, *_ = self._dims()
        c_in = self.n_in or it.channels
        fan_in = kh * kw * c_in
        fan_out = kh * kw * self.n_out
        p = {"W": self._winit(rng, (kh, kw, c_in, self.n_out),
                              fan_in=fan_in, fan_out=fan_out)}
        if self.has_bias:
            p["b"] = self._binit((self.n_out,))
        return p

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        kh, kw, sh, sw, ph, pw, dh, dw = self._dims()
        if self.convolution_mode == ConvolutionMode.SAME:
            padding = "SAME"
        else:
            padding = ((ph, ph), (pw, pw))
        # lax.conv requires equal dtypes; follow numpy promotion (matches the
        # implicit promotion dense layers get from jnp.dot)
        ct = jnp.result_type(x.dtype, params["W"].dtype)
        sdt = self.activation_store_dtype
        if (train and sdt is not None
                and jnp.dtype(sdt).itemsize < jnp.dtype(ct).itemsize):
            # compact saved-activation storage: backward reads the conv
            # input in `sdt` instead of `ct` (HBM traffic/precision trade)
            z = _conv_stored(x.astype(ct), params["W"].astype(ct),
                             (sh, sw), padding, (dh, dw), str(sdt))
        else:
            z = lax.conv_general_dilated(
                x.astype(ct), params["W"].astype(ct),
                window_strides=(sh, sw),
                padding=padding, rhs_dilation=(dh, dw),
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            z = z + params["b"]
        return self._act(z), state


@register_layer
@dataclass
class Convolution1DLayer(LayerConf):
    """1-D convolution over time: input [B, T, F] (reference
    `nn/conf/layers/Convolution1DLayer.java`; layout [B,F,T] there)."""

    input_kind = "rnn"

    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    convolution_mode: str = ConvolutionMode.SAME
    has_bias: bool = True

    def n_in_from(self, it: InputType) -> int:
        return it.size

    def output_type(self, it: InputType) -> InputType:
        t = it.timesteps
        if t is not None:
            if self.convolution_mode == ConvolutionMode.SAME:
                t = conv_output_size(t, self.kernel_size, self.stride,
                                     ConvolutionMode.SAME, self.dilation)
            else:
                t = conv_output_size(t + 2 * self.padding, self.kernel_size,
                                     self.stride, self.convolution_mode,
                                     self.dilation)
        return InputType.recurrent(self.n_out, t)

    @property
    def has_params(self) -> bool:
        return True

    def init_params(self, rng, it: InputType):
        c_in = self.n_in or it.size
        k = self.kernel_size
        p = {"W": self._winit(rng, (k, c_in, self.n_out),
                              fan_in=k * c_in, fan_out=k * self.n_out)}
        if self.has_bias:
            p["b"] = self._binit((self.n_out,))
        return p

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        if self.convolution_mode == ConvolutionMode.SAME:
            padding = "SAME"
        else:
            padding = ((self.padding, self.padding),)
        ct = jnp.result_type(x.dtype, params["W"].dtype)
        z = lax.conv_general_dilated(
            x.astype(ct), params["W"].astype(ct),
            window_strides=(self.stride,), padding=padding,
            rhs_dilation=(self.dilation,),
            dimension_numbers=("NHC", "HIO", "NHC"))
        if self.has_bias:
            z = z + params["b"]
        return self._act(z), state


@register_layer
@dataclass
class SubsamplingLayer(LayerConf):
    """2-D pooling (max/avg/sum/pnorm), NHWC."""

    input_kind = "cnn"

    pooling_type: str = PoolingType.MAX
    kernel_size: Sequence[int] = (2, 2)
    stride: Sequence[int] = (2, 2)
    padding: Sequence[int] = (0, 0)
    convolution_mode: str = ConvolutionMode.TRUNCATE
    pnorm: int = 2
    eps: float = 1e-8

    def output_type(self, it: InputType) -> InputType:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        if self.convolution_mode == ConvolutionMode.SAME:
            oh = conv_output_size(it.height, kh, sh, ConvolutionMode.SAME)
            ow = conv_output_size(it.width, kw, sw, ConvolutionMode.SAME)
        else:
            oh = conv_output_size(it.height + 2 * ph, kh, sh, self.convolution_mode)
            ow = conv_output_size(it.width + 2 * pw, kw, sw, self.convolution_mode)
        return InputType.convolutional(oh, ow, it.channels)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        if self.convolution_mode == ConvolutionMode.SAME:
            pads = "SAME"
        else:
            pads = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        return _pool(x, self.pooling_type, window, strides, pads,
                     self.pnorm, self.eps), state


@register_layer
@dataclass
class Subsampling1DLayer(LayerConf):
    """1-D pooling over time: [B, T, F]."""

    input_kind = "rnn"

    pooling_type: str = PoolingType.MAX
    kernel_size: int = 2
    stride: int = 2
    padding: int = 0
    convolution_mode: str = ConvolutionMode.TRUNCATE
    pnorm: int = 2
    eps: float = 1e-8

    def output_type(self, it: InputType) -> InputType:
        t = it.timesteps
        if t is not None:
            if self.convolution_mode == ConvolutionMode.SAME:
                t = conv_output_size(t, self.kernel_size, self.stride,
                                     ConvolutionMode.SAME)
            else:
                t = conv_output_size(t + 2 * self.padding, self.kernel_size,
                                     self.stride, self.convolution_mode)
        return InputType.recurrent(it.size, t)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if self.convolution_mode == ConvolutionMode.SAME:
            pads = "SAME"
        else:
            pads = ((0, 0), (self.padding, self.padding), (0, 0))
        return _pool(x, self.pooling_type, (1, self.kernel_size, 1),
                     (1, self.stride, 1), pads, self.pnorm, self.eps), state


def _pool(x, pooling_type, window, strides, pads, pnorm, eps):
    if pads == "SAME":
        padding = "SAME"
    else:
        padding = pads
    if pooling_type == PoolingType.MAX:
        return lax.reduce_window(x, -jnp.inf, lax.max, window, strides, padding)
    if pooling_type == PoolingType.SUM:
        return lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
    if pooling_type == PoolingType.AVG:
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return s / counts
    if pooling_type == PoolingType.PNORM:
        p = float(pnorm)
        s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides,
                              padding)
        return (s + eps) ** (1.0 / p)
    raise ValueError(f"Unknown pooling type '{pooling_type}'")


@register_layer
@dataclass
class ZeroPaddingLayer(LayerConf):
    """Zero-pads H/W (reference `nn/conf/layers/ZeroPaddingLayer.java`).
    pad = (top, bottom, left, right) or (h, w)."""

    input_kind = "cnn"

    pad: Sequence[int] = (1, 1)

    def _pads(self):
        p = tuple(int(v) for v in self.pad)
        if len(p) == 2:
            return (p[0], p[0], p[1], p[1])
        if len(p) == 4:
            return p
        raise ValueError("pad must be (h,w) or (top,bottom,left,right)")

    def output_type(self, it: InputType) -> InputType:
        t, b, l, r = self._pads()
        return InputType.convolutional(it.height + t + b, it.width + l + r,
                                       it.channels)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        t, b, l, r = self._pads()
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state
