"""Normalization layers: BatchNormalization, LocalResponseNormalization.

Reference parity:
  * BatchNormalization — `nn/conf/layers/BatchNormalization.java` +
    `nn/layers/normalization/BatchNormalization.java:38` and the cuDNN helper
    `CudnnBatchNormalizationHelper.java`. The layer probes an accelerated
    helper chain at apply time, exactly like the reference's
    `BatchNormalization.initializeHelper` probes for the cuDNN impl:
      1. Pallas fused BN+ReLU kernel (`kernels/bn_relu.py`) for [N, C]
         batches that fit VMEM (the FF/MLP case);
      2. the XLA-epilogue fused formulation (`kernels/batchnorm.py`) for
         sub-f32 training on any shape: one-pass stats fused into the
         producing conv, custom_vjp backward with ReLU-mask recompute;
      3. plain two-pass jnp math (numerically exact, Sterbenz-safe) — the
         fallback, and always the path for f32/f64 (gradient checks).
    Running mean/var live in layer *state* (the reference stores them as
    non-updated params).
  * LocalResponseNormalization — `nn/conf/layers/LocalResponseNormalization.java`
    + `nn/layers/normalization/LocalResponseNormalization.java` and
    `CudnnLocalResponseNormalizationHelper.java`. Cross-channel as in the
    reference (NHWC: window over the last axis).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..conf.base import LayerConf, register_layer
from ..conf.input_type import InputType

__all__ = ["BatchNormalization", "LocalResponseNormalization"]


@register_layer
@dataclass
class BatchNormalization(LayerConf):
    """Works on FF [B,F] (normalizes over batch) and CNN NHWC [B,H,W,C]
    (normalizes over batch+spatial, per channel)."""

    input_kind = "any"

    n_out: Optional[int] = None     # feature/channel count (inferred)
    decay: float = 0.9              # running-average momentum
    eps: float = 1e-5
    gamma_init: float = 1.0
    beta_init: float = 0.0
    lock_gamma_beta: bool = False   # reference lockGammaBeta: fixed scale/shift

    def _nf(self, it: InputType) -> int:
        if self.n_out:
            return self.n_out
        return it.channels if it.kind == "cnn" else it.flat_size()

    def fill_from_input_type(self, it: InputType):
        return {"n_out": self._nf(it)} if not self.n_out else {}

    def output_type(self, it: InputType) -> InputType:
        return it

    @property
    def has_params(self) -> bool:
        return not self.lock_gamma_beta

    def init_params(self, rng, it: InputType):
        if self.lock_gamma_beta:
            return {}
        nf = self._nf(it)
        return {"gamma": jnp.full((nf,), self.gamma_init, jnp.float32),
                "beta": jnp.full((nf,), self.beta_init, jnp.float32)}

    def init_state(self, it: InputType):
        nf = self._nf(it)
        return {"mean": jnp.zeros((nf,), jnp.float32),
                "var": jnp.ones((nf,), jnp.float32)}

    def _helper(self, x, train):
        """Select the accelerated implementation, cuDNN-helper style.
        Returns 'pallas' | 'fused' | None (plain path)."""
        act = self.activation or "identity"
        if not train or act not in ("identity", "relu"):
            return None
        if jnp.dtype(x.dtype).itemsize >= 4:
            return None  # f32/f64: keep the exact two-pass path (gradchecks)
        if x.ndim == 2 and act == "relu" and not self.lock_gamma_beta:
            from ...kernels.bn_relu import _block_c
            if _block_c(x.shape[1], x.shape[0]) is not None:
                return "pallas"
        return "fused"

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        helper = self._helper(x, train)
        if helper is not None:
            nf = x.shape[-1]
            if self.lock_gamma_beta:
                gamma = jnp.full((nf,), self.gamma_init, jnp.float32)
                beta = jnp.full((nf,), self.beta_init, jnp.float32)
            else:
                gamma = params["gamma"].astype(jnp.float32)
                beta = params["beta"].astype(jnp.float32)
            if helper == "pallas":
                from ...kernels.bn_relu import fused_bn_relu
                y, mean, var = fused_bn_relu(x, gamma, beta, eps=self.eps)
            else:
                from ...kernels.batchnorm import fused_bn_act
                sdt = self.activation_store_dtype
                if (sdt is None or jnp.dtype(sdt).itemsize
                        >= jnp.dtype(x.dtype).itemsize):
                    sdt = ""   # exact storage (compute dtype)
                y, mean, var = fused_bn_act(x, gamma, beta, float(self.eps),
                                            self.activation or "identity",
                                            str(sdt))
            d = self.decay
            new_state = {
                "mean": d * state["mean"] + (1 - d) * lax.stop_gradient(mean),
                "var": d * state["var"] + (1 - d) * lax.stop_gradient(var)}
            return y, new_state  # activation already fused
        return self._apply_plain(params, state, x, train=train)

    def _apply_plain(self, params, state, x, *, train=False):
        axes = tuple(range(x.ndim - 1))  # all but feature/channel axis
        # Statistics accumulate in >= f32 (bf16 sums over batch*spatial lose
        # precision and running averages drift; f64 inputs keep f64 so the
        # gradient-check harness stays exact) — but the NORMALIZE step is
        # folded to per-channel scale/shift so the big tensor is touched
        # once in its own dtype: no materialized f32 copy of x, and XLA can
        # fuse y = x*scale + shift into the adjacent conv. This is the
        # fusion the reference buys from cuDNN
        # (CudnnBatchNormalizationHelper.java).
        cdt = jnp.promote_types(x.dtype, jnp.float32)
        if train:
            # two reduction passes, both with f32 accumulation and the
            # elementwise (x - mean)^2 fused into the second reduction by
            # XLA (no materialized f32 copy of x). NOT E[x^2]-E[x]^2: that
            # one-pass form cancels catastrophically for large-mean
            # channels (mean ~1e4, std ~1 -> var underflows to 0 in f32)
            mean = jnp.mean(x, axis=axes, dtype=cdt)
            var = jnp.mean(lax.square(x.astype(cdt) - mean), axis=axes)
            d = self.decay
            new_state = {"mean": d * state["mean"] + (1 - d) * mean,
                         "var": d * state["var"] + (1 - d) * var}
        else:
            mean, var = state["mean"].astype(cdt), state["var"].astype(cdt)
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        if not self.lock_gamma_beta:
            gamma = params["gamma"].astype(cdt)
            beta = params["beta"].astype(cdt)
        else:
            gamma = jnp.asarray(self.gamma_init, cdt)
            beta = jnp.asarray(self.beta_init, cdt)
        if jnp.dtype(x.dtype).itemsize < 4:
            # bf16/f16 activations: fold to y = x*scale + shift (one fused
            # elementwise pass; x's own 8-bit mantissa already bounds the
            # precision, folding loses nothing)
            scale = gamma * inv
            y = (x.astype(cdt) * scale + (beta - mean * scale)) \
                .astype(x.dtype)
        else:
            # f32/f64 activations: keep (x - mean) explicit — for
            # large-mean channels the nearby-value subtraction is exact
            # (Sterbenz) where the folded form loses ~4 decades; XLA fuses
            # this chain just as well in full precision
            y = ((x.astype(cdt) - mean) * (inv * gamma) + beta) \
                .astype(x.dtype)
        return self._act(y), new_state


@register_layer
@dataclass
class LocalResponseNormalization(LayerConf):
    """Cross-channel LRN: y = x / (k + alpha*sum_{nearby ch} x^2)^beta.
    Defaults match the reference (k=2, n=5, alpha=1e-4, beta=0.75)."""

    input_kind = "cnn"

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def output_type(self, it: InputType) -> InputType:
        return it

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        half = self.n // 2
        sq = x * x
        # sum over a window of `n` adjacent channels (last axis)
        window = (1,) * (x.ndim - 1) + (self.n,)
        strides = (1,) * x.ndim
        pads = tuple((0, 0) for _ in range(x.ndim - 1)) + ((half, half),)
        ssum = lax.reduce_window(sq, 0.0, lax.add, window, strides, pads)
        denom = (self.k + self.alpha * ssum) ** self.beta
        return x / denom, state
