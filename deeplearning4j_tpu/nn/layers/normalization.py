"""Normalization layers: BatchNormalization, LocalResponseNormalization.

Reference parity:
  * BatchNormalization — `nn/conf/layers/BatchNormalization.java` +
    `nn/layers/normalization/BatchNormalization.java:38` and the cuDNN helper
    `CudnnBatchNormalizationHelper.java`. TPU-native: plain jnp moment math —
    XLA fuses normalize+scale+shift into neighbors (the role of the fused
    cuDNN kernel). Running mean/var live in layer *state* (the reference
    stores them as non-updated params).
  * LocalResponseNormalization — `nn/conf/layers/LocalResponseNormalization.java`
    + `nn/layers/normalization/LocalResponseNormalization.java` and
    `CudnnLocalResponseNormalizationHelper.java`. Cross-channel as in the
    reference (NHWC: window over the last axis).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..conf.base import LayerConf, register_layer
from ..conf.input_type import InputType

__all__ = ["BatchNormalization", "LocalResponseNormalization"]


@register_layer
@dataclass
class BatchNormalization(LayerConf):
    """Works on FF [B,F] (normalizes over batch) and CNN NHWC [B,H,W,C]
    (normalizes over batch+spatial, per channel)."""

    input_kind = "any"

    n_out: Optional[int] = None     # feature/channel count (inferred)
    decay: float = 0.9              # running-average momentum
    eps: float = 1e-5
    gamma_init: float = 1.0
    beta_init: float = 0.0
    lock_gamma_beta: bool = False   # reference lockGammaBeta: fixed scale/shift

    def _nf(self, it: InputType) -> int:
        if self.n_out:
            return self.n_out
        return it.channels if it.kind == "cnn" else it.flat_size()

    def fill_from_input_type(self, it: InputType):
        return {"n_out": self._nf(it)} if not self.n_out else {}

    def output_type(self, it: InputType) -> InputType:
        return it

    @property
    def has_params(self) -> bool:
        return not self.lock_gamma_beta

    def init_params(self, rng, it: InputType):
        if self.lock_gamma_beta:
            return {}
        nf = self._nf(it)
        return {"gamma": jnp.full((nf,), self.gamma_init, jnp.float32),
                "beta": jnp.full((nf,), self.beta_init, jnp.float32)}

    def init_state(self, it: InputType):
        nf = self._nf(it)
        return {"mean": jnp.zeros((nf,), jnp.float32),
                "var": jnp.ones((nf,), jnp.float32)}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        axes = tuple(range(x.ndim - 1))  # all but feature/channel axis
        # Statistics accumulate in >= f32 (bf16 sums over batch*spatial lose
        # precision and running averages drift; f64 inputs keep f64 so the
        # gradient-check harness stays exact) — but the NORMALIZE step is
        # folded to per-channel scale/shift so the big tensor is touched
        # once in its own dtype: no materialized f32 copy of x, and XLA can
        # fuse y = x*scale + shift into the adjacent conv. This is the
        # fusion the reference buys from cuDNN
        # (CudnnBatchNormalizationHelper.java).
        cdt = jnp.promote_types(x.dtype, jnp.float32)
        if train:
            # two reduction passes, both with f32 accumulation and the
            # elementwise (x - mean)^2 fused into the second reduction by
            # XLA (no materialized f32 copy of x). NOT E[x^2]-E[x]^2: that
            # one-pass form cancels catastrophically for large-mean
            # channels (mean ~1e4, std ~1 -> var underflows to 0 in f32)
            mean = jnp.mean(x, axis=axes, dtype=cdt)
            var = jnp.mean(lax.square(x.astype(cdt) - mean), axis=axes)
            d = self.decay
            new_state = {"mean": d * state["mean"] + (1 - d) * mean,
                         "var": d * state["var"] + (1 - d) * var}
        else:
            mean, var = state["mean"].astype(cdt), state["var"].astype(cdt)
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        if not self.lock_gamma_beta:
            gamma = params["gamma"].astype(cdt)
            beta = params["beta"].astype(cdt)
        else:
            gamma = jnp.asarray(self.gamma_init, cdt)
            beta = jnp.asarray(self.beta_init, cdt)
        if jnp.dtype(x.dtype).itemsize < 4:
            # bf16/f16 activations: fold to y = x*scale + shift (one fused
            # elementwise pass; x's own 8-bit mantissa already bounds the
            # precision, folding loses nothing)
            scale = gamma * inv
            y = (x.astype(cdt) * scale + (beta - mean * scale)) \
                .astype(x.dtype)
        else:
            # f32/f64 activations: keep (x - mean) explicit — for
            # large-mean channels the nearby-value subtraction is exact
            # (Sterbenz) where the folded form loses ~4 decades; XLA fuses
            # this chain just as well in full precision
            y = ((x.astype(cdt) - mean) * (inv * gamma) + beta) \
                .astype(x.dtype)
        return self._act(y), new_state


@register_layer
@dataclass
class LocalResponseNormalization(LayerConf):
    """Cross-channel LRN: y = x / (k + alpha*sum_{nearby ch} x^2)^beta.
    Defaults match the reference (k=2, n=5, alpha=1e-4, beta=0.75)."""

    input_kind = "cnn"

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def output_type(self, it: InputType) -> InputType:
        return it

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        half = self.n // 2
        sq = x * x
        # sum over a window of `n` adjacent channels (last axis)
        window = (1,) * (x.ndim - 1) + (self.n,)
        strides = (1,) * x.ndim
        pads = tuple((0, 0) for _ in range(x.ndim - 1)) + ((half, half),)
        ssum = lax.reduce_window(sq, 0.0, lax.add, window, strides, pads)
        denom = (self.k + self.alpha * ssum) ** self.beta
        return x / denom, state
