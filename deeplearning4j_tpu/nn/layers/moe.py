"""Mixture-of-Experts layer + expert parallelism.

NEW capability relative to the reference (SURVEY.md §2.4 parallelism table:
"Expert parallelism / MoE — NO"), completing the parallelism alphabet
(dp/tp/pp/sp/ep). TPU-first design choices:

  * DENSE expert compute: every expert processes every token and the
    router's top-k gates (zeros elsewhere) combine them. For the moderate
    expert counts this layer targets, batched [E, ...] einsums keep the
    MXU busy with static shapes — no gather/scatter token dispatch, no
    capacity-overflow dropping, and `jax.grad` differentiates the gates
    exactly.
  * Expert parallelism is a SHARDING RULE, not a runtime: expert-indexed
    params ([E, ...], keys prefixed `expert_`) shard on their leading axis
    (`parallel/sharding.py`); XLA partitions the expert einsums and
    inserts the psum that combines expert contributions over ICI.
  * Router load-balance auxiliary loss (Shazeer/Switch style
    E * sum_e f_e * p_e) is returned via the state side-channel and added
    to the training score by `aux_score` — set `load_balance_coef` > 0.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..conf.base import LayerConf, register_layer
from ..conf.input_type import InputType

__all__ = ["MixtureOfExpertsLayer"]


@register_layer
@dataclass
class MixtureOfExpertsLayer(LayerConf):
    """Top-k routed mixture of two-layer FFN experts.

    x [B, n_in] -> router logits [B, E] -> top-k softmax gates ->
    y = sum_k gate_k * FFN_k(x), FFN_e = W2_e @ act(W1_e @ x + b1_e) + b2_e.
    """

    input_kind = "ff"

    n_out: int = 0
    n_experts: int = 4
    top_k: int = 2
    expert_hidden: int = 0          # default: 4 * n_out
    load_balance_coef: float = 0.0  # aux loss weight (0 = off)
    router_noise: float = 0.0       # train-time routing noise stddev

    def output_type(self, it: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    @property
    def has_params(self) -> bool:
        return True

    def _hidden(self) -> int:
        return self.expert_hidden or 4 * self.n_out

    def init_params(self, rng, it: InputType) -> Dict[str, jax.Array]:
        n_in = it.flat_size()
        h = self._hidden()
        e = self.n_experts
        k1, k2, k3 = jax.random.split(rng, 3)
        return {
            "router_W": self._winit(k1, (n_in, e), n_in, e),
            # expert_-prefixed tensors shard on axis 0 (expert parallelism)
            "expert_W1": self._winit(k2, (e, n_in, h), n_in, h),
            "expert_b1": self._binit((e, h)),
            "expert_W2": self._winit(k3, (e, h, self.n_out), h, self.n_out),
            "expert_b2": self._binit((e, self.n_out)),
        }

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if rng is not None:
            rng, noise_rng = jax.random.split(rng)
        else:
            noise_rng = None
        x = self.maybe_dropout_input(x, train, rng)
        logits = x @ params["router_W"]                      # [B, E]
        if train and self.router_noise > 0 and noise_rng is not None:
            logits = logits + self.router_noise * jax.random.normal(
                noise_rng, logits.shape, logits.dtype)
        k = min(self.top_k, self.n_experts)
        # exact top-k via index scatter (a value threshold would admit ALL
        # tied experts, degrading to dense routing on e.g. zero inputs)
        top_vals, top_idx = jax.lax.top_k(logits, k)         # [B, k]
        top_gates = jax.nn.softmax(top_vals, axis=-1)
        gates = jnp.zeros_like(logits).at[
            jnp.arange(logits.shape[0])[:, None], top_idx].set(top_gates)
        # dense expert compute: [B, E, h] -> [B, E, out]
        hid = self._act(jnp.einsum("bi,eih->beh", x, params["expert_W1"])
                        + params["expert_b1"])
        outs = (jnp.einsum("beh,eho->beo", hid, params["expert_W2"])
                + params["expert_b2"])
        y = jnp.einsum("beo,be->bo", outs, gates.astype(outs.dtype))
        if train and self.load_balance_coef > 0:
            # Switch-style aux: E * sum_e (fraction routed to e) * (mean
            # router prob of e); stored in state for aux_score
            probs = jax.nn.softmax(logits, axis=-1)
            frac = jnp.mean((gates > 0).astype(jnp.float32), axis=0)
            aux = self.n_experts * jnp.sum(
                frac * jnp.mean(probs, axis=0).astype(jnp.float32))
            state = dict(state)
            state["aux_loss"] = aux
        return y, state

    def init_state(self, it: InputType) -> Dict[str, jax.Array]:
        return ({"aux_loss": jnp.float32(0.0)}
                if self.load_balance_coef > 0 else {})

    def aux_score(self, state) -> jax.Array:
        if self.load_balance_coef > 0 and "aux_loss" in state:
            return self.load_balance_coef * state["aux_loss"]
        return jnp.float32(0.0)
