"""Generative / pretraining layers: AutoEncoder, RBM, VariationalAutoencoder,
CenterLossOutputLayer.

Reference parity:
  * AutoEncoder — `nn/conf/layers/AutoEncoder.java` +
    `nn/layers/feedforward/autoencoder/AutoEncoder.java`: denoising AE with
    corruption, W/hidden-bias/visible-bias params, decode via W^T.
  * RBM — `nn/conf/layers/RBM.java` + `nn/layers/feedforward/rbm/RBM.java`:
    contrastive divergence (CD-k) pretraining; BINARY/GAUSSIAN visible and
    hidden units. CD gradients are computed directly (positive phase minus
    negative phase) — not via jax.grad — matching the reference's algorithm.
  * VariationalAutoencoder — `nn/conf/layers/variational/` +
    `nn/layers/variational/VariationalAutoencoder.java:48`: encoder/decoder
    MLPs, reparameterization, reconstruction distributions (Gaussian,
    Bernoulli, Composite, LossFunctionWrapper), -ELBO pretrain loss.
  * CenterLossOutputLayer — `nn/conf/layers/CenterLossOutputLayer.java` +
    `nn/layers/training/CenterLossOutputLayer.java`: softmax CE +
    lambda/2*||features - center_{y}||^2. Deviation: centers are trained by
    gradient descent on the center term scaled by `alpha` (the reference uses
    an exponential-moving-average center update); same fixed point.

Pretraining protocol (consumed by `MultiLayerNetwork.pretrain`):
    layer.is_pretrainable -> bool
    layer.pretrain_value_and_grad(params, x, rng) -> (score, grads_dict)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import activations as _activations
from .. import losses as _losses
from ..conf.base import LayerConf, register_layer, register_aux_dataclass
from ..conf.input_type import InputType
from .feedforward import BaseOutputLayerConf

__all__ = [
    "AutoEncoder", "RBM", "VariationalAutoencoder", "CenterLossOutputLayer",
    "GaussianReconstructionDistribution", "BernoulliReconstructionDistribution",
    "CompositeReconstructionDistribution", "LossFunctionWrapper",
]


# ---------------------------------------------------------------------------
# AutoEncoder
# ---------------------------------------------------------------------------

@register_layer
@dataclass
class AutoEncoder(LayerConf):
    n_in: Optional[int] = None
    n_out: int = 0
    corruption_level: float = 0.3
    sparsity: float = 0.0
    pretrain_loss: str = "mse"

    def __post_init__(self):
        if self.activation is None:
            self.activation = "sigmoid"

    def output_type(self, it: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    @property
    def has_params(self) -> bool:
        return True

    @property
    def is_pretrainable(self) -> bool:
        return True

    def init_params(self, rng, it: InputType):
        n_in = self.n_in or it.flat_size()
        return {"W": self._winit(rng, (n_in, self.n_out),
                                 fan_in=n_in, fan_out=self.n_out),
                "b": self._binit((self.n_out,)),
                "vb": self._binit((n_in,))}

    def encode(self, params, x):
        return self._act(x @ params["W"] + params["b"])

    def decode(self, params, h):
        return self._act(h @ params["W"].T + params["vb"])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        return self.encode(params, x), state

    def pretrain_value_and_grad(self, params, x, rng):
        def loss(p):
            xin = x
            if self.corruption_level > 0 and rng is not None:
                keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level,
                                            x.shape)
                xin = jnp.where(keep, x, 0.0)
            h = self.encode(p, xin)
            recon = self.decode(p, h)
            l = _losses.get(self.pretrain_loss).score(x, recon,
                                                      activation="identity")
            if self.sparsity > 0:
                rho_hat = jnp.clip(jnp.mean(h, axis=0), 1e-6, 1 - 1e-6)
                rho = self.sparsity
                l = l + jnp.sum(rho * jnp.log(rho / rho_hat)
                                + (1 - rho) * jnp.log((1 - rho) / (1 - rho_hat)))
            return l
        return jax.value_and_grad(loss)(params)


# ---------------------------------------------------------------------------
# RBM
# ---------------------------------------------------------------------------

@register_layer
@dataclass
class RBM(LayerConf):
    """Restricted Boltzmann Machine with CD-k pretraining."""

    n_in: Optional[int] = None
    n_out: int = 0
    hidden_unit: str = "binary"    # binary | rectified | gaussian
    visible_unit: str = "binary"   # binary | gaussian
    k: int = 1                     # CD-k gibbs steps

    def __post_init__(self):
        if self.activation is None:
            self.activation = "sigmoid"

    def output_type(self, it: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    @property
    def has_params(self) -> bool:
        return True

    @property
    def is_pretrainable(self) -> bool:
        return True

    def init_params(self, rng, it: InputType):
        n_in = self.n_in or it.flat_size()
        return {"W": self._winit(rng, (n_in, self.n_out),
                                 fan_in=n_in, fan_out=self.n_out),
                "b": self._binit((self.n_out,)),      # hidden bias
                "vb": self._binit((n_in,))}           # visible bias

    def _prop_up(self, params, v):
        pre = v @ params["W"] + params["b"]
        if self.hidden_unit == "rectified":
            return jax.nn.relu(pre)
        return jax.nn.sigmoid(pre)

    def _prop_down(self, params, h):
        pre = h @ params["W"].T + params["vb"]
        if self.visible_unit == "gaussian":
            return pre
        return jax.nn.sigmoid(pre)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        return self._prop_up(params, x), state

    def pretrain_value_and_grad(self, params, x, rng):
        """CD-k: grads = -(positive phase - negative phase) (descent form).
        Score reported is the reconstruction MSE (the reference reports
        reconstruction error for RBMs as well)."""
        if rng is None:
            rng = jax.random.PRNGKey(0)
        batch = x.shape[0]
        h_prob = self._prop_up(params, x)
        rngs = jax.random.split(rng, self.k + 1)
        h_sample = (jax.random.bernoulli(rngs[0], h_prob)
                    .astype(x.dtype) if self.hidden_unit == "binary" else h_prob)
        v_neg = x
        h_neg = h_sample
        for i in range(self.k):
            v_neg = self._prop_down(params, h_neg)
            if self.visible_unit == "binary":
                v_neg = jax.random.bernoulli(rngs[i + 1], v_neg).astype(x.dtype)
            h_neg = self._prop_up(params, v_neg)
        pos_W = x.T @ h_prob
        neg_W = v_neg.T @ h_neg
        grads = {
            "W": -(pos_W - neg_W) / batch,
            "b": -jnp.mean(h_prob - h_neg, axis=0),
            "vb": -jnp.mean(x - v_neg, axis=0),
        }
        recon = self._prop_down(params, h_prob)
        score = jnp.mean(jnp.sum((x - recon) ** 2, axis=-1))
        return score, grads


# ---------------------------------------------------------------------------
# VAE reconstruction distributions
# ---------------------------------------------------------------------------

@register_aux_dataclass
@dataclass
class GaussianReconstructionDistribution:
    """p(x|z) = N(mean, sigma^2); dist params per feature: [mean, log(sigma^2)]
    (reference `GaussianReconstructionDistribution.java`)."""

    activation: str = "identity"

    params_per_feature = 2

    def log_prob(self, x, dist_params):
        n = x.shape[-1]
        mean = _activations.get(self.activation)(dist_params[..., :n])
        log_var = dist_params[..., n:]
        var = jnp.exp(log_var)
        return jnp.sum(-0.5 * (jnp.log(2 * jnp.pi) + log_var
                               + (x - mean) ** 2 / var), axis=-1)

    def sample_mean(self, dist_params, n):
        return _activations.get(self.activation)(dist_params[..., :n])


@register_aux_dataclass
@dataclass
class BernoulliReconstructionDistribution:
    """p(x|z) = Bernoulli(sigmoid(logits)) (reference
    `BernoulliReconstructionDistribution.java`)."""

    activation: str = "sigmoid"

    params_per_feature = 1

    def log_prob(self, x, dist_params):
        logits = dist_params
        return jnp.sum(x * jax.nn.log_sigmoid(logits)
                       + (1 - x) * jax.nn.log_sigmoid(-logits), axis=-1)

    def sample_mean(self, dist_params, n):
        return jax.nn.sigmoid(dist_params)


@register_aux_dataclass
@dataclass
class CompositeReconstructionDistribution:
    """Different distributions over feature ranges (reference
    `CompositeReconstructionDistribution.java`). `parts` = list of
    (n_features, distribution)."""

    sizes: Sequence[int] = ()
    dists: Sequence[object] = ()

    @property
    def params_per_feature(self):
        raise AttributeError("composite: use total_params")

    def total_params(self, n_features):
        assert sum(self.sizes) == n_features
        return sum(int(s) * d.params_per_feature
                   for s, d in zip(self.sizes, self.dists))

    def log_prob(self, x, dist_params):
        lp = 0.0
        xi = 0
        pi = 0
        for s, d in zip(self.sizes, self.dists):
            np_ = s * d.params_per_feature
            lp = lp + d.log_prob(x[..., xi:xi + s], dist_params[..., pi:pi + np_])
            xi += s
            pi += np_
        return lp

    def sample_mean(self, dist_params, n):
        outs = []
        pi = 0
        for s, d in zip(self.sizes, self.dists):
            np_ = s * d.params_per_feature
            outs.append(d.sample_mean(dist_params[..., pi:pi + np_], s))
            pi += np_
        return jnp.concatenate(outs, axis=-1)


@register_aux_dataclass
@dataclass
class LossFunctionWrapper:
    """Use a plain loss as the reconstruction term (reference
    `LossFunctionWrapper.java`)."""

    loss: str = "mse"
    activation: str = "identity"

    params_per_feature = 1

    def log_prob(self, x, dist_params):
        per = _losses.get(self.loss).per_example(x, dist_params,
                                                 activation=self.activation)
        return -per

    def sample_mean(self, dist_params, n):
        return _activations.get(self.activation)(dist_params)


# ---------------------------------------------------------------------------
# Variational Autoencoder
# ---------------------------------------------------------------------------

@register_layer
@dataclass
class VariationalAutoencoder(LayerConf):
    """VAE pretrain layer. In a supervised net, `apply` outputs the latent
    mean (the reference's activate() does the same)."""

    n_in: Optional[int] = None
    n_out: int = 0                       # latent size n_z
    encoder_layer_sizes: Sequence[int] = (100,)
    decoder_layer_sizes: Sequence[int] = (100,)
    reconstruction_distribution: object = field(
        default_factory=BernoulliReconstructionDistribution)
    pzx_activation: str = "identity"
    num_samples: int = 1

    def __post_init__(self):
        if self.activation is None:
            self.activation = "tanh"   # hidden-layer activation

    def output_type(self, it: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    @property
    def has_params(self) -> bool:
        return True

    @property
    def is_pretrainable(self) -> bool:
        return True

    def _recon_params_count(self, n_in):
        d = self.reconstruction_distribution
        if isinstance(d, CompositeReconstructionDistribution):
            return d.total_params(n_in)
        return n_in * d.params_per_feature

    def init_params(self, rng, it: InputType):
        n_in = self.n_in or it.flat_size()
        sizes_e = [n_in] + list(self.encoder_layer_sizes)
        sizes_d = [self.n_out] + list(self.decoder_layer_sizes)
        n_recon = self._recon_params_count(n_in)
        keys = jax.random.split(rng, len(sizes_e) + len(sizes_d) + 2)
        p = {}
        for i in range(len(sizes_e) - 1):
            p[f"eW{i}"] = self._winit(keys[i], (sizes_e[i], sizes_e[i + 1]),
                                      fan_in=sizes_e[i], fan_out=sizes_e[i + 1])
            p[f"eb{i}"] = self._binit((sizes_e[i + 1],))
        he = sizes_e[-1]
        k = keys[len(sizes_e) - 1]
        k1, k2 = jax.random.split(k)
        p["zW"] = self._winit(k1, (he, 2 * self.n_out), fan_in=he,
                              fan_out=2 * self.n_out)
        p["zb"] = self._binit((2 * self.n_out,))
        for i in range(len(sizes_d) - 1):
            kk = keys[len(sizes_e) + i]
            p[f"dW{i}"] = self._winit(kk, (sizes_d[i], sizes_d[i + 1]),
                                      fan_in=sizes_d[i], fan_out=sizes_d[i + 1])
            p[f"db{i}"] = self._binit((sizes_d[i + 1],))
        hd = sizes_d[-1]
        p["xW"] = self._winit(keys[-1], (hd, n_recon), fan_in=hd,
                              fan_out=n_recon)
        p["xb"] = self._binit((n_recon,))
        return p

    def _encode(self, params, x):
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = self._act(h @ params[f"eW{i}"] + params[f"eb{i}"])
        z2 = h @ params["zW"] + params["zb"]
        mean, log_var = jnp.split(z2, 2, axis=-1)
        mean = _activations.get(self.pzx_activation)(mean)
        return mean, log_var

    def _decode(self, params, z):
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = self._act(h @ params[f"dW{i}"] + params[f"db{i}"])
        return h @ params["xW"] + params["xb"]

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        mean, _ = self._encode(params, x)
        return mean, state

    def pretrain_value_and_grad(self, params, x, rng):
        if rng is None:
            rng = jax.random.PRNGKey(0)

        def loss(p):
            mean, log_var = self._encode(p, x)
            kl = -0.5 * jnp.sum(1 + log_var - mean ** 2 - jnp.exp(log_var),
                                axis=-1)
            rec = 0.0
            keys = jax.random.split(rng, self.num_samples)
            for s in range(self.num_samples):
                eps = jax.random.normal(keys[s], mean.shape, mean.dtype)
                z = mean + jnp.exp(0.5 * log_var) * eps
                dist_params = self._decode(p, z)
                rec = rec + self.reconstruction_distribution.log_prob(
                    x, dist_params)
            rec = rec / self.num_samples
            return jnp.mean(kl - rec)   # -ELBO
        return jax.value_and_grad(loss)(params)

    def reconstruction_probability(self, params, x, rng, num_samples=5):
        """Reference `reconstructionProbability` — importance-sampled estimate
        used for anomaly detection."""
        mean, log_var = self._encode(params, x)
        keys = jax.random.split(rng, num_samples)
        lps = []
        for s in range(num_samples):
            eps = jax.random.normal(keys[s], mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * log_var) * eps
            dist_params = self._decode(params, z)
            lps.append(self.reconstruction_distribution.log_prob(x, dist_params))
        return jax.scipy.special.logsumexp(jnp.stack(lps), axis=0) - jnp.log(
            float(num_samples))

    def generate_at_mean_given_z(self, params, z):
        n = self.n_in
        return self.reconstruction_distribution.sample_mean(
            self._decode(params, z), n)


# ---------------------------------------------------------------------------
# Center loss
# ---------------------------------------------------------------------------

@register_layer
@dataclass
class CenterLossOutputLayer(BaseOutputLayerConf):
    n_in: Optional[int] = None
    n_out: int = 0
    has_bias: bool = True
    alpha: float = 0.05       # center learning-rate scaling
    lambda_: float = 2e-4     # center-loss weight

    def __post_init__(self):
        if self.activation is None:
            self.activation = "softmax"

    def output_type(self, it: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    @property
    def has_params(self) -> bool:
        return True

    def init_params(self, rng, it: InputType):
        n_in = self.n_in or it.flat_size()
        p = {"W": self._winit(rng, (n_in, self.n_out),
                              fan_in=n_in, fan_out=self.n_out),
             "centers": jnp.zeros((self.n_out, n_in), jnp.float32)}
        if self.has_bias:
            p["b"] = self._binit((self.n_out,))
        return p

    def preout(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return z

    def loss_score(self, params, state, x, labels, *, train=False, rng=None,
                   mask=None):
        base = super().loss_score(params, state, x, labels, train=train,
                                  rng=rng, mask=mask)
        # center term: lambda/2 * ||x - c_y||^2 ; alpha scales the centers'
        # effective learning rate (gradient-descent analog of the reference's
        # EMA center update)
        y_idx = jnp.argmax(labels, axis=-1)
        c_y = params["centers"][y_idx]
        # Two stop-gradient halves so the features see the full center term
        # while the centers' gradient is scaled by alpha (their separate
        # learning rate in the reference).
        diff_for_features = x - jax.lax.stop_gradient(c_y)
        diff_for_centers = jax.lax.stop_gradient(x) - c_y
        center_term = 0.5 * self.lambda_ * jnp.mean(
            jnp.sum(diff_for_features ** 2, axis=-1))
        # zero-valued term whose gradient w.r.t. centers is alpha-scaled
        cgrad_term = 0.5 * self.lambda_ * self.alpha * jnp.mean(
            jnp.sum(diff_for_centers ** 2, axis=-1))
        cgrad_term = cgrad_term - jax.lax.stop_gradient(cgrad_term)
        return base + center_term + cgrad_term
