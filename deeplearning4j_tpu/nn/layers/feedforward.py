"""Feed-forward layers: Dense, Output/Loss, Activation, Dropout, Embedding.

Reference parity:
  * DenseLayer — `nn/conf/layers/DenseLayer.java` + `nn/layers/feedforward/dense/DenseLayer.java`
  * OutputLayer — `nn/conf/layers/OutputLayer.java` + `nn/layers/OutputLayer.java`
  * LossLayer — `nn/conf/layers/LossLayer.java` (no params, loss only)
  * ActivationLayer — `nn/conf/layers/ActivationLayer.java`
  * DropoutLayer — `nn/conf/layers/DropoutLayer.java`
  * EmbeddingLayer — `nn/conf/layers/EmbeddingLayer.java` (+ feedforward/embedding impl)

All matmuls hit the MXU via `jnp.dot`; activations fuse in XLA. Backward is
`jax.grad` — the hand-written `backpropGradient` methods have no analog here.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from .. import losses as _losses
from ..conf.base import LayerConf, register_layer
from ..conf.input_type import InputType

__all__ = [
    "DenseLayer", "OutputLayer", "LossLayer", "ActivationLayer",
    "DropoutLayer", "EmbeddingLayer", "BaseOutputLayerConf",
]


@register_layer
@dataclass
class DenseLayer(LayerConf):
    """Fully connected layer: y = act(x @ W + b). W: [n_in, n_out]."""

    n_in: Optional[int] = None
    n_out: int = 0
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    @property
    def has_params(self) -> bool:
        return True

    def init_params(self, rng, input_type: InputType):
        n_in = self.n_in or input_type.flat_size()
        w = self._winit(rng, (n_in, self.n_out), fan_in=n_in, fan_out=self.n_out)
        p = {"W": w}
        if self.has_bias:
            p["b"] = self._binit((self.n_out,))
        return p

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return self._act(z), state


@dataclass
class BaseOutputLayerConf(LayerConf):
    """Shared machinery for loss-bearing layers (reference:
    `nn/conf/layers/BaseOutputLayer.java`). The network calls `preout` to get
    logits and `loss_score` for the (fused, stable) loss; `apply` gives
    inference-time activations."""

    loss: str = "mcxent"
    loss_weights: Optional[list] = None

    def loss_fn(self):
        return _losses.get(self.loss)

    def preout(self, params, state, x, *, train=False, rng=None, mask=None):
        return x

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        z = self.preout(params, state, x, train=train, rng=rng, mask=mask)
        return self._act(z), state

    def loss_score(self, params, state, x, labels, *, train=False, rng=None,
                   mask=None):
        """Mean per-example loss computed from logits (fused path)."""
        z = self.preout(params, state, x, train=train, rng=rng, mask=mask)
        if z.ndim == 3:
            # time-series logits [B, T, F]: flatten handled by the loss's mask path
            pass
        return self.loss_fn().score(labels, z, activation=self.activation,
                                    mask=mask, weights=self.loss_weights)

    def loss_per_example(self, params, state, x, labels, *, mask=None):
        """Unreduced per-example loss [batch] — the map half of the
        distributed scoring plane (reference
        `BaseOutputLayer.computeScoreForExamples`, BaseOutputLayer.java:117:
        masked per-element score array; time-series scores are SUMMED over
        time per example, RnnOutputLayer.java:219-233)."""
        import jax.numpy as jnp

        z = self.preout(params, state, x, train=False, rng=None, mask=mask)
        per = self.loss_fn().per_example(labels, z,
                                         activation=self.activation,
                                         weights=self.loss_weights)
        if mask is not None:
            m = mask.astype(per.dtype)
            m = jnp.broadcast_to(
                m.reshape(m.shape + (1,) * (per.ndim - m.ndim)), per.shape)
            per = per * m
        while per.ndim > 1:   # [B, T] (RNN) -> sum over time
            per = per.sum(axis=-1)
        return per


@register_layer
@dataclass
class OutputLayer(BaseOutputLayerConf):
    """Dense + loss head (reference OutputLayer extends FeedForwardLayer)."""

    n_in: Optional[int] = None
    n_out: int = 0
    has_bias: bool = True

    def __post_init__(self):
        if self.activation is None:
            self.activation = "softmax"

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    @property
    def has_params(self) -> bool:
        return True

    def init_params(self, rng, input_type: InputType):
        n_in = self.n_in or input_type.flat_size()
        p = {"W": self._winit(rng, (n_in, self.n_out), fan_in=n_in, fan_out=self.n_out)}
        if self.has_bias:
            p["b"] = self._binit((self.n_out,))
        return p

    def preout(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return z


@register_layer
@dataclass
class LossLayer(BaseOutputLayerConf):
    """Parameter-free loss head (reference `nn/conf/layers/LossLayer.java`)."""

    def __post_init__(self):
        if self.activation is None:
            self.activation = "identity"


@register_layer
@dataclass
class ActivationLayer(LayerConf):
    """Applies an activation only (reference `nn/conf/layers/ActivationLayer.java`)."""

    input_kind = "any"

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self._act(x), state


@register_layer
@dataclass
class DropoutLayer(LayerConf):
    """Standalone dropout layer (reference `nn/conf/layers/DropoutLayer.java`).
    `dropout` field = retain probability, inverted scaling at train time."""

    input_kind = "any"

    def __post_init__(self):
        if self.dropout is None:
            self.dropout = 0.5

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.maybe_dropout_input(x, train, rng), state


@register_layer
@dataclass
class EmbeddingLayer(LayerConf):
    """Index -> vector lookup (reference `nn/conf/layers/EmbeddingLayer.java`):
    input is int class indices [B] or one-hot-ish [B,1]; output [B, n_out].
    Lookup is a gather — XLA lowers to an efficient dynamic-slice; the scatter
    in the backward pass only touches used rows (sparse-gradient behavior the
    reference gets from its custom embedding backprop)."""

    n_in: int = 0   # vocab size
    n_out: int = 0
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    @property
    def has_params(self) -> bool:
        return True

    def init_params(self, rng, input_type: InputType):
        p = {"W": self._winit(rng, (self.n_in, self.n_out),
                              fan_in=self.n_in, fan_out=self.n_out)}
        if self.has_bias:
            p["b"] = self._binit((self.n_out,))
        return p

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        idx = x
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[:, 0]
        idx = idx.astype(jnp.int32)
        z = jnp.take(params["W"], idx, axis=0)
        if self.has_bias:
            z = z + params["b"]
        return self._act(z), state
