"""Layer zoo. Config+impl unified dataclasses (see `nn/conf/base.py`)."""
from .feedforward import (
    DenseLayer, OutputLayer, LossLayer, ActivationLayer, DropoutLayer,
    EmbeddingLayer, BaseOutputLayerConf,
)
from .convolution import (
    ConvolutionLayer, Convolution1DLayer, SubsamplingLayer,
    Subsampling1DLayer, ZeroPaddingLayer, ConvolutionMode, PoolingType,
)
from .normalization import BatchNormalization, LocalResponseNormalization
from .pooling import GlobalPoolingLayer
from .recurrent import (GravesLSTM, GravesBidirectionalLSTM, RnnOutputLayer,
                        BaseRecurrentLayer, LastTimeStep)
from .generative import (AutoEncoder, RBM, VariationalAutoencoder,
                         CenterLossOutputLayer,
                         GaussianReconstructionDistribution,
                         BernoulliReconstructionDistribution,
                         CompositeReconstructionDistribution,
                         LossFunctionWrapper)
from .moe import MixtureOfExpertsLayer
from .transformer import EmbeddingSequenceLayer, TransformerBlock

__all__ = [
    "DenseLayer", "OutputLayer", "LossLayer", "ActivationLayer",
    "DropoutLayer", "EmbeddingLayer", "BaseOutputLayerConf",
    "ConvolutionLayer", "Convolution1DLayer", "SubsamplingLayer",
    "Subsampling1DLayer", "ZeroPaddingLayer", "ConvolutionMode",
    "PoolingType", "BatchNormalization", "LocalResponseNormalization",
    "GlobalPoolingLayer",
    "GravesLSTM", "GravesBidirectionalLSTM", "RnnOutputLayer",
    "BaseRecurrentLayer", "LastTimeStep",
    "AutoEncoder", "RBM", "VariationalAutoencoder", "CenterLossOutputLayer",
    "GaussianReconstructionDistribution", "BernoulliReconstructionDistribution",
    "CompositeReconstructionDistribution", "LossFunctionWrapper",
    "MixtureOfExpertsLayer",
    "EmbeddingSequenceLayer", "TransformerBlock",
]
