"""Layer zoo. Config+impl unified dataclasses (see `nn/conf/base.py`)."""
from .feedforward import (
    DenseLayer, OutputLayer, LossLayer, ActivationLayer, DropoutLayer,
    EmbeddingLayer, BaseOutputLayerConf,
)

__all__ = [
    "DenseLayer", "OutputLayer", "LossLayer", "ActivationLayer",
    "DropoutLayer", "EmbeddingLayer", "BaseOutputLayerConf",
]
