"""Activation functions.

Capability parity with the reference's `IActivation` surface (ND4J activations
used throughout `deeplearning4j-nn`, selected by name in layer builders, e.g.
`nn/conf/layers/Layer.java` activation field). All functions are pure
`jnp -> jnp` maps so XLA can fuse them into adjacent matmuls/convs — the
TPU-native replacement for ND4J's per-op native kernels.

Backward passes come from `jax.grad`; no hand-written derivatives.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["get", "ACTIVATIONS"]


def _identity(x):
    return x


def _sigmoid(x):
    return jax.nn.sigmoid(x)


def _tanh(x):
    return jnp.tanh(x)


def _relu(x):
    return jax.nn.relu(x)


def _leakyrelu(x, alpha=0.01):
    return jax.nn.leaky_relu(x, negative_slope=alpha)


def _elu(x):
    return jax.nn.elu(x)


def _selu(x):
    return jax.nn.selu(x)


def _gelu(x):
    return jax.nn.gelu(x)


def _softmax(x):
    # Applied over the feature axis (last axis); DL4J applies softmax row-wise
    # on [batch, classes] activations.
    return jax.nn.softmax(x, axis=-1)


def _logsoftmax(x):
    return jax.nn.log_softmax(x, axis=-1)


def _softplus(x):
    return jax.nn.softplus(x)


def _softsign(x):
    return jax.nn.soft_sign(x)


def _hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def _hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def _cube(x):
    return x ** 3


def _rationaltanh(x):
    # Reference: ND4J ActivationRationalTanh — fast tanh approximation
    # 1.7159 * tanh_approx(2x/3) with |x| clipped rational approximation.
    a = jnp.abs(2.0 * x / 3.0)
    approx = jnp.sign(x) * (1.0 - 1.0 / (1.0 + a + a ** 2 + 1.41645 * a ** 4))
    return 1.7159 * approx


def _rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def _swish(x):
    return jax.nn.silu(x)


def _mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def _threshold_relu(x, theta=1.0):
    return jnp.where(x > theta, x, 0.0)


ACTIVATIONS = {
    "identity": _identity,
    "linear": _identity,
    "sigmoid": _sigmoid,
    "tanh": _tanh,
    "relu": _relu,
    "leakyrelu": _leakyrelu,
    "elu": _elu,
    "selu": _selu,
    "gelu": _gelu,
    "softmax": _softmax,
    "logsoftmax": _logsoftmax,
    "softplus": _softplus,
    "softsign": _softsign,
    "hardtanh": _hardtanh,
    "hardsigmoid": _hardsigmoid,
    "cube": _cube,
    "rationaltanh": _rationaltanh,
    "rectifiedtanh": _rectifiedtanh,
    "swish": _swish,
    "mish": _mish,
    "thresholdedrelu": _threshold_relu,
}


def get(name):
    """Resolve an activation by name (case-insensitive) or pass through a callable."""
    if callable(name):
        return name
    key = str(name).lower()
    if key not in ACTIVATIONS:
        raise ValueError(
            f"Unknown activation '{name}'. Available: {sorted(ACTIVATIONS)}"
        )
    return ACTIVATIONS[key]
