"""Per-layer gradient normalization/clipping.

Parity with `nn/conf/GradientNormalization.java` as applied by
`nn/updater/LayerUpdater.java` (preApply): renormalize-L2 (per layer / per
param type), elementwise clip, L2-norm clip (per layer / per param type).
Pure pytree transforms, fused into the jitted train step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .conf import GradientNormalization

__all__ = ["apply_gradient_normalization"]


def _global_l2(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(l * l) for l in leaves) + 1e-30)


def apply_gradient_normalization(mode: str, threshold: float, grads):
    """grads: one layer's param dict (pytree). Returns transformed grads."""
    if mode in (None, GradientNormalization.NONE):
        return grads
    if mode == GradientNormalization.RENORMALIZE_L2_PER_LAYER:
        norm = _global_l2(grads)
        return jax.tree_util.tree_map(lambda g: g / norm, grads)
    if mode == GradientNormalization.RENORMALIZE_L2_PER_PARAM_TYPE:
        return jax.tree_util.tree_map(
            lambda g: g / jnp.sqrt(jnp.sum(g * g) + 1e-30), grads)
    if mode == GradientNormalization.CLIP_ELEMENTWISE_ABSOLUTE_VALUE:
        t = threshold
        return jax.tree_util.tree_map(lambda g: jnp.clip(g, -t, t), grads)
    if mode == GradientNormalization.CLIP_L2_PER_LAYER:
        norm = _global_l2(grads)
        scale = jnp.minimum(1.0, threshold / norm)
        return jax.tree_util.tree_map(lambda g: g * scale, grads)
    if mode == GradientNormalization.CLIP_L2_PER_PARAM_TYPE:
        def clip(g):
            norm = jnp.sqrt(jnp.sum(g * g) + 1e-30)
            return g * jnp.minimum(1.0, threshold / norm)
        return jax.tree_util.tree_map(clip, grads)
    raise ValueError(f"Unknown gradient normalization '{mode}'")
