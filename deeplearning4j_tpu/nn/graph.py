"""ComputationGraph — the DAG model.

Capability parity with `nn/graph/ComputationGraph.java:79` (2447 LoC):
multiple inputs/outputs, vertex system, topological execution, fit on
DataSet/MultiDataSet, evaluate, rnn state. TPU-first design mirrors
MultiLayerNetwork: params/state are dicts keyed by vertex name, the whole DAG
(all vertices in topo order) traces into ONE jitted train step, backward via
`jax.grad` of the summed output losses.
"""
from __future__ import annotations

import functools
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import functools as _functools

from .conf import NeuralNetConfiguration
from .conf.base import LayerConf, cast_floating
from .conf.graph import ComputationGraphConfiguration, GraphVertex
from .gradnorm import apply_gradient_normalization
from .layers.feedforward import BaseOutputLayerConf
from ..datasets.iterators import DataSet, DataSetIterator, MultiDataSet
from ..eval.evaluation import Evaluation
from ..telemetry.compile_watch import watch_compiles
from ..telemetry.runtime import active as _tel_active, null_span as _null_span

log = logging.getLogger("deeplearning4j_tpu")

__all__ = ["ComputationGraph"]


class ComputationGraph:
    # everything a training step mutates — TrainingGuard snapshot scope
    _fault_state_attrs = ("params", "state", "updater_state", "_rng",
                          "iteration_count", "epoch_count", "_score")

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.iteration_count = 0
        self.epoch_count = 0
        self.listeners = []
        self.last_batch_size = 0
        self.params: Optional[Dict[str, Dict]] = None
        self.state: Optional[Dict[str, Dict]] = None
        self.updater_state: Optional[Dict[str, Any]] = None
        self._score = float("nan")
        self._rng = None

    # ------------------------------------------------------------------
    @property
    def layer_vertices(self) -> Dict[str, LayerConf]:
        return {k: v for k, v in self.conf.vertices.items()
                if isinstance(v, LayerConf)}

    def get_layer(self, name: str) -> LayerConf:
        return self.conf.vertices[name]

    @property
    def topological_order(self) -> List[str]:
        return self.conf.topological_order

    def init(self, seed: Optional[int] = None) -> "ComputationGraph":
        from . import activations as _acts
        for layer in self.layer_vertices.values():
            if layer.activation is not None:  # fail fast on bad names
                _acts.get(layer.activation)
        seed = self.conf.conf.seed if seed is None else seed
        self._rng = jax.random.PRNGKey(seed)
        self._rng, init_rng = jax.random.split(self._rng)
        names = sorted(self.layer_vertices)
        rngs = dict(zip(names, jax.random.split(init_rng, max(1, len(names)))))
        params, state = {}, {}
        for name, layer in self.layer_vertices.items():
            it = self._input_type_for(name)
            params[name] = layer.init_params(rngs[name], it)
            state[name] = layer.init_state(it)
        self.params = params
        self.state = state
        self.updater_state = {
            name: self._layer_updater(self.conf.vertices[name]).init(p)
            for name, p in params.items()}
        return self

    def _input_type_for(self, name):
        rec = self.conf.inferred_input_types.get(name)
        if rec is not None:
            it = rec[1]
            if isinstance(it, list):
                it = it[0]
            return it
        from .conf.input_type import InputType
        layer = self.conf.vertices[name]
        n_in = getattr(layer, "n_in", None)
        if layer.has_params and not n_in:
            raise ValueError(
                f"Vertex '{name}' needs n_in or graph input_types")
        return InputType.feed_forward(n_in or 0)

    def _layer_updater(self, layer):
        return (layer.updater if isinstance(layer, LayerConf) and layer.updater
                else self.conf.conf.updater)

    @_functools.cached_property
    def _compute_dtype(self):
        """jnp dtype for mixed-precision compute, or None when disabled."""
        cdt = self.conf.conf.compute_dtype
        if cdt is None or jnp.dtype(cdt) == jnp.dtype(self.conf.conf.dtype):
            return None
        return jnp.dtype(cdt)

    def _precision_remat_context(self):
        """FitCheckpointer context entries (see MultiLayerNetwork) — the
        policies whose mismatch a resume should warn about."""
        c = self.conf.conf
        return {"compute_dtype": c.compute_dtype, "remat": c.remat,
                "remat_policy": c.remat_policy}

    # ------------------------------------------------------------------
    # Functional core
    # ------------------------------------------------------------------
    def _apply_vertex(self, name, rng_i, values, masks, new_state,
                      new_carries, params, state, train, cdt, out_set,
                      carries):
        """Apply one vertex in place (values/masks/new_state/new_carries are
        mutated). Shared by the plain topo loop and the remat-segment path."""
        v = self.conf.vertices[name]
        in_names = self.conf.vertex_inputs[name]
        ins = [values[i_] for i_ in in_names]
        in_masks = [masks.get(i_) for i_ in in_names]
        if isinstance(v, LayerConf):
            x = ins[0]
            m = in_masks[0]
            rec = self.conf.inferred_input_types.get(name)
            if rec is not None and rec[0] is not None:
                x = rec[0].apply(x)
                m = rec[0].apply_mask(m)
            if name in out_set and isinstance(v, BaseOutputLayerConf):
                values[name] = (x, m)  # defer loss/activation to caller
                masks[name] = m
                return
            p_v = params[name]
            # Mixed precision: hidden vertices compute in cdt; output
            # layers keep master-dtype params (see MultiLayerNetwork).
            if cdt is not None and not isinstance(v, BaseOutputLayerConf):
                p_v = cast_floating(p_v, cdt)
            if carries is not None and getattr(v, "is_recurrent", False):
                (y, new_carries[name]), new_state[name] = v.apply(
                    p_v, state[name], x, train=train, rng=rng_i,
                    mask=m, carry=carries.get(name), return_carry=True)
            else:
                y, new_state[name] = v.apply(p_v, state[name], x,
                                             train=train, rng=rng_i,
                                             mask=m)
            values[name] = y
            masks[name] = v.output_mask(m)
        else:
            values[name] = v.apply(ins, in_masks)
            masks[name] = v.output_mask(in_masks)

    def _forward_values(self, params, state, inputs: Dict[str, Any], train,
                        rng, fmasks: Optional[Dict[str, Any]] = None,
                        stop_at_outputs: bool = False, carries=None):
        """Execute vertices in topo order. Returns (values, masks, new_state)
        — or (values, masks, new_state, new_carries) when `carries` (a dict
        keyed by recurrent vertex name) is given, for stateful streaming
        inference (reference ComputationGraph.rnnTimeStep).
        Output-layer vertices contribute their *pre-activation input* (the
        caller applies loss or activation)."""
        cdt = self._compute_dtype
        if cdt is not None:
            inputs = {k: (v.astype(cdt)
                          if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)
                          else v) for k, v in inputs.items()}
        values: Dict[str, Any] = dict(inputs)
        new_carries: Dict[str, Any] = {}
        masks: Dict[str, Any] = dict(fmasks or {})
        for k in self.conf.network_inputs:
            masks.setdefault(k, None)
        new_state = dict(state)
        layer_names = [n for n in self.conf.topological_order
                       if n in self.conf.vertices]
        rngs = (jax.random.split(rng, max(1, len(layer_names)))
                if rng is not None else [None] * len(layer_names))
        out_set = set(self.conf.network_outputs) if stop_at_outputs else set()
        remat = self.conf.conf.remat
        if remat in ("layer", "blocks") and train and carries is None:
            if all(m is None for m in masks.values()):
                self._forward_segments(
                    remat, layer_names, rngs, values, masks, new_state,
                    params, state, train, cdt, out_set)
                return values, masks, new_state
            import warnings
            warnings.warn(
                f"remat={remat!r} is inactive for this step: segment "
                "checkpointing does not support mask arrays — training "
                "falls back to the save-everything path (no activation "
                "memory savings)", stacklevel=3)
        for i, name in enumerate(layer_names):
            self._apply_vertex(name, rngs[i], values, masks, new_state,
                               new_carries, params, state, train, cdt,
                               out_set, carries)
        if carries is not None:
            return values, masks, new_state, new_carries
        return values, masks, new_state

    @_functools.cached_property
    def _block_segments(self) -> List[List[str]]:
        """Partition the topo order into remat segments, cutting wherever
        exactly ONE value is live (consumed by later vertices). For residual
        nets the skip connection keeps the block input live across the block,
        so cuts land on block boundaries; linear chains cut at every vertex
        (≡ per-layer checkpointing)."""
        layer_names = [n for n in self.conf.topological_order
                       if n in self.conf.vertices]
        pos = {n: i for i, n in enumerate(layer_names)}
        last_use: Dict[str, int] = {}
        for j, n in enumerate(layer_names):
            for src in self.conf.vertex_inputs[n]:
                last_use[src] = max(last_use.get(src, -1), j)
        outputs = set(self.conf.network_outputs)
        segments: List[List[str]] = []
        cur: List[str] = []
        for i, n in enumerate(layer_names):
            cur.append(n)
            if i == len(layer_names) - 1:
                cut = True
            else:
                live = {v for v, lu in last_use.items()
                        if lu > i and pos.get(v, -1) <= i}
                live |= {o for o in outputs if pos.get(o, len(layer_names)) <= i}
                cut = live == {n}
            if cut:
                segments.append(cur)
                cur = []
        if cur:
            segments.append(cur)
        return segments

    def _forward_segments(self, remat, layer_names, rngs, values, masks,
                          new_state, params, state, train, cdt, out_set):
        """Run the topo order as jax.checkpoint segments: only segment
        boundaries (and the small per-segment state updates) are saved for
        backward; intra-segment activations are rematerialized. Mutates
        values/masks/new_state (masks stay None — guarded by caller)."""
        pos = {n: i for i, n in enumerate(layer_names)}
        segments = ([[n] for n in layer_names] if remat == "layer"
                    else self._block_segments)
        last_use: Dict[str, int] = {}
        for j, n in enumerate(layer_names):
            for src in self.conf.vertex_inputs[n]:
                last_use[src] = max(last_use.get(src, -1), j)
        for seg in segments:
            seg_set = set(seg)
            seg_end = pos[seg[-1]]
            boundary = {}
            for n in seg:
                for src in self.conf.vertex_inputs[n]:
                    if src not in seg_set:
                        boundary[src] = values[src]
            seg_params = {n: params[n] for n in seg if n in params}
            seg_state = {n: state[n] for n in seg if n in state}
            seg_rngs = ([rngs[pos[n]] for n in seg]
                        if rngs[0] is not None else None)
            if seg_rngs is not None:
                seg_rngs = jnp.stack(seg_rngs)
            keep = [n for n in seg
                    if last_use.get(n, -1) > seg_end or n in out_set]

            def seg_fn(boundary, seg_params, seg_state, seg_rngs,
                       _seg=tuple(seg), _keep=tuple(keep)):
                vals = dict(boundary)
                msk = {k: None for k in vals}
                ns: Dict[str, Any] = {}
                for k, name in enumerate(_seg):
                    r = seg_rngs[k] if seg_rngs is not None else None
                    self._apply_vertex(name, r, vals, msk, ns, {},
                                       seg_params, seg_state, train, cdt,
                                       out_set, None)
                return {n: vals[n] for n in _keep}, ns

            from .remat import resolve_policy
            res, ns = jax.checkpoint(
                seg_fn,
                policy=resolve_policy(self.conf.conf.remat_policy))(
                    boundary, seg_params, seg_state, seg_rngs)
            values.update(res)
            masks.update({n: None for n in res})
            new_state.update(ns)

    def _reg_score(self, params):
        """Full-network l1/l2 penalty (MultiLayerNetwork._reg_score
        counterpart — single source for every scoring path)."""
        reg = jnp.float32(0.0)
        for name, p in params.items():
            if p:
                reg = reg + self.conf.vertices[name].reg_score(p)
        return reg

    def _loss_fn(self, params, state, inputs, labels, rng, fmasks=None,
                 lmasks=None, train=True):
        """labels: dict {output_name: labels}; lmasks likewise."""
        values, masks, new_state = self._forward_values(
            params, state, inputs, train, rng, fmasks, stop_at_outputs=True)
        total = jnp.float32(0.0)
        batch = next(iter(inputs.values())).shape[0]
        live = jnp.zeros((batch,), jnp.float32)
        all_masked = True
        for i, name in enumerate(self.conf.network_outputs):
            v = self.conf.vertices[name]
            if not isinstance(v, BaseOutputLayerConf):
                raise ValueError(
                    f"Network output '{name}' must be an output/loss layer "
                    "for training")
            x, m = values[name]
            lm = (lmasks or {}).get(name)
            eff = lm if lm is not None else m
            # output layers may carry input dropout (e.g. GoogLeNet's 0.6
            # head) — give each output head its own key
            out_rng = (jax.random.fold_in(rng, i)
                       if (rng is not None and train) else None)
            total = total + v.loss_score(params[name], state[name], x,
                                         labels[name], train=train,
                                         rng=out_rng, mask=eff)
            if eff is None:
                all_masked = False
            else:
                live = jnp.maximum(live, eff.astype(jnp.float32).reshape(
                    (eff.shape[0], -1)).max(axis=1))
        # Regularization normalizes by REAL rows (live in ANY output's
        # mask), not the padded batch size, so PadToBatchIterator's
        # weight-zero rows are a learning no-op (each output's loss is
        # already a masked mean); an unmasked output counts every row
        if all_masked:
            batch = jnp.maximum(jnp.sum(live), 1.0)
        score = total + self._reg_score(params) / batch
        # layer auxiliary losses (MoE router load balancing) — train only
        if train:
            for name, s in new_state.items():
                v = self.conf.vertices.get(name)
                if v is not None and hasattr(v, "aux_score"):
                    score = score + v.aux_score(s)
        return score, new_state

    def _make_train_step(self):
        base_loss = self._loss_fn
        if self.conf.conf.remat == "full":
            # save only the step inputs; recompute the entire forward in
            # backward (jax.checkpoint over the whole loss)
            from .remat import resolve_policy
            pol = resolve_policy(self.conf.conf.remat_policy)

            def loss_fn(params, state, inputs, labels, rng,
                        fmasks=None, lmasks=None):
                f = lambda p, s, i_, l_, r_: base_loss(
                    p, s, i_, l_, r_, fmasks=fmasks, lmasks=lmasks)
                return jax.checkpoint(f, policy=pol)(params, state, inputs,
                                                     labels, rng)
        else:
            loss_fn = base_loss

        def train_step(params, state, opt_state, step, inputs, labels, rng,
                       fmasks, lmasks):
            (score, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, state, inputs, labels,
                                       rng, fmasks=fmasks,
                                       lmasks=lmasks)
            if not self.conf.conf.minimize:
                grads = jax.tree_util.tree_map(lambda g: -g, grads)
            new_params, new_opt = self.apply_vertex_updates(
                params, grads, opt_state, step)
            return new_params, new_state, new_opt, score

        return train_step

    def apply_vertex_updates(self, params, grads, opt_state, step):
        """Apply per-vertex updaters to the gradient tree — the update
        half of the train step, shared with the ZeRO sharded-optimizer
        step (parallel/zero.py), which reduces the gradients itself and
        needs only the update applied. Pure/traceable."""
        new_params, new_opt = {}, {}
        for name, p in params.items():
            layer = self.conf.vertices[name]
            g, os = grads[name], opt_state[name]
            if not p or layer.frozen:
                new_params[name] = p
                new_opt[name] = os
                continue
            g = apply_gradient_normalization(
                layer.gradient_normalization,
                layer.gradient_normalization_threshold or 1.0, g)
            upd = self._layer_updater(layer)
            lr = self._layer_lr(layer, step)
            updates, os = upd.update(g, os, step, lr)
            if getattr(layer, "bias_learning_rate", None) is not None:
                # same bias-lr rescale as the multilayer step (updater
                # steps are linear in lr, so rescaling is exact)
                from .multilayer import _rescale_bias_updates
                if lr is None:
                    eff = getattr(upd, "learning_rate", 1.0) or 1.0
                    scale = layer.bias_learning_rate / eff
                else:
                    scale = layer.bias_learning_rate / jnp.maximum(
                        jnp.asarray(lr, jnp.float32), 1e-30)
                updates = _rescale_bias_updates(updates, scale)
            # tree-wise: vertex params may be nested dicts (BiLSTM)
            new_params[name] = jax.tree_util.tree_map(
                lambda a, u: a - u, p, updates)
            new_opt[name] = os
        return new_params, new_opt

    def _layer_lr(self, layer, step):
        sched = self.conf.conf.lr_schedule
        base = layer.learning_rate
        if sched is None:
            return base
        lr = sched(step)
        if base is not None and sched.base_lr:
            lr = lr * (base / sched.base_lr)
        return lr

    @functools.cached_property
    def train_step_fn(self):
        return self._make_train_step()

    @functools.cached_property
    def grad_step_fn(self):
        """Gradient half of the graph train step — ``(params, state,
        inputs, labels, rng, fmasks, lmasks) -> (score, new_state,
        grads)`` with remat="full" and the minimize sign folded in
        (MultiLayerNetwork.grad_step_fn counterpart; composed by the
        accumulation superstep and the ZeRO step)."""
        base_loss = self._loss_fn
        if self.conf.conf.remat == "full":
            from .remat import resolve_policy
            pol = resolve_policy(self.conf.conf.remat_policy)

            def loss_fn(params, state, inputs, labels, rng,
                        fmasks=None, lmasks=None):
                f = lambda p, s, i_, l_, r_: base_loss(
                    p, s, i_, l_, r_, fmasks=fmasks, lmasks=lmasks)
                return jax.checkpoint(f, policy=pol)(params, state, inputs,
                                                     labels, rng)
        else:
            loss_fn = base_loss
        minimize = self.conf.conf.minimize

        def grad_step(params, state, inputs, labels, rng, fmasks, lmasks):
            (score, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, state, inputs, labels, rng,
                                       fmasks=fmasks, lmasks=lmasks)
            if not minimize:
                grads = jax.tree_util.tree_map(lambda g: -g, grads)
            return score, new_state, grads

        return grad_step

    def apply_updates(self, params, grads, opt_state, step):
        """Update half on a full gradient tree (apply_vertex_updates under
        the shared grad/update split the accumulation and ZeRO steps
        compose). Pure/traceable."""
        return self.apply_vertex_updates(params, grads, opt_state, step)

    def _accum_superstep_fn(self, skip_nonfinite: bool):
        """Jitted accumulated superstep over stacked input/label DICT
        windows [K, M, batch, ...] (None mask leaves pass through as
        static absence) — see nn/superstep.build_accum_superstep. Cached
        per skip flag; K/M are shape-derived."""
        cache = self.__dict__.setdefault("_accum_superstep_cache", {})
        fn = cache.get(bool(skip_nonfinite))
        if fn is None:
            from .superstep import build_accum_superstep
            fn = cache[bool(skip_nonfinite)] = watch_compiles(
                jax.jit(build_accum_superstep(self.grad_step_fn,
                                              self.apply_updates,
                                              bool(skip_nonfinite)),
                        donate_argnums=(0, 1, 2)),
                "graph/accum_superstep")
        return fn

    @functools.cached_property
    def _train_step(self):
        return watch_compiles(
            jax.jit(self.train_step_fn, donate_argnums=(0, 1, 2)),
            "graph/train_step")

    @functools.cached_property
    def predict_fn(self):
        """Raw (unjitted) pure inference step — for callers that jit it
        themselves with custom shardings (distributed evaluation plane)."""
        def predict(params, state, inputs, fmasks):
            values, masks, _ = self._forward_values(
                params, state, inputs, False, None, fmasks,
                stop_at_outputs=True)
            return self._collect_outputs(params, state, values)
        return predict

    @functools.cached_property
    def _predict_fn(self):
        return watch_compiles(jax.jit(self.predict_fn), "graph/predict")

    def _collect_outputs(self, params, state, values):
        """Activate the network outputs from forward values (shared by the
        predict and rnn-step paths)."""
        outs = []
        for name in self.conf.network_outputs:
            v = self.conf.vertices[name]
            if isinstance(v, BaseOutputLayerConf):
                x, m = values[name]
                y, _ = v.apply(params[name], state[name], x, train=False,
                               rng=None, mask=m)
            else:
                y = values[name]
            outs.append(y)
        return tuple(outs)

    @functools.cached_property
    def _score_fn(self):
        def score(params, state, inputs, labels, fmasks, lmasks):
            s, _ = self._loss_fn(params, state, inputs, labels, None,
                                 fmasks=fmasks, lmasks=lmasks, train=False)
            return s
        return watch_compiles(jax.jit(score), "graph/score")

    # ------------------------------------------------------------------
    # Data plumbing
    # ------------------------------------------------------------------
    def _to_inputs(self, ds) -> Tuple[Dict, Dict, Dict, Dict]:
        ins = self.conf.network_inputs
        outs = self.conf.network_outputs
        if isinstance(ds, DataSet):
            if len(ins) != 1 or len(outs) != 1:
                raise ValueError("DataSet fits single-input/single-output "
                                 "graphs; use MultiDataSet")
            x, y, fm, lm = ds.device_tuple()
            return ({ins[0]: x}, {outs[0]: y}, {ins[0]: fm}, {outs[0]: lm})
        if isinstance(ds, MultiDataSet):
            f, l, fm, lm = ds.device_tuple()
            inputs = dict(zip(ins, f))
            labels = dict(zip(outs, l))
            fm = fm or (None,) * len(ins)
            lm = lm or (None,) * len(outs)
            fmasks = dict(zip(ins, fm))
            lmasks = dict(zip(outs, lm))
            return inputs, labels, fmasks, lmasks
        raise TypeError(f"Cannot fit on {type(ds)}")

    # ------------------------------------------------------------------
    # Public API
    # ------------------------------------------------------------------
    def fit(self, data, epochs: int = 1, *, superstep=1,
            grad_accumulation: int = 1, prefetch: bool = False,
            pad_ragged: bool = False, time_buckets=None,
            checkpoint_dir: Optional[str] = None, checkpoint_every: int = 0,
            resume: bool = False, guard=None):
        """fit(DataSet/MultiDataSet) or fit(iterator). `pad_ragged` pads
        ragged final batches to the fixed batch size with weight-zero rows
        (one train-step compile per fit, learning no-op); `prefetch` moves
        `device_tuple()` to a background thread one batch ahead so
        host->device transfer overlaps compute (see datasets/pipeline.py).

        `superstep=K` (iterator inputs) runs windows of K batches as ONE
        jitted `lax.scan` dispatch — bit-identical to the K=1 per-batch
        loop, with listeners/guard/checkpoints firing at superstep edges
        on the per-window loss vector (see nn/superstep.py). "auto" sizes
        K from batch bytes and adapts it to the measured dispatch/compute
        ratio, "epoch" windows the whole epoch. Line-search optimizers
        fall back to per-batch dispatch.

        `grad_accumulation=M` accumulates M consecutive iterator
        microbatches into one optimizer step (fp32 accumulators, update on
        the mean — effective batch M·b at b's activation memory), exactly
        as on `MultiLayerNetwork.fit`; composes with `superstep` (windows
        of K·M microbatches), listener/checkpoint cadence per optimizer
        step.

        Fault-tolerance knobs (`checkpoint_dir`/`checkpoint_every`/
        `resume`/`guard`) behave exactly as on `MultiLayerNetwork.fit`:
        crash-safe interval checkpoints + SIGTERM snapshot, resume that
        replays counters/RNG/shuffle epoch so it matches an uninterrupted
        run, and a TrainingGuard applying its non-finite-loss policy per
        batch (see fault/)."""
        from .superstep import validate_grad_accumulation
        accum_m = validate_grad_accumulation(grad_accumulation)
        if self.params is None:
            self.init()
        if isinstance(data, (DataSet, MultiDataSet)):
            if checkpoint_dir is not None or resume:
                raise ValueError(
                    "checkpoint_dir/resume need an iterator fit (the "
                    "checkpoint records epoch/batch progress)")
            if accum_m != 1:
                raise ValueError(
                    f"grad_accumulation={accum_m} needs an iterator fit "
                    "(M consecutive microbatches form one optimizer step)")
            if superstep != 1:
                log.info("superstep=%r ignored for a single-DataSet fit "
                         "(one batch is one step); pass an iterator to "
                         "window batches", superstep)
            if guard is not None:
                guard.run_step(self, lambda: self._fit_batch(data))
            else:
                self._fit_batch(data)
            return self
        from ..fault.resume import maybe_fit_checkpointer
        ckpt = maybe_fit_checkpointer(self, checkpoint_dir, checkpoint_every,
                                      resume,
                                      context={"grad_accumulation": accum_m})
        skip, done_epochs = (0, 0) if ckpt is None else ckpt.resume_into(data)
        from ..datasets.pipeline import build_pipeline
        data, close = build_pipeline(data, pad_ragged=pad_ragged,
                                     prefetch=prefetch,
                                     time_buckets=time_buckets)
        runner = self._make_superstep_runner(superstep, guard, ckpt, accum_m)
        if runner is not None:
            runner.skip(skip)
            skip = 0
            if self.listeners:
                from ..optimize.listeners import warn_scan_replay
                warn_scan_replay(self.listeners)
        sigterm = (ckpt.sigterm_snapshot() if ckpt is not None
                   else _null_span())
        try:
            with sigterm:
                for _ in range(max(0, epochs - done_epochs)):
                    data.reset()
                    if runner is not None:
                        runner.run_epoch(data)
                    else:
                        while data.has_next():
                            ds = (guard.next_batch(data) if guard is not None
                                  else data.next())
                            if skip:
                                skip -= 1   # resume: prefix already trained
                                continue
                            if guard is not None:
                                guard.run_step(self,
                                               lambda b=ds: self._fit_batch(b))
                            else:
                                self._fit_batch(ds)
                            if ckpt is not None:
                                ckpt.on_batch()
                    self.epoch_count += 1
                    if ckpt is not None:
                        ckpt.on_epoch()
                if ckpt is not None:
                    ckpt.on_fit_end()
        finally:
            close()
        return self

    def _make_superstep_runner(self, superstep, guard, ckpt, accum_m=1):
        """SuperstepRunner for this fit, or None for the per-batch loop
        (superstep=1 with grad_accumulation=1, or a line-search
        optimizer — which rejects M>1 rather than silently changing the
        effective batch)."""
        from .conf import OptimizationAlgorithm as OA
        from .superstep import (SuperstepRunner, accum_skip_nonfinite,
                                validate_superstep)

        k = validate_superstep(superstep)
        if k == 1 and accum_m == 1:
            return None
        if self.conf.conf.optimization_algo != OA.STOCHASTIC_GRADIENT_DESCENT:
            if accum_m != 1:
                raise ValueError(
                    f"grad_accumulation={accum_m} is not supported with "
                    "line-search optimizers (per-batch sequential)")
            log.info("superstep=%r falls back to per-batch dispatch: "
                     "line-search optimizers are per-batch sequential",
                     superstep)
            return None
        adapter = _GraphSuperstepAdapter(
            self, m=accum_m,
            skip_nonfinite=accum_skip_nonfinite(guard, accum_m))
        return SuperstepRunner(self, adapter, k, guard=guard, ckpt=ckpt,
                               grad_accumulation=accum_m)

    @_functools.cached_property
    def _superstep_fn(self):
        """Device-resident superstep: `lax.scan` of the graph train step
        over a [K, batch, ...] window of stacked input/label dicts, RNG
        chain threaded inside — bit-identical to the per-batch loop (see
        nn/superstep.py)."""
        from .superstep import build_superstep
        return watch_compiles(
            jax.jit(build_superstep(self.train_step_fn),
                    donate_argnums=(0, 1, 2)),
            "graph/superstep")

    @_functools.cached_property
    def _line_solver(self):
        from ..optimize.solvers import GraphLineSearchSolver
        return GraphLineSearchSolver(
            self, self.conf.conf.optimization_algo,
            max_line_search_iterations=
            self.conf.conf.max_num_line_search_iterations)

    def _fit_batch(self, ds):
        from .conf import OptimizationAlgorithm as OA

        tel = _tel_active()
        span = tel.span if tel is not None else _null_span
        with span("host/batch_prep"):
            inputs, labels, fmasks, lmasks = self._to_inputs(ds)
        self._rng, step_rng = jax.random.split(self._rng)
        if self.conf.conf.optimization_algo != OA.STOCHASTIC_GRADIENT_DESCENT:
            with span("device/dispatch", kind="line_search"):
                self.params, self.state, score = self._line_solver.fit_batch(
                    self.params, self.state, inputs, labels, step_rng,
                    fmasks, lmasks)
            self._score = score
            self.last_batch_size = int(
                next(iter(inputs.values())).shape[0])
            self.iteration_count += 1
            for listener in self.listeners:
                listener.iteration_done(self, self.iteration_count)
            return
        step = jnp.asarray(self.iteration_count, jnp.int32)
        with span("device/dispatch", kind="train_step"):
            (self.params, self.state, self.updater_state,
             score) = self._train_step(self.params, self.state,
                                       self.updater_state, step, inputs,
                                       labels, step_rng, fmasks, lmasks)
        if tel is not None and tel.sync_per_step:
            with span("device/sync"):
                jax.block_until_ready(score)
        self._score = score
        self.last_batch_size = int(next(iter(inputs.values())).shape[0])
        self.iteration_count += 1
        for listener in self.listeners:
            listener.iteration_done(self, self.iteration_count)

    def fit_scan_arrays(self, xs, ys, epochs: int = 1):
        """Device-resident multi-step training: the whole [T]-step pass runs
        as ONE `lax.scan` dispatch (MultiLayerNetwork.fit_scan_arrays
        analog for graphs). `xs`: [T, batch, ...] array (single-input
        graphs) or dict {input_name: [T, batch, ...]}; `ys` likewise for
        outputs. Pass device-resident arrays (jax.device_put once) — on
        remote-tunnel backends the link, not the math, is the bottleneck.

        Listener caveat: iteration_done is replayed AFTER the scan with
        per-step scores, so every call sees the END-OF-WINDOW params —
        per-iteration param/update histograms are not faithful on this
        path (a warning fires for such listeners); use fit() for those."""
        from .conf import OptimizationAlgorithm as OA

        if self.params is None:
            self.init()
        if self.conf.conf.optimization_algo != OA.STOCHASTIC_GRADIENT_DESCENT:
            raise ValueError(
                "fit_scan_arrays supports SGD-updater training only; "
                "line-search optimizers are per-batch sequential — use fit()")
        tel = _tel_active()
        span = tel.span if tel is not None else _null_span
        if not isinstance(xs, dict):
            xs = {self.conf.network_inputs[0]: xs}
        if not isinstance(ys, dict):
            ys = {self.conf.network_outputs[0]: ys}
        with span("host/batch_prep"):
            xs = {k: jnp.asarray(v) for k, v in xs.items()}
            ys = {k: jnp.asarray(v) for k, v in ys.items()}
        key = (tuple(sorted((k, tuple(v.shape), str(v.dtype))
                            for k, v in xs.items())),
               tuple(sorted((k, tuple(v.shape), str(v.dtype))
                            for k, v in ys.items())))
        cache = self.__dict__.setdefault("_scan_epoch_cache", {})
        epoch_fn = cache.get(key)
        if epoch_fn is None:
            step_fn = self.train_step_fn

            @jax.jit
            def epoch_fn(params, state, opt, step0, xs, ys, rng):
                n = next(iter(xs.values())).shape[0]
                keys = jax.random.split(rng, n)

                def body(carry, inp):
                    params, state, opt, step = carry
                    xt, yt, k = inp
                    params, state, opt, score = step_fn(
                        params, state, opt, step, xt, yt, k, None, None)
                    return (params, state, opt, step + 1), score

                (params, state, opt, _), scores = jax.lax.scan(
                    body, (params, state, opt, step0), (xs, ys, keys))
                return params, state, opt, scores

            epoch_fn = cache[key] = watch_compiles(epoch_fn,
                                                   "graph/scan_epoch")
        n_steps = int(next(iter(xs.values())).shape[0])
        if self.listeners:
            from ..optimize.listeners import warn_scan_replay
            warn_scan_replay(self.listeners)
        for _ in range(epochs):
            self._rng, k = jax.random.split(self._rng)
            with span("device/dispatch", kind="scan_epoch"):
                (self.params, self.state, self.updater_state,
                 scores) = epoch_fn(
                    self.params, self.state, self.updater_state,
                    jnp.asarray(self.iteration_count, jnp.int32), xs, ys, k)
            self.last_batch_size = int(next(iter(xs.values())).shape[1])
            if self.listeners:
                with span("device/sync", kind="scan_scores"):
                    host_scores = np.asarray(scores)
                for i in range(n_steps):
                    self._score = host_scores[i]
                    self.iteration_count += 1
                    for listener in self.listeners:
                        listener.iteration_done(self, self.iteration_count)
            else:
                self._score = scores[-1]
                self.iteration_count += n_steps
            self.epoch_count += 1
        return self

    def output(self, *features, features_masks=None):
        if self.params is None:
            self.init()
        ins = self.conf.network_inputs
        inputs = {n: jnp.asarray(f) for n, f in zip(ins, features)}
        fmasks = {n: None for n in ins}
        if features_masks is not None:
            fmasks = {n: (None if m is None else jnp.asarray(m))
                      for n, m in zip(ins, features_masks)}
        return self._predict_fn(self.params, self.state, inputs, fmasks)

    def output_single(self, *features, **kw):
        return self.output(*features, **kw)[0]

    # -- stateful RNN inference (reference ComputationGraph.rnnTimeStep) --
    @functools.cached_property
    def _rnn_step_fn(self):
        def step(params, state, inputs, carries):
            values, masks, _, new_carries = self._forward_values(
                params, state, inputs, False, None, None,
                stop_at_outputs=True, carries=carries)
            return self._collect_outputs(params, state, values), new_carries
        return watch_compiles(jax.jit(step), "graph/rnn_step")

    def rnn_time_step(self, *features):
        """Feed one (or a few) timesteps through the graph, carrying hidden
        state of every recurrent vertex across calls. 2-D inputs are
        treated as single timesteps per input (mixed-rank multi-input
        graphs keep their static inputs 2-D)."""
        if self.params is None:
            self.init()
        xs = [jnp.asarray(f) for f in features]
        squeeze = xs[0].ndim == 2
        xs = [x[:, None, :] if x.ndim == 2 else x for x in xs]
        inputs = dict(zip(self.conf.network_inputs, xs))
        batch = int(xs[0].shape[0])
        carries = getattr(self, "_rnn_carries", None)
        if carries:  # non-empty: a graph with no recurrent vertices caches {}
            cached_batch = jax.tree_util.tree_leaves(carries)[0].shape[0]
            if cached_batch != batch:
                raise ValueError(
                    f"rnn_time_step batch changed from {cached_batch} to "
                    f"{batch}; call rnn_clear_previous_state() first")
        if carries is None:
            rec = {name: v for name, v in self.conf.vertices.items()
                   if getattr(v, "is_recurrent", False)}
            not_stepable = [n for n, v in rec.items()
                            if not hasattr(v, "init_carry")]
            if not_stepable:
                raise ValueError(
                    f"rnn_time_step unsupported for vertices "
                    f"{not_stepable} (bidirectional layers need the full "
                    "sequence — the reference rejects these too)")
            carries = {name: v.init_carry(batch, xs[0].dtype)
                       for name, v in rec.items()}
        outs, self._rnn_carries = self._rnn_step_fn(
            self.params, self.state, inputs, carries)
        if squeeze:
            outs = tuple(o[:, 0] if o.ndim == 3 else o for o in outs)
        return outs if len(outs) > 1 else outs[0]

    def rnn_clear_previous_state(self):
        self._rnn_carries = None

    def score(self, ds=None) -> float:
        if ds is None:
            return float(self._score)
        inputs, labels, fmasks, lmasks = self._to_inputs(ds)
        return float(self._score_fn(self.params, self.state, inputs, labels,
                                    fmasks, lmasks))

    @functools.cached_property
    def score_examples_fn(self):
        """Raw per-example scoring step — jitted by callers (see
        _score_examples_fn and the ParallelTrainer scoring plane)."""
        def per_example(params, state, inputs, labels, fmasks, lmasks,
                        add_reg):
            values, masks, _ = self._forward_values(
                params, state, inputs, False, None, fmasks,
                stop_at_outputs=True)
            per = None
            for name in self.conf.network_outputs:
                v = self.conf.vertices[name]
                x, m = values[name]
                lm = (lmasks or {}).get(name)
                eff = lm if lm is not None else m
                contrib = v.loss_per_example(params[name], state[name], x,
                                             labels[name], mask=eff)
                per = contrib if per is None else per + contrib
            if add_reg:
                per = per + self._reg_score(params)
            return per
        return per_example

    @functools.cached_property
    def _score_examples_fn(self):
        return watch_compiles(
            jax.jit(self.score_examples_fn, static_argnums=(6,)),
            "graph/score_examples")

    def score_examples(self, data, add_regularization_terms: bool = True
                       ) -> np.ndarray:
        """Per-example scores summed over all output layers — reference
        `ComputationGraph.scoreExamples` (ComputationGraph.java; the map
        half of Spark's `ScoreExamplesFunction.java:1`). Accepts DataSet /
        MultiDataSet or an iterator thereof."""
        if self.params is None:
            self.init()
        if not isinstance(data, (DataSet, MultiDataSet)):
            data.reset()
            outs = []
            while data.has_next():
                outs.append(self.score_examples(data.next(),
                                                add_regularization_terms))
            return (np.concatenate(outs) if outs
                    else np.zeros(0, np.float32))
        inputs, labels, fmasks, lmasks = self._to_inputs(data)
        per = self._score_examples_fn(self.params, self.state, inputs,
                                      labels, fmasks, lmasks,
                                      bool(add_regularization_terms))
        return np.asarray(per)

    def evaluate(self, iterator, labels_list=None, top_n: int = 1) -> Evaluation:
        ev = Evaluation(labels=labels_list, top_n=top_n)
        iterator.reset()
        while iterator.has_next():
            ds = iterator.next()
            if isinstance(ds, DataSet):
                out = self.output(ds.features,
                                  features_masks=[ds.features_mask])[0]
                ev.eval(ds.labels, np.asarray(out), mask=ds.labels_mask)
            else:
                outs = self.output(*ds.features,
                                   features_masks=ds.features_masks)
                for o, l, m in zip(outs, ds.labels,
                                   ds.labels_masks or [None] * len(ds.labels)):
                    ev.eval(l, np.asarray(o), mask=m)
        return ev

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def num_params(self) -> int:
        return sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(self.params))

    def params_flat(self) -> np.ndarray:
        from .multilayer import _flat_leaves
        parts = [np.asarray(leaf).ravel()
                 for name in sorted(self.params)
                 for leaf in _flat_leaves(self.params[name])]
        return np.concatenate(parts) if parts else np.zeros(0, np.float32)

    def set_params_flat(self, vec: np.ndarray):
        from .multilayer import _unflatten_like
        vec = np.asarray(vec)
        to_array = lambda chunk, leaf: jnp.asarray(
            chunk.reshape(leaf.shape), dtype=leaf.dtype)
        pos = 0
        new_params = {}
        for name in sorted(self.params):
            new_params[name], pos = _unflatten_like(
                self.params[name], vec, pos, to_array)
        self.params = new_params

    def clone(self) -> "ComputationGraph":
        g = ComputationGraph(self.conf)
        if self.params is not None:
            copy = lambda a: jnp.array(a, copy=True)
            g.params = jax.tree_util.tree_map(copy, self.params)
            g.state = jax.tree_util.tree_map(copy, self.state)
            g.updater_state = jax.tree_util.tree_map(copy, self.updater_state)
            g._rng = self._rng
        g.iteration_count = self.iteration_count
        return g


class _GraphSuperstepAdapter:
    """SuperstepRunner hooks for ComputationGraph (see nn/superstep.py):
    batches are dicts keyed by input/output name (DataSet or MultiDataSet
    sources), masks are dicts whose values may be None — None leaves pass
    through the scan as the same static absence the per-batch step sees.
    With ``m>1`` dispatch routes the window through the accumulated
    superstep in [K, M] groups."""

    def __init__(self, net: ComputationGraph, m: int = 1,
                 skip_nonfinite: bool = False):
        self.net = net
        self.m = int(m)
        self.skip_nonfinite = bool(skip_nonfinite)

    @staticmethod
    def _shape(a):
        return None if a is None else tuple(np.shape(a))

    def signature(self, ds):
        if isinstance(ds, MultiDataSet):
            seq = lambda xs: (None if xs is None else
                              tuple(self._shape(a) for a in xs))
            return (seq(ds.features), seq(ds.labels),
                    seq(ds.features_masks), seq(ds.labels_masks))
        return (self._shape(ds.features), self._shape(ds.labels),
                self._shape(ds.features_mask), self._shape(ds.labels_mask))

    def batch_nbytes(self, ds):
        from ..datasets.pipeline import batch_nbytes
        if isinstance(ds, MultiDataSet):
            arrays = list(ds.features) + list(ds.labels)
            for ms in (ds.features_masks, ds.labels_masks):
                if ms is not None:
                    arrays.extend(ms)
            return batch_nbytes(arrays)
        return batch_nbytes((ds.features, ds.labels, ds.features_mask,
                             ds.labels_mask))

    def stage(self, window):
        from ..datasets.pipeline import stage_window
        return stage_window([self.net._to_inputs(ds) for ds in window])

    def dispatch(self, staged, n, step0):
        net = self.net
        if self.m == 1:
            xs, ys, fms, lms = staged
            (net.params, net.state, net.updater_state, net._rng,
             scores) = net._superstep_fn(
                net.params, net.state, net.updater_state,
                jnp.asarray(step0, jnp.int32), net._rng, xs, ys, fms, lms)
            return scores
        from .superstep import dispatch_accum_groups
        fn = net._accum_superstep_fn(self.skip_nonfinite)

        def run_group(seg, step):
            xs, ys, fms, lms = seg
            (net.params, net.state, net.updater_state, net._rng, scores,
             mscores) = fn(net.params, net.state, net.updater_state,
                           jnp.asarray(step, jnp.int32), net._rng,
                           xs, ys, fms, lms)
            return scores, mscores

        return dispatch_accum_groups(staged, n, self.m, step0, run_group)

    def on_window_end(self, window):
        last = window[-1]
        feats = (last.features[0] if isinstance(last, MultiDataSet)
                 else last.features)
        self.net.last_batch_size = int(np.shape(feats)[0])
