"""Loss functions.

Capability parity with ND4J's `ILossFunction` implementations as consumed by the
reference's output layers (`nn/conf/layers/OutputLayer.java`,
`nn/layers/BaseOutputLayer`). Every loss is a pure function

    loss(labels, preactivations_or_activations, activation_fn, mask) -> scalar

returning the mean per-example score, with optional per-element label weights
and per-timestep masks (the reference's masked scoring path is
`util/MaskedReductionUtil.java`). Gradients flow through `jax.grad` — no
hand-coded `computeGradient` like ND4J's loss classes.

Numerically-fused paths: `mcxent` + softmax and `xent` + sigmoid are computed
from logits with log-sum-exp / log-sigmoid so XLA sees the fused stable form.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["get", "LOSSES", "Loss"]

_EPS = 1e-7


def _apply_mask(per_example, mask):
    """per_example: [batch, ...] already reduced over features -> [batch] or
    [batch, time]. Mask broadcasts over it; returns masked mean."""
    if mask is None:
        return jnp.mean(per_example)
    mask = mask.astype(per_example.dtype)
    mask = jnp.broadcast_to(mask.reshape(mask.shape + (1,) * (per_example.ndim - mask.ndim)), per_example.shape)
    total = jnp.sum(per_example * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    return total / count


class Loss:
    """A named loss. `score(labels, logits, activation, mask, weights)` returns the
    scalar mean score; `per_example` returns the unreduced [batch,...] scores."""

    def __init__(self, name, fn, fused_with=None):
        self.name = name
        self._fn = fn
        # activation name this loss fuses with when computed from logits
        self.fused_with = fused_with

    def per_example(self, labels, logits, activation=None, weights=None):
        return self._fn(labels, logits, activation, weights)

    def score(self, labels, logits, activation=None, mask=None, weights=None):
        return _apply_mask(self.per_example(labels, logits, activation, weights), mask)

    def __repr__(self):
        return f"Loss({self.name})"


def _activate(logits, activation):
    from . import activations

    if activation is None:
        return logits
    return activations.get(activation)(logits)


def _wsum(per_elem, weights):
    """Reduce feature axis with optional per-class weights."""
    if weights is not None:
        per_elem = per_elem * jnp.asarray(weights, dtype=per_elem.dtype)
    return jnp.sum(per_elem, axis=-1)


def _mse(labels, logits, activation, weights):
    out = _activate(logits, activation)
    return _wsum((out - labels) ** 2, weights) / labels.shape[-1]


def _l2(labels, logits, activation, weights):
    out = _activate(logits, activation)
    return _wsum((out - labels) ** 2, weights)


def _mae(labels, logits, activation, weights):
    out = _activate(logits, activation)
    return _wsum(jnp.abs(out - labels), weights) / labels.shape[-1]


def _l1(labels, logits, activation, weights):
    out = _activate(logits, activation)
    return _wsum(jnp.abs(out - labels), weights)


def _mcxent(labels, logits, activation, weights):
    # Multi-class cross entropy. When paired with softmax we fuse from logits
    # (stable log_softmax); with any other activation we take log of outputs.
    act_name = str(activation).lower() if activation is not None else None
    if act_name in (None, "softmax"):
        logp = jax.nn.log_softmax(logits, axis=-1)
    else:
        out = _activate(logits, activation)
        logp = jnp.log(jnp.clip(out, _EPS, 1.0))
    return -_wsum(labels * logp, weights)


def _xent(labels, logits, activation, weights):
    # Binary cross entropy per output unit. Fused sigmoid path from logits.
    act_name = str(activation).lower() if activation is not None else None
    if act_name in (None, "sigmoid"):
        logp = jax.nn.log_sigmoid(logits)
        lognotp = jax.nn.log_sigmoid(-logits)
    else:
        out = jnp.clip(_activate(logits, activation), _EPS, 1.0 - _EPS)
        logp, lognotp = jnp.log(out), jnp.log1p(-out)
    return -_wsum(labels * logp + (1.0 - labels) * lognotp, weights)


def _nll(labels, logits, activation, weights):
    # Reference treats NEGATIVELOGLIKELIHOOD as MCXENT (LossNegativeLogLikelihood
    # extends LossMCXENT in ND4J).
    return _mcxent(labels, logits, activation, weights)


def _hinge(labels, logits, activation, weights):
    # labels in {-1, +1} (DL4J converts {0,1} labels; we accept both)
    out = _activate(logits, activation)
    y = jnp.where(labels <= 0, -1.0, 1.0)
    return _wsum(jnp.maximum(0.0, 1.0 - y * out), weights)


def _squared_hinge(labels, logits, activation, weights):
    out = _activate(logits, activation)
    y = jnp.where(labels <= 0, -1.0, 1.0)
    return _wsum(jnp.maximum(0.0, 1.0 - y * out) ** 2, weights)


def _kld(labels, logits, activation, weights):
    out = jnp.clip(_activate(logits, activation), _EPS, 1.0)
    lab = jnp.clip(labels, _EPS, 1.0)
    return _wsum(lab * (jnp.log(lab) - jnp.log(out)), weights)


def _poisson(labels, logits, activation, weights):
    out = jnp.clip(_activate(logits, activation), _EPS, None)
    return _wsum(out - labels * jnp.log(out), weights)


def _cosine_proximity(labels, logits, activation, weights):
    out = _activate(logits, activation)
    ln = jnp.linalg.norm(labels, axis=-1, keepdims=True)
    on = jnp.linalg.norm(out, axis=-1, keepdims=True)
    cos = jnp.sum(labels * out, axis=-1) / jnp.squeeze(
        jnp.maximum(ln * on, _EPS), -1
    )
    return -cos


def _mape(labels, logits, activation, weights):
    out = _activate(logits, activation)
    return _wsum(jnp.abs((labels - out) / jnp.clip(jnp.abs(labels), _EPS, None)), weights) * (
        100.0 / labels.shape[-1]
    )


def _msle(labels, logits, activation, weights):
    out = _activate(logits, activation)
    d = jnp.log1p(jnp.clip(out, -1 + _EPS, None)) - jnp.log1p(jnp.clip(labels, -1 + _EPS, None))
    return _wsum(d ** 2, weights) / labels.shape[-1]


LOSSES = {
    "mse": Loss("mse", _mse),
    "l2": Loss("l2", _l2),
    "mae": Loss("mae", _mae),
    "l1": Loss("l1", _l1),
    "mcxent": Loss("mcxent", _mcxent, fused_with="softmax"),
    "xent": Loss("xent", _xent, fused_with="sigmoid"),
    "negativeloglikelihood": Loss("negativeloglikelihood", _nll, fused_with="softmax"),
    "hinge": Loss("hinge", _hinge),
    "squared_hinge": Loss("squared_hinge", _squared_hinge),
    "kl_divergence": Loss("kl_divergence", _kld),
    "poisson": Loss("poisson", _poisson),
    "cosine_proximity": Loss("cosine_proximity", _cosine_proximity),
    "mape": Loss("mape", _mape),
    "msle": Loss("msle", _msle),
}
# Aliases matching the reference's LossFunctions.LossFunction enum names
LOSSES["squared_loss"] = LOSSES["l2"]
LOSSES["reconstruction_crossentropy"] = LOSSES["xent"]


def get(name):
    if isinstance(name, Loss):
        return name
    key = str(name).lower()
    if key not in LOSSES:
        raise ValueError(f"Unknown loss '{name}'. Available: {sorted(LOSSES)}")
    return LOSSES[key]
