"""Weight initialization schemes.

Parity with the reference's `nn/weights/WeightInit.java:47` enum
(DISTRIBUTION, ZERO, SIGMOID_UNIFORM, UNIFORM, XAVIER, XAVIER_UNIFORM,
XAVIER_FAN_IN, XAVIER_LEGACY, RELU, RELU_UNIFORM) and
`nn/weights/WeightInitUtil.java`'s formulas, realized as pure
`jax.random`-keyed initializers (TPU-native: deterministic, splittable PRNG
instead of a global RNG).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["WeightInit", "init_weight", "Distribution"]


class WeightInit:
    DISTRIBUTION = "distribution"
    ZERO = "zero"
    ONES = "ones"
    SIGMOID_UNIFORM = "sigmoid_uniform"
    UNIFORM = "uniform"
    XAVIER = "xavier"
    XAVIER_UNIFORM = "xavier_uniform"
    XAVIER_FAN_IN = "xavier_fan_in"
    XAVIER_LEGACY = "xavier_legacy"
    RELU = "relu"
    RELU_UNIFORM = "relu_uniform"
    NORMAL = "normal"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    VAR_SCALING_NORMAL_FAN_AVG = "var_scaling_normal_fan_avg"
    IDENTITY = "identity"

    ALL = [
        DISTRIBUTION, ZERO, ONES, SIGMOID_UNIFORM, UNIFORM, XAVIER,
        XAVIER_UNIFORM, XAVIER_FAN_IN, XAVIER_LEGACY, RELU, RELU_UNIFORM,
        NORMAL, LECUN_NORMAL, LECUN_UNIFORM, VAR_SCALING_NORMAL_FAN_AVG,
        IDENTITY,
    ]


@dataclass
class Distribution:
    """Custom distribution for WeightInit.DISTRIBUTION (reference:
    `nn/conf/distribution/{Normal,Uniform,Binomial,GaussianDistribution}.java`)."""

    kind: str = "normal"  # normal | uniform | binomial | constant
    mean: float = 0.0
    std: float = 1.0
    lower: float = -1.0
    upper: float = 1.0
    n_trials: int = 1
    prob: float = 0.5
    value: float = 0.0

    def sample(self, rng, shape, dtype=jnp.float32):
        k = self.kind.lower()
        if k in ("normal", "gaussian"):
            return self.mean + self.std * jax.random.normal(rng, shape, dtype)
        if k == "uniform":
            return jax.random.uniform(rng, shape, dtype, self.lower, self.upper)
        if k == "binomial":
            return jax.random.binomial(
                rng, self.n_trials, self.prob, shape
            ).astype(dtype)
        if k == "constant":
            return jnp.full(shape, self.value, dtype)
        raise ValueError(f"Unknown distribution kind '{self.kind}'")

    def to_dict(self):
        return {"kind": self.kind, "mean": self.mean, "std": self.std,
                "lower": self.lower, "upper": self.upper,
                "n_trials": self.n_trials, "prob": self.prob, "value": self.value}

    @staticmethod
    def from_dict(d):
        return Distribution(**d)


def init_weight(
    rng: jax.Array,
    shape: Sequence[int],
    scheme: str = WeightInit.XAVIER,
    fan_in: Optional[float] = None,
    fan_out: Optional[float] = None,
    distribution: Optional[Distribution] = None,
    dtype=jnp.float32,
) -> jax.Array:
    """Initialize a weight tensor.

    fan_in/fan_out default to shape[0]/shape[-1] for 2-D weights; conv layers
    pass receptive-field-scaled fans explicitly (as the reference does via
    `ConvolutionParamInitializer`).
    """
    shape = tuple(int(s) for s in shape)
    if fan_in is None:
        fan_in = float(shape[0]) if len(shape) > 1 else float(shape[0])
    if fan_out is None:
        fan_out = float(shape[-1]) if len(shape) > 1 else float(shape[0])
    s = str(scheme).lower()

    if s == WeightInit.DISTRIBUTION:
        if distribution is None:
            raise ValueError("WeightInit.DISTRIBUTION requires a Distribution")
        return distribution.sample(rng, shape, dtype)
    if s == WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if s == WeightInit.ONES:
        return jnp.ones(shape, dtype)
    if s == WeightInit.SIGMOID_UNIFORM:
        r = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, -r, r)
    if s == WeightInit.UNIFORM:
        # Reference WeightInitUtil: U(-a, a), a = 1/sqrt(fanIn)
        a = 1.0 / math.sqrt(fan_in)
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if s == WeightInit.XAVIER:
        # Gaussian, var = 2/(fanIn+fanOut)
        return jax.random.normal(rng, shape, dtype) * math.sqrt(2.0 / (fan_in + fan_out))
    if s == WeightInit.XAVIER_UNIFORM:
        r = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, -r, r)
    if s == WeightInit.XAVIER_FAN_IN:
        return jax.random.normal(rng, shape, dtype) / math.sqrt(fan_in)
    if s == WeightInit.XAVIER_LEGACY:
        # Legacy DL4J: N(0, 1/(fanIn+fanOut))
        return jax.random.normal(rng, shape, dtype) * math.sqrt(1.0 / (fan_in + fan_out))
    if s == WeightInit.RELU:
        # He: N(0, 2/fanIn)
        return jax.random.normal(rng, shape, dtype) * math.sqrt(2.0 / fan_in)
    if s == WeightInit.RELU_UNIFORM:
        r = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(rng, shape, dtype, -r, r)
    if s == WeightInit.NORMAL:
        return jax.random.normal(rng, shape, dtype) / math.sqrt(fan_in)
    if s == WeightInit.LECUN_NORMAL:
        return jax.random.normal(rng, shape, dtype) * math.sqrt(1.0 / fan_in)
    if s == WeightInit.LECUN_UNIFORM:
        r = math.sqrt(3.0 / fan_in)
        return jax.random.uniform(rng, shape, dtype, -r, r)
    if s == WeightInit.VAR_SCALING_NORMAL_FAN_AVG:
        return jax.random.normal(rng, shape, dtype) * math.sqrt(2.0 / (fan_in + fan_out))
    if s == WeightInit.IDENTITY:
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY init requires square 2-D shape")
        return jnp.eye(shape[0], dtype=dtype)
    raise ValueError(f"Unknown weight init '{scheme}'. Available: {WeightInit.ALL}")
