"""Transfer learning.

Parity with `nn/transferlearning/TransferLearning.java:34` (.Builder and
.GraphBuilder), `FineTuneConfiguration.java`, and `TransferLearningHelper.java`:
clone a trained net, freeze layers up to a boundary, remove/replace output
layers, override training hyperparameters, and featurize through the frozen
part. Frozen layers = `frozen=True` on the layer config — the jitted train
step skips their updates (optimizer masking), which is the TPU-native form of
the reference's FrozenLayer wrapper; XLA's DCE then prunes their backward
computation entirely.
"""
from __future__ import annotations

import copy as _copy
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional

import jax

from .conf import MultiLayerConfiguration, NeuralNetConfiguration
from .conf.base import LayerConf
from .multilayer import MultiLayerNetwork
from ..datasets.iterators import DataSet

__all__ = ["FineTuneConfiguration", "TransferLearning",
           "GraphTransferLearning", "TransferLearningHelper"]


def _tree_shapes_match(fresh, src) -> bool:
    """Same keys and leaf shapes — the transfer copy guard."""
    if not isinstance(fresh, dict) or not isinstance(src, dict):
        return jax.numpy.shape(fresh) == jax.numpy.shape(src)
    return (set(fresh) == set(src)
            and all(_tree_shapes_match(fresh[k], src[k]) for k in src))


def _copy_tree(t):
    """Deep-copy a param/state pytree into fresh device buffers."""
    return jax.tree_util.tree_map(
        lambda a: jax.numpy.array(a, copy=True), t)


@dataclass
class FineTuneConfiguration:
    """Hyperparameter overrides applied to all non-frozen layers
    (reference `FineTuneConfiguration.java`)."""

    updater: Optional[object] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    seed: Optional[int] = None
    weight_init: Optional[str] = None
    activation: Optional[str] = None

    class Builder:
        def __init__(self):
            self._c = FineTuneConfiguration()

        def updater(self, u, learning_rate=None):
            from . import updaters as _updaters
            self._c.updater = _updaters.get(u, learning_rate); return self

        def l1(self, v):
            self._c.l1 = float(v); return self

        def l2(self, v):
            self._c.l2 = float(v); return self

        def dropout(self, v):
            self._c.dropout = float(v); return self

        def seed(self, s):
            self._c.seed = int(s); return self

        def weight_init(self, w):
            self._c.weight_init = w; return self

        def activation(self, a):
            self._c.activation = a; return self

        def build(self):
            return self._c

    def apply_to_global(self, conf: NeuralNetConfiguration) -> NeuralNetConfiguration:
        kw = {}
        if self.updater is not None:
            kw["updater"] = self.updater
        if self.l1 is not None:
            kw["l1"] = self.l1
            kw["use_regularization"] = True
        if self.l2 is not None:
            kw["l2"] = self.l2
            kw["use_regularization"] = True
        if self.seed is not None:
            kw["seed"] = self.seed
        return replace(conf, **kw) if kw else conf

    def apply_to_layer(self, layer: LayerConf) -> LayerConf:
        kw = {}
        if self.updater is not None:
            kw["updater"] = self.updater
        if self.l1 is not None:
            kw["l1"] = self.l1
        if self.l2 is not None:
            kw["l2"] = self.l2
        if self.dropout is not None:
            kw["dropout"] = self.dropout
        return replace(layer, **kw) if kw else layer


class TransferLearning:
    """`TransferLearning.Builder(model)` fluent API."""

    class Builder:
        def __init__(self, model: MultiLayerNetwork):
            if model.params is None:
                raise ValueError("Model must be initialized/trained first")
            self._model = model
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._freeze_until: Optional[int] = None
            self._n_out_replacements: Dict[int, tuple] = {}
            self._remove_from: Optional[int] = None
            self._appended: List[LayerConf] = []

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_idx: int):
            """Freeze layers [0..layer_idx] (reference setFeatureExtractor)."""
            self._freeze_until = int(layer_idx)
            return self

        def nout_replace(self, layer_idx: int, n_out: int,
                         weight_init: Optional[str] = None):
            """Change a layer's n_out and reinit it (+ reinit next layer's
            n_in) — reference nOutReplace."""
            self._n_out_replacements[int(layer_idx)] = (int(n_out), weight_init)
            return self

        def remove_output_layer(self):
            return self.remove_layers_from_output(len(self._model.layers) - 1)

        def remove_layers_from_output(self, idx: int):
            self._remove_from = int(idx)
            return self

        def add_layer(self, layer: LayerConf):
            self._appended.append(layer)
            return self

        def build(self) -> MultiLayerNetwork:
            src = self._model
            layers = [replace(l) for l in src.layers]
            params = [dict(p) for p in src.params]
            reinit = set()

            if self._remove_from is not None:
                layers = layers[:self._remove_from]
                params = params[:self._remove_from]

            for idx, (n_out, w_init) in sorted(self._n_out_replacements.items()):
                if idx >= len(layers):
                    raise ValueError(f"nout_replace index {idx} out of range")
                kw = {"n_out": n_out}
                if w_init:
                    kw["weight_init"] = w_init
                layers[idx] = replace(layers[idx], **kw)
                reinit.add(idx)
                if idx + 1 < len(layers) and hasattr(layers[idx + 1], "n_in"):
                    layers[idx + 1] = replace(layers[idx + 1], n_in=None)
                    reinit.add(idx + 1)

            n_existing = len(layers)
            layers.extend(self._appended)
            params.extend({} for _ in self._appended)
            reinit.update(range(n_existing, len(layers)))

            if self._fine_tune is not None:
                layers = [l if l.frozen else self._fine_tune.apply_to_layer(l)
                          for l in layers]

            if self._freeze_until is not None:
                for i in range(min(self._freeze_until + 1, len(layers))):
                    layers[i] = replace(layers[i], frozen=True)

            g_conf = src.conf.conf
            if self._fine_tune is not None:
                g_conf = self._fine_tune.apply_to_global(g_conf)

            # re-run shape inference over the edited layer list
            from .conf import ListBuilder
            lb = ListBuilder(g_conf)
            for l in layers:
                lb.layer(l)
            if src.conf.input_type is not None:
                lb.set_input_type(src.conf.input_type)
            for i, pp in src.conf.preprocessors.items():
                if i < len(layers):
                    lb.input_pre_processor(i, pp)
            new_conf = lb.build()
            # ListBuilder re-resolves inheritance; keep frozen flags
            new_net = MultiLayerNetwork(new_conf)
            new_net.init()
            # copy kept params AND layer state (BN running mean/var — the
            # reference keeps global stats in the param table, so transfer
            # carries them; without this a transferred frozen feature
            # extractor produces wrong eval outputs until stats re-warm);
            # reinit'ed layers keep fresh values
            new_params = list(new_net.params)
            new_state = list(new_net.state)
            src_state = list(src.state)
            if self._remove_from is not None:
                src_state = src_state[:self._remove_from]
            for i in range(len(new_conf.layers)):
                if i < len(params) and i not in reinit and params[i]:
                    new_params[i] = _copy_tree(params[i])
                if (i < len(src_state) and i not in reinit and src_state[i]
                        and _tree_shapes_match(new_state[i], src_state[i])):
                    new_state[i] = _copy_tree(src_state[i])
            new_net.params = tuple(new_params)
            new_net.state = tuple(new_state)
            return new_net


class TransferLearningHelper:
    """Featurize through the frozen part once; train only the unfrozen tail
    (reference `TransferLearningHelper.java`)."""

    def __init__(self, model: MultiLayerNetwork,
                 frozen_until: Optional[int] = None):
        self.model = model
        if frozen_until is None:
            frozen_until = -1
            for i, l in enumerate(model.layers):
                if l.frozen:
                    frozen_until = i
        self.frozen_until = frozen_until

    def featurize(self, ds: DataSet) -> DataSet:
        """Run the frozen head once and return a DataSet of features for the
        trainable tail."""
        import jax.numpy as jnp
        import numpy as np
        x = jnp.asarray(ds.features)
        h, _, _, _ = self.model._forward(self.model.params, self.model.state,
                                         x, False, None,
                                         upto=self.frozen_until + 1)
        return DataSet(np.asarray(h), ds.labels, ds.features_mask,
                       ds.labels_mask)

    def unfrozen_graph(self) -> MultiLayerNetwork:
        """A network of only the unfrozen tail (shares param values)."""
        from .conf import ListBuilder
        tail_layers = self.model.layers[self.frozen_until + 1:]
        lb = ListBuilder(self.model.conf.conf)
        for l in tail_layers:
            lb.layer(replace(l))
        net = MultiLayerNetwork(lb.build())
        net.init()
        net.params = tuple(
            jax.tree_util.tree_map(lambda a: jax.numpy.array(a, copy=True), p)
            for p in self.model.params[self.frozen_until + 1:])
        return net

    def fit_featurized(self, ds: DataSet):
        """Train the tail on featurized data, writing params back."""
        tail = self.unfrozen_graph()
        tail.fit(ds)
        k = self.frozen_until + 1
        new_params = list(self.model.params)
        for i, p in enumerate(tail.params):
            new_params[k + i] = p
        self.model.params = tuple(new_params)
        return self.model


class GraphTransferLearning:
    """`TransferLearning.GraphBuilder` parity
    (`nn/transferlearning/TransferLearning.java` GraphBuilder inner class):
    freeze ancestor subgraphs (setFeatureExtractor), nOutReplace on named
    layers (downstream consumers re-inferred + re-initialized), remove /
    add vertices, change network outputs — then rebuild with shape
    inference and transfer every surviving parameter."""

    class GraphBuilder:
        def __init__(self, graph):
            if graph.params is None:
                raise ValueError("Graph must be initialized/trained first")
            self._graph = graph
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._freeze_roots: List[str] = []
            self._n_out_replacements: Dict[str, tuple] = {}
            self._removed: List[str] = []
            self._added: List[tuple] = []     # (name, layer_or_vertex, inputs)
            self._outputs: Optional[List[str]] = None

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, *vertex_names: str):
            """Freeze the named vertices and every ancestor (reference
            setFeatureExtractor: everything up to and including the named
            vertices becomes a frozen feature extractor)."""
            self._freeze_roots.extend(vertex_names)
            return self

        def nout_replace(self, vertex_name: str, n_out: int,
                         weight_init: Optional[str] = None):
            self._n_out_replacements[vertex_name] = (int(n_out), weight_init)
            return self

        def remove_vertex_and_connections(self, name: str):
            self._removed.append(name)
            return self

        def add_layer(self, name: str, layer: LayerConf, *inputs: str):
            self._added.append((name, layer, inputs))
            return self

        def add_vertex(self, name: str, vertex, *inputs: str):
            self._added.append((name, vertex, inputs))
            return self

        def set_outputs(self, *names: str):
            self._outputs = list(names)
            return self

        # -- internals -------------------------------------------------
        def _ancestors(self, conf, roots):
            out = set()
            stack = list(roots)
            while stack:
                n = stack.pop()
                if n in out or n not in conf.vertices:
                    continue
                out.add(n)
                stack.extend(i for i in conf.vertex_inputs.get(n, ())
                             if i in conf.vertices)
            return out

        def build(self):
            from .graph import ComputationGraph

            src = self._graph
            conf = src.conf
            g_conf = conf.conf
            if self._fine_tune is not None:
                g_conf = self._fine_tune.apply_to_global(g_conf)

            removed = set(self._removed)
            vertices, vertex_inputs = {}, {}
            shape_changed = []   # vertices whose OUTPUT width may change
            for n in conf.topological_order:
                if n not in conf.vertices or n in removed:
                    continue
                ins = [i for i in conf.vertex_inputs[n] if i not in removed]
                if len(ins) != len(conf.vertex_inputs[n]) and not ins:
                    raise ValueError(
                        f"removing {sorted(removed)} strands vertex '{n}'")
                if len(ins) != len(conf.vertex_inputs[n]):
                    # narrowed a multi-input vertex (e.g. Merge lost a
                    # branch): its output shape changes downstream
                    shape_changed.append(n)
                v = conf.vertices[n]
                vertices[n] = replace(v) if isinstance(v, LayerConf) else v
                vertex_inputs[n] = ins

            reinit = set()
            for name, (n_out, w_init) in self._n_out_replacements.items():
                if name not in vertices:
                    raise ValueError(f"nout_replace: no vertex '{name}'")
                kw = {"n_out": n_out}
                if w_init:
                    kw["weight_init"] = w_init
                vertices[name] = replace(vertices[name], **kw)
                reinit.add(name)
                shape_changed.append(name)

            # propagate shape changes FORWARD: a consumer layer re-infers
            # its n_in (and is re-initialized, stopping propagation — its
            # n_out is unchanged); non-layer vertices (Merge/ElementWise/
            # ...) transmit the change to their own consumers
            frontier = list(shape_changed)
            seen = set(frontier)
            while frontier:
                src_name = frontier.pop()
                for c, ins in vertex_inputs.items():
                    if src_name not in ins:
                        continue
                    if isinstance(vertices[c], LayerConf):
                        if hasattr(vertices[c], "n_in"):
                            vertices[c] = replace(vertices[c], n_in=None)
                        reinit.add(c)
                    elif c not in seen:
                        seen.add(c)
                        frontier.append(c)

            for name, v, ins in self._added:
                vertices[name] = v
                vertex_inputs[name] = list(ins)
                reinit.add(name)

            frozen = self._ancestors(
                type("C", (), {"vertices": vertices,
                               "vertex_inputs": vertex_inputs})(),
                self._freeze_roots)
            for n in list(vertices):
                v = vertices[n]
                if not isinstance(v, LayerConf):
                    continue
                if self._fine_tune is not None and n not in frozen:
                    vertices[n] = v = self._fine_tune.apply_to_layer(v)
                if n in frozen:
                    vertices[n] = replace(v, frozen=True)

            # rebuild with shape inference through the standard builder,
            # adding vertices in an order where inputs precede consumers
            from .conf.graph import GraphBuilder as _GB
            gb = _GB(g_conf)
            gb.add_inputs(*conf.network_inputs)
            pending = dict(vertices)
            placed = set(conf.network_inputs)
            while pending:
                progressed = False
                for n in list(pending):
                    if all(i in placed for i in vertex_inputs[n]):
                        v = pending.pop(n)
                        if isinstance(v, LayerConf):
                            gb.add_layer(n, v, *vertex_inputs[n])
                        else:
                            gb.add_vertex(n, v, *vertex_inputs[n])
                        placed.add(n)
                        progressed = True
                if not progressed:
                    raise ValueError(
                        f"cannot order vertices {sorted(pending)} — "
                        "dangling inputs after edits")
            outputs = self._outputs or [o for o in conf.network_outputs
                                        if o in vertices]
            if not outputs:
                raise ValueError("no network outputs remain; set_outputs()")
            gb.set_outputs(*outputs)
            if conf.input_types:
                gb.set_input_types(*conf.input_types)
            new_graph = ComputationGraph(gb.build())
            new_graph.init()
            # transfer surviving params AND layer state, SHAPE-CHECKED:
            # only copy when the fresh init's shapes match the source
            # exactly (belt and braces on top of the forward shape
            # propagation above). State carries BN running mean/var — the
            # reference keeps global stats in the param table, so a
            # transferred frozen feature extractor must keep them or eval/
            # featurize outputs are wrong until the stats re-warm
            new_params = dict(new_graph.params)
            new_state = dict(new_graph.state)
            for n, p in src.params.items():
                if n not in new_params or n in reinit or not p:
                    continue
                if _tree_shapes_match(new_params[n], p):
                    new_params[n] = _copy_tree(p)
            for n, s in src.state.items():
                if n not in new_state or n in reinit or not s:
                    continue
                if _tree_shapes_match(new_state[n], s):
                    new_state[n] = _copy_tree(s)
            new_graph.params = new_params
            new_graph.state = new_state
            return new_graph
