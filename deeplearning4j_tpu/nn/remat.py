"""Selective rematerialization policies (ISSUE 18).

`remat` (NeuralNetConfiguration / LayerConf) decides WHERE checkpoint
boundaries go (None / "layer" / "blocks" / "full"; the 1F1B stage body
always checkpoints its vmapped stage). `remat_policy` decides WHAT each
boundary saves — a named `jax.checkpoint_policies` entry threaded
through every `jax.checkpoint(...)` site:

  name          policy                                 saves
  ----          ------                                 -----
  None          (jax default)                          nothing: recompute
                                                       everything from the
                                                       boundary inputs
  "nothing"     nothing_saveable                       same, stated
                                                       explicitly
  "dots"        checkpoint_dots                        matmul/einsum
                                                       outputs (recompute
                                                       only the cheap
                                                       elementwise tail)
  "dots_no_batch"  checkpoint_dots_with_no_batch_dims  matmuls WITHOUT a
                                                       batch dim (weight-
                                                       shaped residuals
                                                       only — activations
                                                       still recomputed)
  "everything"  everything_saveable                    all residuals (the
                                                       no-remat memory
                                                       profile inside a
                                                       checkpoint wrapper)

All policies are numerics no-ops: they trade activation memory for
recompute FLOPs without touching the math (asserted to f32-ulp
equivalence in tests/test_precision_remat.py).

`saved_bytes` is the static activation-byte accounting — what one
checkpoint boundary actually saves for a concrete call — published
through `_pp_info` the way `_ZeroPlan` publishes its byte accounting,
and surfaced as the bench's activation-bytes column.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["REMAT_POLICIES", "resolve_policy", "saved_bytes"]

#: name -> jax.checkpoint policy callable (None = jax's save-nothing
#: default). Names are config-file citizens: serialized in the model
#: JSON and recorded in FitCheckpointer context.
REMAT_POLICIES = {
    "nothing": jax.checkpoint_policies.nothing_saveable,
    "dots": jax.checkpoint_policies.checkpoint_dots,
    "dots_no_batch":
        jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    "everything": jax.checkpoint_policies.everything_saveable,
}


def resolve_policy(name: Optional[str]):
    """Policy name -> `jax.checkpoint(policy=...)` callable (None stays
    None: jax's default save-nothing behaviour). Raises with the valid
    names on a typo — a silently-ignored policy would quietly change the
    memory profile the user asked for."""
    if name is None:
        return None
    try:
        return REMAT_POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown remat_policy '{name}'. Valid policies: "
            f"{', '.join(sorted(REMAT_POLICIES))} (or None for jax's "
            "save-nothing default)") from None


def saved_bytes(fn: Callable, *args, policy: Optional[str] = None) -> int:
    """Static activation-byte accounting: total bytes of INTERMEDIATE
    residuals the checkpointed `fn(*args)` saves for the backward pass
    under the named policy (0 = recompute everything from the boundary
    inputs). Residuals that are just the boundary's own arguments are
    excluded — they are alive either way; the accounting counts only
    what the policy ADDS. Uses `jax.ad_checkpoint.saved_residuals` on
    concrete zero-filled arguments — a trace-time measurement, no
    training step involved."""
    try:
        from jax.ad_checkpoint import saved_residuals
    except ImportError:      # not re-exported publicly on jax 0.4.x
        from jax._src.ad_checkpoint import saved_residuals

    def concrete(a):
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            return jnp.zeros(a.shape, a.dtype)
        return a

    args = jax.tree_util.tree_map(concrete, args)
    ck = jax.checkpoint(fn, policy=resolve_policy(policy))
    total = 0
    for val, source in saved_residuals(ck, *args):
        if source.startswith("from the argument"):
            continue
        aval = getattr(val, "aval", val)
        total += int(np.prod(aval.shape) if aval.shape else 1) \
            * jnp.dtype(aval.dtype).itemsize
    return total
