"""Observability stack (L9).

Capability parity with the reference's stats pipeline
(`ui/stats/BaseStatsListener.java:43` → SBE-encoded `StatsReport` →
`StatsStorage` (`deeplearning4j-core/.../api/storage/StatsStorage.java`) →
Play web UI (`PlayUIServer.java:53`, `module/train/TrainModule.java:53`)).

TPU-native shape: the listener snapshots score/param/update statistics from
the pytree between jitted steps (one device→host sync per report), reports are
plain dicts serialized as JSON-lines (replacing the SBE binary codec), storage
is pluggable (in-memory / file), and the dashboard is a dependency-free
stdlib http.server rendering overview/model/system pages.
"""
from .stats import StatsListener, StatsReport
from .storage import (FileStatsStorage, InMemoryStatsStorage,
                      SqliteStatsStorage, StatsStorage,
                      StatsStorageEvent, StatsStorageListener)
from .server import UIServer
from .legacy_listeners import (WebReporter, RemoteFlowIterationListener,
                               RemoteHistogramIterationListener)

__all__ = [
    "WebReporter", "RemoteFlowIterationListener",
    "RemoteHistogramIterationListener", "SqliteStatsStorage",
    "StatsListener", "StatsReport", "StatsStorage", "InMemoryStatsStorage",
    "FileStatsStorage", "StatsStorageEvent", "StatsStorageListener",
    "UIServer",
]
