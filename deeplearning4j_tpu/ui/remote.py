"""Remote stats routing — multi-host observability.

Reference analogs: `RemoteUIStatsStorageRouter`
(`deeplearning4j-core/.../impl/RemoteUIStatsStorageRouter.java` — HTTP
POSTs stats to a remote UI) and the receiving `RemoteReceiverModule`
(`deeplearning4j-play/.../module/remote/RemoteReceiverModule.java`). In a
TPU pod each worker host attaches this router to its StatsListener and the
coordinator (or a laptop) runs `UIServer(...).enable_remote_listener()`;
training stats flow over plain HTTP, off the ICI fabric.

Includes the reference router's bounded retry queue: transient connection
failures buffer updates and retry on the next put rather than dropping or
blocking training.
"""
from __future__ import annotations

import json
import logging
import threading
import urllib.request
from collections import deque
from typing import Deque, Dict, Tuple

from .storage import StatsStorage

log = logging.getLogger("deeplearning4j_tpu")

__all__ = ["RemoteUIStatsStorageRouter"]


class RemoteUIStatsStorageRouter(StatsStorage):
    """StatsStorage front half only: put_update POSTs to the remote UI.
    Query methods are unsupported (the storage lives on the receiver)."""

    def __init__(self, url: str, retry_queue_size: int = 512,
                 timeout: float = 5.0):
        self.url = url.rstrip("/") + "/remote"
        self.timeout = timeout
        self._retry: Deque[Dict] = deque(maxlen=retry_queue_size)
        self._lock = threading.Lock()
        self._drain_lock = threading.Lock()  # one drainer at a time

    def _post(self, payload: Dict) -> bool:
        req = urllib.request.Request(
            self.url, json.dumps(payload).encode(),
            {"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return 200 <= r.status < 300
        except Exception as e:
            log.debug("remote stats post failed: %s", e)
            return False

    def put_update(self, session_id, type_id, worker_id, timestamp, report):
        payload = {"session": session_id, "type": type_id,
                   "worker": worker_id, "ts": timestamp, "report": report}
        # enqueue-then-drain-from-head: updates always deliver in order
        # (the dashboard's 'latest' stays monotonic), and a black-holed UI
        # host costs the training loop ONE timeout per iteration, not
        # (pending+1) timeouts — the drain stops at the first failure.
        with self._lock:
            self._retry.append(payload)
        self._try_drain()

    def _try_drain(self):
        """Drain the retry queue from the head, serialized by a TRY-lock
        (two drainers could read the same head and POST it twice) — a
        caller never BLOCKS on the drain lock: stalling a training
        thread behind someone else's slow POST for a full HTTP timeout
        is exactly what graftlint's blocking-call-under-lock flags. A
        failed try-acquire means an active drainer exists; it delivers
        late enqueues via its inner loop, and the post-release re-check
        below closes the remaining window (an append landing between
        the drainer's final empty-check and its release)."""
        while True:
            if not self._drain_lock.acquire(blocking=False):
                return
            try:
                while True:
                    with self._lock:
                        if not self._retry:
                            break
                        head = self._retry[0]
                    if not self._post(head):
                        return      # head retried on the next cycle
                    with self._lock:
                        if self._retry and self._retry[0] is head:
                            self._retry.popleft()
            finally:
                self._drain_lock.release()
            # re-check AFTER releasing: a payload enqueued during the
            # final empty-check window must not strand until the next
            # put_update (it may be the run's last stats report)
            with self._lock:
                if not self._retry:
                    return

    @property
    def pending(self) -> int:
        return len(self._retry)

    # query half lives on the receiver
    def list_session_ids(self):
        raise NotImplementedError("router is write-only; query the UI host")

    def list_type_ids(self, session_id):
        raise NotImplementedError("router is write-only; query the UI host")

    def list_worker_ids(self, session_id, type_id):
        raise NotImplementedError("router is write-only; query the UI host")

    def get_all_updates(self, session_id, type_id, worker_id):
        raise NotImplementedError("router is write-only; query the UI host")
