"""Legacy remote iteration listeners — the WebReporter tier.

Reference: `deeplearning4j-ui-remote-iterationlisteners/.../ui/
WebReporter.java` (static POST-to-UI-host rate-limited reporter) with
`flow/RemoteFlowIterationListener.java`,
`weights/RemoteHistogramIterationListener.java` and
`weights/RemoteConvolutionalIterationListener.java` — per-iteration
listeners that push a rendered payload directly to a remote endpoint
instead of going through a StatsStorage.

The modern path here (as in the reference's successor UI) is
`StatsListener` -> `RemoteUIStatsStorageRouter` -> `/remote`; these
classes keep the legacy capability: direct per-iteration POST of a typed
payload (flow topology snapshot / parameter histograms / conv
activations) to an arbitrary HTTP endpoint, with WebReporter's
queue-and-rate-limit behavior.
"""
from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from collections import deque
from typing import Dict, Optional

import numpy as np

from ..optimize.listeners import TrainingListener

log = logging.getLogger("deeplearning4j_tpu")

__all__ = ["WebReporter", "RemoteFlowIterationListener",
           "RemoteHistogramIterationListener"]


class WebReporter:
    """POST JSON payloads to a UI host from a BACKGROUND worker thread
    with a bounded queue (WebReporter.java's LinkedBlockingQueue + posting
    thread): a slow or black-holed UI host never stalls the training loop
    — `report()` only enqueues. Rate-limited to at most one post per
    `min_interval` seconds; failed heads are retried on the next cycle."""

    def __init__(self, url: str, timeout: float = 5.0,
                 queue_size: int = 128, min_interval: float = 0.0):
        self.url = url
        self.timeout = timeout
        self.min_interval = float(min_interval)
        self._queue = deque(maxlen=queue_size)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="dl4jtpu-webreporter")
        self._worker.start()

    def _post(self, payload: Dict) -> bool:
        req = urllib.request.Request(
            self.url, json.dumps(payload).encode(),
            {"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return 200 <= r.status < 300
        except Exception as e:
            log.debug("legacy web report failed: %s", e)
            return False

    def _run(self):
        while not self._stop:
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            while not self._stop:
                with self._lock:
                    head = self._queue[0] if self._queue else None
                if head is None:
                    break
                if self.min_interval:
                    time.sleep(self.min_interval)
                if not self._post(head):
                    break  # retry the head on the next wake/poll cycle
                with self._lock:
                    if self._queue and self._queue[0] is head:
                        self._queue.popleft()

    def report(self, payload: Dict):
        with self._lock:
            self._queue.append(payload)
        self._wake.set()

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait for the queue to drain (tests / shutdown)."""
        deadline = time.time() + timeout
        self._wake.set()
        while time.time() < deadline:
            with self._lock:
                if not self._queue:
                    return True
            time.sleep(0.02)
        return False

    def close(self):
        self._stop = True
        self._wake.set()

    @property
    def pending(self) -> int:
        return len(self._queue)


class RemoteFlowIterationListener(TrainingListener):
    """Per-iteration network-topology + score snapshot POSTed to a remote
    endpoint (RemoteFlowIterationListener.java capability)."""

    def __init__(self, url: str, frequency: int = 1,
                 reporter: Optional[WebReporter] = None):
        self.reporter = reporter or WebReporter(url)
        self.frequency = max(1, int(frequency))

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency != 0:
            return
        from .stats import model_topology

        self.reporter.report({
            "type": "flow",
            "iteration": int(iteration),
            "score": float(model.score()),
            "model": model_topology(model),
        })


class RemoteHistogramIterationListener(TrainingListener):
    """Per-iteration parameter histograms POSTed to a remote endpoint
    (RemoteHistogramIterationListener.java capability)."""

    collects_param_stats = True

    def __init__(self, url: str, frequency: int = 1, bins: int = 20,
                 reporter: Optional[WebReporter] = None):
        self.reporter = reporter or WebReporter(url)
        self.frequency = max(1, int(frequency))
        self.bins = int(bins)

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency != 0:
            return
        from .stats import _flatten_params

        hists = {}
        for k, v in _flatten_params(model).items():
            counts, edges = np.histogram(v.ravel(), bins=self.bins)
            hists[k] = {"counts": counts.tolist(),
                        "min": float(edges[0]), "max": float(edges[-1])}
        self.reporter.report({
            "type": "histogram",
            "iteration": int(iteration),
            "score": float(model.score()),
            "histograms": hists,
        })
