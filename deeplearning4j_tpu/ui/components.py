"""Chart/component DSL (deeplearning4j-ui-components analog).

Reference (SURVEY.md §2.9): `ui/components/{chart,component,table,text,
decorator}/` — a Java chart DSL serialized to JSON and rendered by bundled
TypeScript. Here the DSL serializes to the same kind of JSON AND renders
itself to dependency-free inline SVG/HTML (no TS toolchain; works offline),
which is also what the training dashboard embeds.
"""
from __future__ import annotations

import html as _html
import json
from typing import List, Optional, Sequence, Tuple

__all__ = ["StyleChart", "ChartLine", "ChartScatter", "ChartHistogram",
           "ComponentTable", "ComponentText", "render_page"]


class StyleChart:
    """Subset of the reference's StyleChart: size + series colors."""

    _PALETTE = ["#1971c2", "#e8590c", "#2f9e44", "#9c36b5", "#e03131",
                "#0c8599"]

    def __init__(self, width: int = 480, height: int = 280,
                 colors: Optional[Sequence[str]] = None):
        self.width = int(width)
        self.height = int(height)
        self.colors = list(colors) if colors else list(self._PALETTE)

    def to_dict(self):
        return {"width": self.width, "height": self.height,
                "colors": self.colors}


class _Component:
    kind = "component"

    def to_dict(self) -> dict:
        raise NotImplementedError

    def to_json(self) -> str:
        return json.dumps({"type": self.kind, **self.to_dict()})

    def render_svg(self) -> str:
        raise NotImplementedError


def _axes(style: StyleChart, x_min, x_max, y_min, y_max, title):
    w, h = style.width, style.height
    parts = [f'<svg width="{w}" height="{h}" '
             f'xmlns="http://www.w3.org/2000/svg" '
             f'style="background:#fff;border:1px solid #ddd">']
    if title:
        parts.append(f'<text x="{w // 2}" y="14" text-anchor="middle" '
                     f'font-size="12">{_html.escape(title)}</text>')
    for frac, val in ((0.0, y_max), (1.0, y_min)):
        y = 20 + frac * (h - 40)
        parts.append(f'<text x="4" y="{y:.0f}" font-size="9">'
                     f'{val:.3g}</text>')
    for frac, val in ((0.0, x_min), (1.0, x_max)):
        x = 35 + frac * (w - 50)
        parts.append(f'<text x="{x:.0f}" y="{h - 4}" font-size="9">'
                     f'{val:.3g}</text>')
    return parts


def _scale(xs, ys, style: StyleChart, x_rng, y_rng):
    w, h = style.width, style.height
    (x0, x1), (y0, y1) = x_rng, y_rng
    sx = (x1 - x0) or 1.0
    sy = (y1 - y0) or 1.0
    px = [35 + (x - x0) / sx * (w - 50) for x in xs]
    py = [20 + (1 - (y - y0) / sy) * (h - 40) for y in ys]
    return px, py


class ChartLine(_Component):
    """Multi-series line chart (`chart/ChartLine.java`)."""

    kind = "chart-line"

    def __init__(self, title: str = "", style: Optional[StyleChart] = None):
        self.title = title
        self.style = style or StyleChart()
        self.series: List[Tuple[str, List[float], List[float]]] = []

    def add_series(self, name: str, x: Sequence[float],
                   y: Sequence[float]) -> "ChartLine":
        self.series.append((name, [float(v) for v in x],
                            [float(v) for v in y]))
        return self

    def to_dict(self):
        return {"title": self.title, "style": self.style.to_dict(),
                "series": [{"name": n, "x": x, "y": y}
                           for n, x, y in self.series]}

    def _ranges(self):
        xs = [v for _, x, _ in self.series for v in x] or [0.0, 1.0]
        ys = [v for _, _, y in self.series for v in y] or [0.0, 1.0]
        return (min(xs), max(xs)), (min(ys), max(ys))

    def render_svg(self) -> str:
        x_rng, y_rng = self._ranges()
        parts = _axes(self.style, *x_rng, *y_rng, self.title)
        for i, (name, x, y) in enumerate(self.series):
            color = self.style.colors[i % len(self.style.colors)]
            px, py = _scale(x, y, self.style, x_rng, y_rng)
            pts = " ".join(f"{a:.1f},{b:.1f}" for a, b in zip(px, py))
            parts.append(f'<polyline fill="none" stroke="{color}" '
                         f'stroke-width="1.5" points="{pts}"/>')
            parts.append(f'<text x="{self.style.width - 8}" '
                         f'y="{20 + 12 * i}" text-anchor="end" '
                         f'font-size="10" fill="{color}">'
                         f'{_html.escape(name)}</text>')
        parts.append("</svg>")
        return "".join(parts)


class ChartScatter(ChartLine):
    """Scatter chart (`chart/ChartScatter.java`)."""

    kind = "chart-scatter"

    def render_svg(self) -> str:
        x_rng, y_rng = self._ranges()
        parts = _axes(self.style, *x_rng, *y_rng, self.title)
        for i, (name, x, y) in enumerate(self.series):
            color = self.style.colors[i % len(self.style.colors)]
            px, py = _scale(x, y, self.style, x_rng, y_rng)
            for a, b in zip(px, py):
                parts.append(f'<circle cx="{a:.1f}" cy="{b:.1f}" r="2.2" '
                             f'fill="{color}" fill-opacity="0.7"/>')
            parts.append(f'<text x="{self.style.width - 8}" '
                         f'y="{20 + 12 * i}" text-anchor="end" '
                         f'font-size="10" fill="{color}">'
                         f'{_html.escape(name)}</text>')
        parts.append("</svg>")
        return "".join(parts)


class ChartHistogram(_Component):
    """Histogram chart (`chart/ChartHistogram.java`): explicit bin edges."""

    kind = "chart-histogram"

    def __init__(self, title: str = "", style: Optional[StyleChart] = None):
        self.title = title
        self.style = style or StyleChart()
        self.bins: List[Tuple[float, float, float]] = []  # (lo, hi, count)

    def add_bin(self, low: float, high: float,
                count: float) -> "ChartHistogram":
        self.bins.append((float(low), float(high), float(count)))
        return self

    def to_dict(self):
        return {"title": self.title, "style": self.style.to_dict(),
                "bins": [{"low": lo, "high": hi, "count": c}
                         for lo, hi, c in self.bins]}

    def render_svg(self) -> str:
        if not self.bins:
            return "<svg/>"
        x0 = min(lo for lo, _, _ in self.bins)
        x1 = max(hi for _, hi, _ in self.bins)
        y1 = max(c for _, _, c in self.bins) or 1.0
        parts = _axes(self.style, x0, x1, 0.0, y1, self.title)
        w, h = self.style.width, self.style.height
        sx = (x1 - x0) or 1.0
        for lo, hi, c in self.bins:
            px = 35 + (lo - x0) / sx * (w - 50)
            pw = max(1.0, (hi - lo) / sx * (w - 50) - 1)
            ph = c / y1 * (h - 40)
            parts.append(
                f'<rect x="{px:.1f}" y="{h - 20 - ph:.1f}" '
                f'width="{pw:.1f}" height="{ph:.1f}" '
                f'fill="{self.style.colors[0]}" fill-opacity="0.8"/>')
        parts.append("</svg>")
        return "".join(parts)


class ComponentTable(_Component):
    """Simple table (`table/ComponentTable.java`)."""

    kind = "component-table"

    def __init__(self, header: Sequence[str],
                 rows: Sequence[Sequence[object]]):
        self.header = [str(hh) for hh in header]
        self.rows = [[str(c) for c in row] for row in rows]

    def to_dict(self):
        return {"header": self.header, "rows": self.rows}

    def render_svg(self) -> str:   # tables render as HTML
        head = "".join(f"<th>{_html.escape(hh)}</th>"
                       for hh in self.header)
        body = "".join(
            "<tr>" + "".join(f"<td>{_html.escape(c)}</td>" for c in row)
            + "</tr>" for row in self.rows)
        return (f'<table border="1" cellspacing="0" cellpadding="4">'
                f"<tr>{head}</tr>{body}</table>")


class ComponentText(_Component):
    kind = "component-text"

    def __init__(self, text: str):
        self.text = text

    def to_dict(self):
        return {"text": self.text}

    def render_svg(self) -> str:
        return f"<p>{_html.escape(self.text)}</p>"


def render_page(title: str, components: Sequence[_Component]) -> str:
    """Standalone HTML page embedding the rendered components (the role of
    the reference's TS renderer bundle)."""
    body = "<br/>".join(c.render_svg() for c in components)
    t = _html.escape(title)
    return (f"<!DOCTYPE html><html><head><title>{t}</title></head>"
            f"<body style='font-family:sans-serif'><h2>{t}</h2>"
            f"{body}</body></html>")
