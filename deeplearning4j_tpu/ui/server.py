"""UIServer: training dashboard over a StatsStorage.

Parity with `ui/play/PlayUIServer.java:53` + `ui/api/UIServer.java:14`
(singleton `get_instance()`, `attach(storage)`) and the TrainModule pages
(overview / model / system, `module/train/TrainModule.java:53`). The
reference embeds a Play server with Scala views + TS charts; here it's a
dependency-free stdlib ThreadingHTTPServer serving one HTML page that polls
JSON endpoints and renders inline-SVG charts (works offline, no CDN).
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from .storage import StatsStorage

__all__ = ["UIServer"]

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training UI</title><style>
body{font-family:sans-serif;margin:20px;background:#fafafa}
h1{font-size:18px} h2{font-size:15px;margin:18px 0 6px}
.tab{display:inline-block;margin-right:12px;cursor:pointer;color:#06c}
.tab.active{font-weight:bold;color:#000}
table{border-collapse:collapse;font-size:12px}
td,th{border:1px solid #ccc;padding:3px 8px;text-align:right}
th{background:#eee} svg{background:#fff;border:1px solid #ddd}
#meta{color:#666;font-size:12px}
</style></head><body>
<h1>deeplearning4j_tpu &mdash; training</h1>
<div id="meta"></div>
<div><span class="tab active" data-p="overview">Overview</span>
<span class="tab" data-p="model">Model</span>
<span class="tab" data-p="flow">Flow</span>
<span class="tab" data-p="histograms">Histograms</span>
<span class="tab" data-p="tsne">t-SNE</span>
<span class="tab" data-p="system">System</span></div>
<div id="content"></div>
<script>
let page='overview';
document.querySelectorAll('.tab').forEach(t=>t.onclick=()=>{
  document.querySelectorAll('.tab').forEach(x=>x.classList.remove('active'));
  t.classList.add('active'); page=t.dataset.p; refresh();});
function bars(hist,w,h,color){
  if(!hist||!hist.counts||!hist.counts.length)
    return '<svg width="'+w+'" height="'+h+'"></svg>';
  const n=hist.counts.length, mx=Math.max(...hist.counts)||1;
  let s='<svg width="'+w+'" height="'+h+'">';
  const bw=(w-40)/n;
  hist.counts.forEach((c,i)=>{
    const bh=c/mx*(h-24);
    s+='<rect x="'+(20+i*bw)+'" y="'+(h-12-bh)+'" width="'+Math.max(1,bw-1)+
      '" height="'+bh+'" fill="'+color+'" fill-opacity="0.85"/>';});
  s+='<text x="4" y="'+(h-2)+'" font-size="9">'+hist.min.toPrecision(3)+
    '</text><text x="'+(w-4)+'" y="'+(h-2)+'" text-anchor="end" font-size="9">'+
    hist.max.toPrecision(3)+'</text></svg>';
  return s;
}
function line(xs,ys,w,h,color){
  if(ys.length<2) return '<svg width="'+w+'" height="'+h+'"></svg>';
  const mn=Math.min(...ys), mx=Math.max(...ys), sp=(mx-mn)||1;
  const pts=ys.map((y,i)=>((i/(ys.length-1))*(w-20)+10)+','+
    (h-10-((y-mn)/sp)*(h-20))).join(' ');
  return '<svg width="'+w+'" height="'+h+'"><polyline fill="none" stroke="'+
    color+'" stroke-width="1.5" points="'+pts+'"/>'+
    '<text x="4" y="12" font-size="10">'+mx.toPrecision(4)+'</text>'+
    '<text x="4" y="'+(h-2)+'" font-size="10">'+mn.toPrecision(4)+'</text></svg>';
}
function flow(model,params){
  // FlowListenerModule analog: vertices laid out by topological depth,
  // edges as lines, per-vertex param counts + latest param stdev
  if(!model||!model.length) return '<p>no model info in this session</p>';
  const depth={}, rows={}, pos={};
  model.forEach(v=>{
    depth[v.name]=v.inputs.length?
      1+Math.max(...v.inputs.map(i=>depth[i]??0)):0;});
  model.forEach(v=>{
    const d=depth[v.name]; rows[d]=(rows[d]??0);
    pos[v.name]=[d,rows[d]]; rows[d]++;});
  const BW=140,BH=40,GX=40,GY=14;
  const W=(Math.max(...Object.values(depth))+1)*(BW+GX)+20;
  const H=(Math.max(...Object.values(rows))+0)*(BH+GY)+20;
  let s='<svg width="'+W+'" height="'+H+'">';
  const xy=n=>{const p=pos[n];
    return [10+p[0]*(BW+GX), 10+p[1]*(BH+GY)];};
  model.forEach(v=>v.inputs.forEach(i=>{
    if(!(i in pos)) return;
    const a=xy(i), b=xy(v.name);
    s+='<line x1="'+(a[0]+BW)+'" y1="'+(a[1]+BH/2)+'" x2="'+b[0]+
      '" y2="'+(b[1]+BH/2)+'" stroke="#999"/>';}));
  model.forEach(v=>{
    const p=xy(v.name);
    const st=Object.entries(params).find(([k,_])=>k.startsWith(v.name+'/'));
    s+='<rect x="'+p[0]+'" y="'+p[1]+'" width="'+BW+'" height="'+BH+
      '" rx="4" fill="'+(v.type=='Input'?'#dde':'#fff')+
      '" stroke="#36c"/>'+
      '<text x="'+(p[0]+5)+'" y="'+(p[1]+14)+'" font-size="11" '+
      'font-weight="bold">'+v.name+'</text>'+
      '<text x="'+(p[0]+5)+'" y="'+(p[1]+27)+'" font-size="9">'+v.type+
      ' · '+v.n_params+'p'+(st?' · σ '+st[1].stdev.toPrecision(2):'')+
      '</text>';});
  return s+'</svg>';
}
async function refresh(){
  const d=await (await fetch('/train/data.json')).json();
  document.getElementById('meta').textContent=
    'session '+d.session+' · '+d.iterations.length+' reports · last score '+
    (d.scores.at(-1)??'-');
  let html='';
  if(page=='overview'){
    html+='<h2>Score vs iteration</h2>'+line(d.iterations,d.scores,640,220,'#c33');
    if(d.samples_per_sec.length)
      html+='<h2>samples/sec</h2>'+line(d.iterations,d.samples_per_sec,640,140,'#36c');
  } else if(page=='model'){
    html+='<h2>Parameters (latest)</h2><table><tr><th>param</th><th>mean</th>'+
      '<th>stdev</th><th>min</th><th>max</th><th>update stdev</th></tr>';
    for(const [k,v] of Object.entries(d.params))
      html+='<tr><td style="text-align:left">'+k+'</td><td>'+v.mean.toPrecision(4)+
        '</td><td>'+v.stdev.toPrecision(4)+'</td><td>'+v.min.toPrecision(4)+
        '</td><td>'+v.max.toPrecision(4)+'</td><td>'+
        (d.updates[k]?d.updates[k].stdev.toPrecision(4):'-')+'</td></tr>';
    html+='</table>';
    html+='<h2>Mean parameter stdev vs iteration</h2>'+
      line(d.iterations,d.param_stdev,640,140,'#393');
  } else if(page=='flow'){
    html+='<h2>Network structure</h2>'+flow(d.model,d.params);
  } else if(page=='tsne'){
    const t=await (await fetch('/tsne/data.json')).json();
    if(!t.points||!t.points.length){
      html+='<p>no t-SNE coordinates attached '+
        '(UIServer.attach_tsne(coords, labels))</p>';
    } else {
      const xs=t.points.map(p=>p[0]), ys=t.points.map(p=>p[1]);
      const mnx=Math.min(...xs), mxx=Math.max(...xs),
            mny=Math.min(...ys), mxy=Math.max(...ys);
      const W=640,H=480,pal=['#c33','#36c','#393','#939','#c93','#399',
                             '#663','#636','#366','#933'];
      const cls=[...new Set(t.labels??[])];
      let s='<svg width="'+W+'" height="'+H+'">';
      t.points.forEach((p,i)=>{
        const x=10+(p[0]-mnx)/((mxx-mnx)||1)*(W-20);
        const y=10+(p[1]-mny)/((mxy-mny)||1)*(H-20);
        const c=t.labels?pal[cls.indexOf(t.labels[i])%pal.length]:'#36c';
        s+='<circle cx="'+x+'" cy="'+y+'" r="3" fill="'+c+
          '" fill-opacity="0.7"><title>'+(t.labels?t.labels[i]:i)+
          '</title></circle>';});
      html+='<h2>t-SNE embedding ('+t.points.length+' points)</h2>'+
        s+'</svg>';
      if(cls.length)
        html+='<p>'+cls.map((c,i)=>'<span style="color:'+
          pal[i%pal.length]+'">&#9679; '+c+'</span>').join(' &nbsp; ')+
          '</p>';
    }
  } else if(page=='histograms'){
    for(const [k,v] of Object.entries(d.params)){
      html+='<h2>'+k+'</h2>'+bars(v.histogram,320,110,'#36c');
      if(d.updates[k])
        html+=' '+bars(d.updates[k].histogram,320,110,'#c63');
    }
    if(!Object.keys(d.params).length)
      html+='<p>no parameter histograms collected '+
        '(StatsListener(collect_histograms=True))</p>';
  } else {
    html+='<h2>Host RSS (MB)</h2>'+line(d.iterations,d.rss_mb,640,140,'#939');
  }
  document.getElementById('content').innerHTML=html;
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpu-ui"

    def log_message(self, *a):  # quiet
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        ui: "UIServer" = self.server.ui  # type: ignore[attr-defined]
        url = urlparse(self.path)
        if url.path in ("/", "/train", "/train/overview"):
            body = _PAGE.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if url.path == "/train/sessions.json":
            self._json(ui.sessions())
            return
        if url.path == "/train/data.json":
            q = parse_qs(url.query)
            session = q.get("session", [None])[0]
            self._json(ui.train_data(session))
            return
        if url.path == "/tsne/data.json":
            self._json(ui.tsne_data())
            return
        self._json({"error": "not found"}, 404)

    def do_POST(self):
        """Remote stats ingestion (`RemoteReceiverModule` analog): workers
        POST updates from `RemoteUIStatsStorageRouter`."""
        ui: "UIServer" = self.server.ui  # type: ignore[attr-defined]
        if urlparse(self.path).path != "/remote":
            self._json({"error": "not found"}, 404)
            return
        if ui.remote_storage is None:
            self._json({"error": "remote listener not enabled"}, 403)
            return
        try:
            n = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(n) or b"{}")
            ui.remote_storage.put_update(
                body["session"], body.get("type", "remote"),
                body.get("worker", "0"), float(body.get("ts", 0.0)),
                body.get("report", {}))
            self._json({"status": "ok"})
        except Exception as e:
            self._json({"error": f"{type(e).__name__}: {e}"}, 400)


class UIServer:
    """Singleton dashboard server (`UIServer.getInstance()` in the
    reference). attach() storages; start() binds the port."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000, host: str = "127.0.0.1"):
        self.port = port
        self.host = host  # bind 0.0.0.0 to receive remote worker stats
        self._storages: List[StatsStorage] = []
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.remote_storage: Optional[StatsStorage] = None

    def attach_tsne(self, coords, labels=None) -> "UIServer":
        """Attach 2-D embedding coordinates for the t-SNE tab (reference
        `module/tsne/TsneModule.java`: uploaded coordinate files rendered
        as a scatter). `coords`: [N, 2] array-like; `labels`: optional N
        strings for coloring/tooltips."""
        import numpy as np

        c = np.asarray(coords, dtype=float)
        if c.ndim != 2 or c.shape[1] < 2:
            raise ValueError("coords must be [N, >=2]")
        self._tsne = {"points": c[:, :2].tolist(),
                      "labels": (None if labels is None
                                 else [str(l) for l in labels])}
        return self

    def tsne_data(self) -> dict:
        return getattr(self, "_tsne", {"points": [], "labels": None})

    def enable_remote_listener(self, storage: Optional[StatsStorage] = None
                               ) -> "UIServer":
        """Accept POSTed stats from remote workers at /remote (reference
        RemoteReceiverModule) into `storage` (default: a fresh in-memory
        storage), which is also attached to the dashboard."""
        from .storage import InMemoryStatsStorage

        self.remote_storage = storage or InMemoryStatsStorage()
        return self.attach(self.remote_storage)

    @classmethod
    def get_instance(cls, port: int = 9000,
                     host: str = "127.0.0.1") -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(port, host=host)
        return cls._instance

    def attach(self, storage: StatsStorage) -> "UIServer":
        if storage not in self._storages:
            self._storages.append(storage)
        return self

    def detach(self, storage: StatsStorage) -> "UIServer":
        if storage in self._storages:
            self._storages.remove(storage)
        return self

    # -- data assembly (TrainModule's JSON endpoints) --------------------
    def sessions(self) -> List[str]:
        out = []
        for st in self._storages:
            out.extend(st.list_session_ids())
        return out

    def _updates(self, session: Optional[str]):
        for st in self._storages:
            sessions = st.list_session_ids()
            if not sessions:
                continue
            sid = session if session in sessions else sessions[-1]
            for typ in st.list_type_ids(sid):
                for worker in st.list_worker_ids(sid, typ):
                    return sid, st.get_all_updates(sid, typ, worker)
        return None, []

    def train_data(self, session: Optional[str] = None) -> dict:
        sid, updates = self._updates(session)
        reports = [r for _, r in updates]
        latest = reports[-1] if reports else {}
        import numpy as np

        param_stdev = []
        for r in reports:
            ps = r.get("params") or {}
            param_stdev.append(
                float(np.mean([v["stdev"] for v in ps.values()]))
                if ps else 0.0)
        model = next((r["model"] for r in reports if "model" in r), [])
        return {
            "session": sid,
            "iterations": [r.get("iteration", i)
                           for i, r in enumerate(reports)],
            "scores": [r.get("score") for r in reports],
            "samples_per_sec": [r["perf"]["samples_per_sec"]
                                for r in reports if "perf" in r],
            "rss_mb": [r.get("memory", {}).get("rss_mb", 0) for r in reports],
            "param_stdev": param_stdev,
            "params": latest.get("params", {}),
            "updates": latest.get("updates", {}),
            "model": model,
        }

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "UIServer":
        if self._httpd is not None:
            return self
        self._httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        self.port = self._httpd.server_address[1]
        self._httpd.ui = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="dl4jtpu-ui")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None
