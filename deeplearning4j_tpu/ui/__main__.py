"""UI server CLI — `python -m deeplearning4j_tpu.ui --port 9000
[--storage stats.bin]`.

Reference analog: `PlayUIServer.main` with its JCommander `--uiPort` flag
(`deeplearning4j-play/.../ui/play/PlayUIServer.java:53`, SURVEY.md §2.10).
"""
import argparse
import time


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="deeplearning4j_tpu.ui",
        description="Training-stats dashboard server")
    ap.add_argument("--port", type=int, default=9000,
                    help="HTTP port (reference --uiPort)")
    ap.add_argument("--storage", default=None,
                    help="FileStatsStorage path to attach (watches for "
                         "updates); omit for an empty in-memory storage")
    args = ap.parse_args(argv)

    from .server import UIServer
    from .storage import FileStatsStorage, InMemoryStatsStorage

    storage = (FileStatsStorage(args.storage) if args.storage
               else InMemoryStatsStorage())
    srv = UIServer(port=args.port).attach(storage).start()
    print(f"UI server listening on http://127.0.0.1:{srv.port}/train")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
