"""StatsListener: per-iteration training statistics.

Parity with `ui/stats/BaseStatsListener.java:43` — collects score,
per-parameter summary stats + histograms, update(-magnitude) stats, memory
and throughput each `frequency` iterations, and routes a `StatsReport` into a
`StatsStorage`. One device→host sync per report (the reference pays the same
via INDArray host reads); set frequency>1 to amortize.
"""
from __future__ import annotations

import resource
import time
import uuid
from typing import Dict, List, Optional

import numpy as np

from .storage import StatsStorage
from ..optimize.listeners import TrainingListener

__all__ = ["StatsListener", "StatsReport", "model_topology"]


def _flatten_params(model) -> Dict[str, np.ndarray]:
    """{"layername/param": host array} for MultiLayerNetwork (tuple of dicts)
    or ComputationGraph (dict of dicts)."""
    out: Dict[str, np.ndarray] = {}
    params = model.params
    if params is None:
        return out
    if isinstance(params, dict):
        items = params.items()
    else:
        names = [getattr(l, "name", None) or f"layer{i}"
                 for i, l in enumerate(model.layers)]
        items = zip(names, params)
    for name, p in items:
        if not p:
            continue
        for k, v in sorted(p.items()):
            out[f"{name}/{k}"] = np.asarray(v)
    return out


def _summary(arr: np.ndarray, bins: int) -> Dict:
    flat = arr.ravel().astype(np.float64)
    counts, edges = np.histogram(flat, bins=bins)
    return {
        "mean": float(flat.mean()),
        "stdev": float(flat.std()),
        "min": float(flat.min()),
        "max": float(flat.max()),
        "histogram": {"counts": counts.tolist(),
                      "min": float(edges[0]), "max": float(edges[-1])},
    }


def _param_count(p) -> int:
    if not p:
        return 0
    return int(sum(np.asarray(v).size for v in p.values()))


def model_topology(model) -> List[Dict]:
    """Vertex list for the Flow view (reference FlowListenerModule /
    ModelInfo): [{name, type, inputs, n_params}] in topological order.
    Works for ComputationGraph (DAG) and MultiLayerNetwork (chain)."""
    conf = getattr(model, "conf", None)
    out: List[Dict] = []
    if hasattr(conf, "vertices"):  # ComputationGraph
        for name in conf.network_inputs:
            out.append({"name": name, "type": "Input", "inputs": [],
                        "n_params": 0})
        params = model.params or {}
        for name in conf.topological_order:
            if name not in conf.vertices:
                continue
            v = conf.vertices[name]
            out.append({"name": name, "type": type(v).__name__,
                        "inputs": list(conf.vertex_inputs[name]),
                        "n_params": _param_count(params.get(name))})
        return out
    # MultiLayerNetwork: sequential chain
    out.append({"name": "input", "type": "Input", "inputs": [],
                "n_params": 0})
    prev = "input"
    params = model.params or ()
    for i, layer in enumerate(getattr(model, "layers", ())):
        name = getattr(layer, "name", None) or f"layer{i}"
        out.append({"name": name, "type": type(layer).__name__,
                    "inputs": [prev],
                    "n_params": _param_count(
                        params[i] if i < len(params) else {})})
        prev = name
    return out


class StatsReport(dict):
    """A plain-dict report (JSON-able). Keys: iteration, timestamp, score,
    params {name: summary}, updates {name: summary}, memory, perf, and (on
    the first report of a session) model — the topology for the Flow
    view."""


class StatsListener(TrainingListener):
    TYPE_ID = "StatsListener"
    # reads model.params per iteration_done — under fit_scan_arrays replay
    # every call sees end-of-window params (see fit_scan_arrays docstring)
    collects_param_stats = True

    def __init__(self, storage: StatsStorage, frequency: int = 1,
                 session_id: Optional[str] = None, worker_id: str = "local",
                 collect_histograms: bool = True, histogram_bins: int = 20,
                 collect_updates: bool = True):
        self.storage = storage
        self.frequency = max(1, int(frequency))
        self.session_id = session_id or uuid.uuid4().hex[:12]
        self.worker_id = worker_id
        self.collect_histograms = collect_histograms
        self.histogram_bins = int(histogram_bins)
        self.collect_updates = collect_updates
        self._prev_params: Optional[Dict[str, np.ndarray]] = None
        self._last_time: Optional[float] = None
        self._last_iter: Optional[int] = None
        self._sent_model = False

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency != 0:
            return
        now = time.time()
        report = StatsReport(iteration=int(iteration), timestamp=now,
                             score=float(model.score()))
        if not self._sent_model:
            # topology travels with the FIRST report (the reference's
            # StatsInitializationReport carries the model info the Flow
            # module renders)
            try:
                report["model"] = model_topology(model)
            except Exception:
                pass
            self._sent_model = True

        params = _flatten_params(model)
        if self.collect_histograms:
            report["params"] = {k: _summary(v, self.histogram_bins)
                                for k, v in params.items()}
        if self.collect_updates and self._prev_params is not None:
            upd = {}
            for k, v in params.items():
                prev = self._prev_params.get(k)
                if prev is not None and prev.shape == v.shape:
                    upd[k] = _summary(v - prev, self.histogram_bins)
            report["updates"] = upd
        self._prev_params = params if self.collect_updates else None

        # memory (reference samples JVM/GC; here RSS + device stats if any)
        mem = {"rss_mb": resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss / 1024.0}
        try:
            import jax

            stats = jax.devices()[0].memory_stats()
            if stats:
                mem["device_bytes_in_use"] = int(
                    stats.get("bytes_in_use", 0))
        except Exception:
            pass
        report["memory"] = mem

        # throughput (PerformanceListener's samples/sec, folded in)
        if self._last_time is not None and iteration > (self._last_iter or 0):
            dt = now - self._last_time
            iters = iteration - self._last_iter
            if dt > 0:
                report["perf"] = {
                    "iterations_per_sec": iters / dt,
                    "samples_per_sec":
                        iters * getattr(model, "last_batch_size", 0) / dt,
                }
        self._last_time = now
        self._last_iter = iteration

        self.storage.put_update(self.session_id, self.TYPE_ID,
                                self.worker_id, now, report)
