"""Convolutional activation visualization (reference
`module/convolutional/ConvolutionalListenerModule.java` + its listener —
renders grids of first-conv-layer activation maps in the UI).

`ConvolutionalIterationListener` samples the network's first 4-D activation
every N iterations, tiles the channels of a few examples into one grayscale
grid, and stores it as a base64 PNG in the stats stream; `activation_grid`
is the reusable tiler (also handy for notebook display)."""
from __future__ import annotations

import base64
import io
import time
from typing import Optional

import numpy as np

from ..optimize.listeners import IterationListener
from .storage import StatsStorage

__all__ = ["ConvolutionalIterationListener", "activation_grid"]


def activation_grid(acts: np.ndarray, max_channels: int = 16,
                    pad: int = 1) -> np.ndarray:
    """Tile one example's [H, W, C] activation maps into a single
    grayscale u8 image grid (channels left-to-right, wrapped)."""
    acts = np.asarray(acts, np.float32)
    if acts.ndim != 3:
        raise ValueError(f"need [H, W, C] activations, got {acts.shape}")
    h, w, c = acts.shape
    c = min(c, max_channels)
    cols = int(np.ceil(np.sqrt(c)))
    rows = int(np.ceil(c / cols))
    grid = np.zeros((rows * (h + pad) - pad, cols * (w + pad) - pad),
                    np.float32)
    for i in range(c):
        a = acts[:, :, i]
        lo, hi = float(a.min()), float(a.max())
        a = (a - lo) / (hi - lo) if hi > lo else np.zeros_like(a)
        r, col = divmod(i, cols)
        grid[r * (h + pad):r * (h + pad) + h,
             col * (w + pad):col * (w + pad) + w] = a
    return (grid * 255).astype(np.uint8)


class ConvolutionalIterationListener(IterationListener):
    """Every `frequency` iterations: run the stored last batch forward,
    take the FIRST 4-D (conv) activation, tile `n_examples` grids, PNG-
    encode, and put a report on the stats stream (type 'activations')."""

    def __init__(self, storage: StatsStorage, frequency: int = 10,
                 n_examples: int = 2, max_channels: int = 16,
                 session_id: Optional[str] = None):
        self.storage = storage
        self.frequency = max(1, int(frequency))
        self.n_examples = int(n_examples)
        self.max_channels = int(max_channels)
        self.session_id = session_id or f"conv-{int(time.time())}"

    def _first_conv_activation(self, model, x) -> Optional[np.ndarray]:
        # feed_forward's first element is the INPUT itself (which is
        # already 4-D for CNN data) — skip it, we want layer activations
        for act in model.feed_forward(x)[1:]:
            a = np.asarray(act)
            if a.ndim == 4:          # [B, H, W, C]
                return a
        return None

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency != 0:
            return
        x = getattr(model, "last_input", None)
        if x is None:
            return
        n = min(self.n_examples, int(x.shape[0]))
        acts = self._first_conv_activation(model, x[:n])
        if acts is None:
            return
        try:
            from PIL import Image
        except ImportError:
            return
        images = []
        for i in range(n):
            grid = activation_grid(acts[i], self.max_channels)
            buf = io.BytesIO()
            Image.fromarray(grid, mode="L").save(buf, format="PNG")
            images.append(base64.b64encode(buf.getvalue()).decode())
        self.storage.put_update(
            self.session_id, "activations", "worker-0", time.time(),
            {"iteration": iteration, "pngs_base64": images,
             "shape": list(acts.shape)})
