"""StatsStorage SPI + implementations.

Parity with `deeplearning4j-core/.../api/storage/StatsStorage.java` (the SPI
the UI plugs into: sessions → type → worker → time-ordered updates, plus
change listeners) and the impls in `deeplearning4j-ui-model/.../ui/storage/`
(InMemoryStatsStorage, FileStatsStorage). The reference persists SBE binary;
here a report is a JSON-able dict and FileStatsStorage appends JSON lines.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["StatsStorage", "InMemoryStatsStorage", "FileStatsStorage",
           "SqliteStatsStorage", "StatsStorageEvent", "StatsStorageListener"]


class StatsStorageEvent:
    NEW_SESSION = "new_session"
    NEW_WORKER = "new_worker"
    POST_UPDATE = "post_update"

    def __init__(self, kind: str, session_id: str, type_id: str,
                 worker_id: str, timestamp: float):
        self.kind = kind
        self.session_id = session_id
        self.type_id = type_id
        self.worker_id = worker_id
        self.timestamp = timestamp


StatsStorageListener = Callable[[StatsStorageEvent], None]


class StatsStorage:
    """SPI: (session, type, worker) → time-ordered updates."""

    def put_update(self, session_id: str, type_id: str, worker_id: str,
                   timestamp: float, report: Dict) -> None:
        raise NotImplementedError

    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def list_type_ids(self, session_id: str) -> List[str]:
        raise NotImplementedError

    def list_worker_ids(self, session_id: str, type_id: str) -> List[str]:
        raise NotImplementedError

    def get_all_updates(self, session_id: str, type_id: str,
                        worker_id: str) -> List[Tuple[float, Dict]]:
        raise NotImplementedError

    def get_all_updates_after(self, session_id: str, type_id: str,
                              worker_id: str, timestamp: float
                              ) -> List[Tuple[float, Dict]]:
        return [(t, r) for t, r in
                self.get_all_updates(session_id, type_id, worker_id)
                if t > timestamp]

    def get_latest_update(self, session_id: str, type_id: str,
                          worker_id: str) -> Optional[Tuple[float, Dict]]:
        ups = self.get_all_updates(session_id, type_id, worker_id)
        return ups[-1] if ups else None

    # -- change notification (UI polling uses this) ---------------------
    def register_listener(self, listener: StatsStorageListener) -> None:
        self._listeners().append(listener)

    def deregister_listener(self, listener: StatsStorageListener) -> None:
        try:
            self._listeners().remove(listener)
        except ValueError:
            pass

    def _listeners(self) -> List[StatsStorageListener]:
        if not hasattr(self, "_listener_list"):
            self._listener_list: List[StatsStorageListener] = []
        return self._listener_list

    def _notify(self, event: StatsStorageEvent) -> None:
        for listener in list(self._listeners()):
            listener(event)


class InMemoryStatsStorage(StatsStorage):
    def __init__(self):
        self._lock = threading.Lock()
        # {session: {type: {worker: [(ts, report), ...]}}}
        self._data: Dict[str, Dict[str, Dict[str, List[Tuple[float, Dict]]]]] = {}

    def put_update(self, session_id, type_id, worker_id, timestamp, report):
        with self._lock:
            new_session = session_id not in self._data
            sess = self._data.setdefault(session_id, {})
            typ = sess.setdefault(type_id, {})
            new_worker = worker_id not in typ
            typ.setdefault(worker_id, []).append((timestamp, dict(report)))
        if new_session:
            self._notify(StatsStorageEvent(StatsStorageEvent.NEW_SESSION,
                                           session_id, type_id, worker_id,
                                           timestamp))
        if new_worker:
            self._notify(StatsStorageEvent(StatsStorageEvent.NEW_WORKER,
                                           session_id, type_id, worker_id,
                                           timestamp))
        self._notify(StatsStorageEvent(StatsStorageEvent.POST_UPDATE,
                                       session_id, type_id, worker_id,
                                       timestamp))

    def list_session_ids(self):
        with self._lock:
            return list(self._data)

    def list_type_ids(self, session_id):
        with self._lock:
            return list(self._data.get(session_id, {}))

    def list_worker_ids(self, session_id, type_id):
        with self._lock:
            return list(self._data.get(session_id, {}).get(type_id, {}))

    def get_all_updates(self, session_id, type_id, worker_id):
        with self._lock:
            return list(self._data.get(session_id, {}).get(type_id, {})
                        .get(worker_id, []))


class FileStatsStorage(InMemoryStatsStorage):
    """JSON-lines persistence: every update appends one line
    {"session":..,"type":..,"worker":..,"ts":..,"report":{...}}; the
    constructor replays an existing file (round-trip-able storage, the role
    of the reference's FileStatsStorage/MapDBStatsStorage)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._file_lock = threading.Lock()
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    super().put_update(rec["session"], rec["type"],
                                       rec["worker"], rec["ts"],
                                       rec["report"])

    def put_update(self, session_id, type_id, worker_id, timestamp, report):
        rec = {"session": session_id, "type": type_id, "worker": worker_id,
               "ts": timestamp, "report": report}
        with self._file_lock, open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        super().put_update(session_id, type_id, worker_id, timestamp, report)


class SqliteStatsStorage(StatsStorage):
    """SQLite-backed storage (reference `sqlite/J7FileStatsStorage.java` /
    `mapdb/MapDBStatsStorage.java` role): durable, queryable from other
    processes, safe for concurrent writers through SQLite's own locking.
    Reports are stored as JSON text in an indexed (session, type, worker,
    ts) table."""

    def __init__(self, path: str):
        import sqlite3

        self.path = path
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        with self._lock:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS updates ("
                " session TEXT NOT NULL, type TEXT NOT NULL,"
                " worker TEXT NOT NULL, ts REAL NOT NULL,"
                " report TEXT NOT NULL)")
            self._conn.execute(
                "CREATE INDEX IF NOT EXISTS idx_updates ON updates"
                " (session, type, worker, ts)")
            self._conn.commit()

    def close(self):
        with self._lock:
            self._conn.close()

    def put_update(self, session_id, type_id, worker_id, timestamp, report):
        with self._lock:
            new_session = not self._conn.execute(
                "SELECT 1 FROM updates WHERE session=? LIMIT 1",
                (session_id,)).fetchone()
            new_worker = not self._conn.execute(
                "SELECT 1 FROM updates WHERE session=? AND type=? AND "
                "worker=? LIMIT 1",
                (session_id, type_id, worker_id)).fetchone()
            self._conn.execute(
                "INSERT INTO updates VALUES (?,?,?,?,?)",
                (session_id, type_id, worker_id, float(timestamp),
                 json.dumps(report)))
            self._conn.commit()
        if new_session:
            self._notify(StatsStorageEvent(StatsStorageEvent.NEW_SESSION,
                                           session_id, type_id, worker_id,
                                           timestamp))
        if new_worker:
            self._notify(StatsStorageEvent(StatsStorageEvent.NEW_WORKER,
                                           session_id, type_id, worker_id,
                                           timestamp))
        self._notify(StatsStorageEvent(StatsStorageEvent.POST_UPDATE,
                                       session_id, type_id, worker_id,
                                       timestamp))

    def list_session_ids(self):
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT session FROM updates ORDER BY rowid")
            return [r[0] for r in rows]

    def list_type_ids(self, session_id):
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT type FROM updates WHERE session=?",
                (session_id,))
            return [r[0] for r in rows]

    def list_worker_ids(self, session_id, type_id):
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT worker FROM updates WHERE session=? AND "
                "type=?", (session_id, type_id))
            return [r[0] for r in rows]

    def get_all_updates(self, session_id, type_id, worker_id):
        with self._lock:
            rows = self._conn.execute(
                "SELECT ts, report FROM updates WHERE session=? AND type=?"
                " AND worker=? ORDER BY ts, rowid",
                (session_id, type_id, worker_id))
            return [(t, json.loads(r)) for t, r in rows]
