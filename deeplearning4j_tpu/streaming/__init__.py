"""Streaming ingest + online serving (dl4j-streaming analog).

Reference (SURVEY.md §2.4): `streaming/kafka/NDArrayKafkaClient.java`,
`NDArrayPublisher/Consumer`, `routes/DL4jServeRouteBuilder.java:27` —
Camel routes that consume serialized arrays from Kafka, restore a model
with ModelSerializer, run `output()`, and publish the result.

TPU-native shape: the broker is replaced with length-prefixed numpy (.npy)
messages over TCP sockets — no Kafka/Camel runtime. `NDArrayConsumer`
listens, `NDArrayPublisher` connects and sends, and `InferenceRoute` wires
consumer -> restored model -> publisher exactly like DL4jServeRouteBuilder
(`configure:50`). The host-side serving plane stays off the device; each
batch is one `model.output` call on the accelerator.
"""
from __future__ import annotations

import io
import queue
import socket
import struct
import threading
from typing import Optional

import numpy as np

__all__ = ["NDArraySerde", "NDArrayConsumer", "NDArrayPublisher",
           "InferenceRoute"]


class NDArraySerde:
    """Array <-> bytes via the self-describing .npy format (the role of the
    reference's Nd4j binary serde in `NDArrayKafkaClient`)."""

    @staticmethod
    def to_bytes(arr: np.ndarray) -> bytes:
        buf = io.BytesIO()
        np.save(buf, np.asarray(arr), allow_pickle=False)
        return buf.getvalue()

    @staticmethod
    def from_bytes(data: bytes) -> np.ndarray:
        return np.load(io.BytesIO(data), allow_pickle=False)


def _send_msg(sock: socket.socket, payload: bytes):
    sock.sendall(struct.pack(">Q", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    parts = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            return None
        parts.append(chunk)
        n -= len(chunk)
    return b"".join(parts)


def _recv_msg(sock: socket.socket) -> Optional[bytes]:
    head = _recv_exact(sock, 8)
    if head is None:
        return None
    (ln,) = struct.unpack(">Q", head)
    return _recv_exact(sock, ln)


class NDArrayConsumer:
    """Listens on a TCP port; received arrays are queued for `take()`
    (reference NDArrayConsumer over a Kafka topic)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 queue_size: int = 64):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(8)
        self.host, self.port = self._srv.getsockname()
        self._q: queue.Queue = queue.Queue(queue_size)
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            # daemon reader per connection; no bookkeeping — readers exit
            # with their socket, and close() unblocks them via shutdown
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn: socket.socket):
        with conn:
            while not self._stop.is_set():
                try:
                    msg = _recv_msg(conn)
                except OSError:
                    return
                if msg is None:
                    return
                self._q.put(NDArraySerde.from_bytes(msg))

    def take(self, timeout: Optional[float] = None) -> Optional[np.ndarray]:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def close(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NDArrayPublisher:
    """Connects to a consumer and publishes arrays (reference
    NDArrayPublisher)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)

    def publish(self, arr: np.ndarray):
        _send_msg(self._sock, NDArraySerde.to_bytes(arr))

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class InferenceRoute:
    """Serve route (`DL4jServeRouteBuilder.configure:50`): consume input
    arrays, run the restored model's `output()`, publish predictions.

    Use `start()` for the background-thread route (consumer port ->
    downstream publisher), or call `process(arr)` synchronously."""

    def __init__(self, model_or_path, listen_port: int = 0,
                 forward: Optional[NDArrayPublisher] = None,
                 before_processing=None):
        if isinstance(model_or_path, str):
            from ..util.serializer import ModelSerializer
            self.model = ModelSerializer.restore(model_or_path)
        else:
            self.model = model_or_path
        self.consumer = NDArrayConsumer(port=listen_port)
        self.forward = forward
        self.before_processing = before_processing
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.consumer.port

    def process(self, arr: np.ndarray) -> np.ndarray:
        if self.before_processing is not None:
            arr = self.before_processing(arr)
        return np.asarray(self.model.output(arr))

    def _loop(self):
        import logging
        log = logging.getLogger("deeplearning4j_tpu")
        while not self._stop.is_set():
            arr = self.consumer.take(timeout=0.2)
            if arr is None:
                continue
            try:
                out = self.process(arr)
                if self.forward is not None:
                    self.forward.publish(out)
            except Exception:   # a bad batch must not kill the route
                log.exception("InferenceRoute: dropping failed batch "
                              "(shape=%s)", getattr(arr, "shape", None))

    def start(self) -> "InferenceRoute":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self.consumer.close()
