"""File-backed replayable topic — broker-grade streaming without a broker.

The reference's ingestion rode a real Kafka
(`streaming/kafka/NDArrayKafkaClient.java`, `NDArrayPublisher/Consumer`,
`routes/CamelKafkaRouteBuilder.java`): durable append-only topics, consumer
offsets, replay from any offset. The round-3 `streaming/` module covered the
transport (ephemeral TCP pub/sub) but not those broker semantics. This
module supplies them with an append-only segmented log on the filesystem —
no external broker dependency, same capability surface:

  * `FileTopic` — segmented append-only log; records are length-prefixed
    blobs; logical offsets (record indices) like Kafka's; torn tails from
    a crash are detected on open and skipped by readers, then truncated
    by the NEXT WRITER before it appends (Kafka log recovery on the
    partition leader) — with a warning and a
    `dl4j_topic_torn_records_total` counter, so a crashed producer never
    poisons subsequent consumers.
  * `TopicPublisher` — `publish(array)` appends durably (fsync optional).
  * `TopicConsumer` — `take(timeout)` / `seek(offset)` / `commit()`;
    committed offsets persist per consumer GROUP (atomic file replace),
    so a crashed consumer resumes exactly where it committed — the
    produce/crash/re-consume contract the TCP tier cannot offer.

The serde is the module's `NDArraySerde` (.npy), so `TopicPublisher` /
`TopicConsumer` are drop-in durable counterparts of `NDArrayPublisher` /
`NDArrayConsumer`.
"""
from __future__ import annotations

import json
import logging
import os
import struct
import time
from typing import List, Optional, Tuple

import numpy as np

from . import NDArraySerde
from ..telemetry.runtime import active as _tel_active

__all__ = ["FileTopic", "TopicPublisher", "TopicConsumer"]

_LEN = struct.Struct(">Q")
_SEG_PREFIX = "segment_"
_SEG_SUFFIX = ".log"

_log = logging.getLogger(__name__)


def _count_torn(topic: str, n: int = 1):
    tel = _tel_active()
    if tel is not None:
        tel.registry.counter(
            "dl4j_topic_torn_records_total",
            "torn tail records truncated during topic log recovery",
            labels=("topic",)).inc(n, topic=topic)


class FileTopic:
    """Append-only segmented log with logical offsets.

    Layout: `<root>/<name>/segment_<base-offset>.log` holds records
    `[8-byte big-endian length][payload]` starting at logical offset
    `<base-offset>`; `<root>/<name>/offsets/<group>.json` holds committed
    consumer-group offsets.

    Concurrency contract (Kafka's per-partition-leader analog): any number
    of reader processes, ONE writer at a time. Logical offsets are assigned
    from a cursor that `append` re-syncs against the last segment's on-disk
    length first, so sequential writer handoff (crash → restart, or another
    process that appended since this object was opened) assigns correct
    offsets — but two writers appending CONCURRENTLY race between the
    re-sync and the write and can mint duplicate offsets; run one producer
    per topic, as the reference ran one Kafka partition leader."""

    def __init__(self, root: str, name: str = "ndarrays",
                 segment_bytes: int = 16 << 20, fsync: bool = False):
        self.dir = os.path.join(str(root), name)
        os.makedirs(self.dir, exist_ok=True)
        os.makedirs(os.path.join(self.dir, "offsets"), exist_ok=True)
        self.segment_bytes = int(segment_bytes)
        self.fsync = fsync
        # path -> byte offset of each valid record (built per segment on
        # first touch, extended incrementally): read(offset) seeks
        # directly instead of skipping headers from the segment base
        self._index: dict = {}
        # path -> byte length the index covers; a mismatch with the file's
        # real size means another writer appended (or we crashed mid-write)
        self._indexed_bytes: dict = {}
        self._reindex()   # read-only: opening a topic never truncates

    # -- log structure ---------------------------------------------------
    def _segments(self) -> List[Tuple[int, str]]:
        """[(base_offset, path)] sorted by base offset."""
        out = []
        for n in os.listdir(self.dir):
            if n.startswith(_SEG_PREFIX) and n.endswith(_SEG_SUFFIX):
                base = int(n[len(_SEG_PREFIX):-len(_SEG_SUFFIX)])
                out.append((base, os.path.join(self.dir, n)))
        return sorted(out)

    @staticmethod
    def _scan(path: str) -> Tuple[List[int], int]:
        """(record_byte_offsets, valid_byte_length) — stops at a torn
        tail."""
        offs: List[int] = []
        pos = 0
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            while pos + _LEN.size <= size:
                head = f.read(_LEN.size)
                if len(head) < _LEN.size:
                    break
                (ln,) = _LEN.unpack(head)
                if pos + _LEN.size + ln > size:
                    break   # torn record (crash mid-append)
                f.seek(ln, os.SEEK_CUR)
                offs.append(pos)
                pos += _LEN.size + ln
        return offs, pos

    def _reindex(self):
        """Index the last segment up to its valid prefix and compute the
        end offset. Read-only: a torn tail (partial record from a crashed
        or in-flight producer) is simply ignored, NEVER truncated — a
        reader must not destroy bytes a live writer may still be
        appending. Returns (path, valid, size) for the last segment, or
        None when the log is empty."""
        segs = self._segments()
        if not segs:
            self._end = 0
            return None
        base, path = segs[-1]
        offs, valid = self._scan(path)
        self._index[path] = offs
        self._indexed_bytes[path] = valid
        self._end = base + len(offs)
        return path, valid, os.path.getsize(path)

    def _recover(self):
        """Writer-side log recovery (Kafka's analog runs on the partition
        leader): truncate a torn tail in the last segment so the next
        append lands on a record boundary. Only the append path calls
        this — see `_reindex` for the reader contract."""
        last = self._reindex()
        if last is None:
            return
        path, valid, size = last
        if valid < size:
            with open(path, "r+b") as f:
                f.truncate(valid)
            _log.warning(
                "topic %s: truncated torn tail record in %s "
                "(%d bytes past last valid record at %d)",
                os.path.basename(self.dir), os.path.basename(path),
                size - valid, valid)
            _count_torn(os.path.basename(self.dir))

    # -- producer side ---------------------------------------------------
    def append(self, payload: bytes) -> int:
        """Append one record; returns its logical offset. Durable against
        torn writes (recovery truncates); `fsync=True` makes it durable
        against power loss too. Single writer at a time: see class
        docstring."""
        segs = self._segments()
        if segs:
            # re-sync the offset cursor if the last segment grew (or was
            # torn) behind our back — a previous writer's appends must not
            # be assigned duplicate logical offsets
            last = segs[-1][1]
            if self._indexed_bytes.get(last) != os.path.getsize(last):
                self._recover()
                segs = self._segments()
        if segs and os.path.getsize(segs[-1][1]) < self.segment_bytes:
            path = segs[-1][1]
        else:
            path = os.path.join(
                self.dir, f"{_SEG_PREFIX}{self._end:020d}{_SEG_SUFFIX}")
        byte_off = os.path.getsize(path) if os.path.exists(path) else 0
        with open(path, "ab") as f:
            f.write(_LEN.pack(len(payload)) + payload)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        self._index.setdefault(path, []).append(byte_off)
        self._indexed_bytes[path] = byte_off + _LEN.size + len(payload)
        off = self._end
        self._end += 1
        return off

    # -- consumer side ---------------------------------------------------
    def end_offset(self) -> int:
        """One past the last record currently in the log. Trusts the
        cached value; a read miss triggers the rescan (`read` below), so
        cross-process appends are still observed without paying a full
        last-segment scan per call."""
        return self._end

    def begin_offset(self) -> int:
        segs = self._segments()
        return segs[0][0] if segs else 0

    def read(self, offset: int) -> Optional[bytes]:
        """Record at logical `offset`, or None past the end."""
        if offset >= self._end:
            self._reindex()   # another process may have appended
            if offset >= self._end:
                return None
        segs = self._segments()
        seg = None
        for base, path in segs:
            if base <= offset:
                seg = (base, path)
            else:
                break
        if seg is None:
            raise KeyError(f"offset {offset} below log start "
                           f"{self.begin_offset()}")
        base, path = seg
        offs = self._index.get(path)
        if offs is None or offset - base >= len(offs):
            offs, valid = self._scan(path)
            self._index[path] = offs
            self._indexed_bytes[path] = valid
            if offset - base >= len(offs):
                return None
        with open(path, "rb") as f:
            f.seek(offs[offset - base])
            head = f.read(_LEN.size)
            if len(head) < _LEN.size:
                return None
            (ln,) = _LEN.unpack(head)
            data = f.read(ln)
            return data if len(data) == ln else None

    # -- committed group offsets ----------------------------------------
    def _offsets_path(self, group: str) -> str:
        return os.path.join(self.dir, "offsets", f"{group}.json")

    def committed(self, group: str) -> int:
        try:
            with open(self._offsets_path(group)) as f:
                return int(json.load(f)["offset"])
        except (OSError, ValueError, KeyError):
            return self.begin_offset()

    def commit(self, group: str, offset: int):
        p = self._offsets_path(group)
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"offset": int(offset)}, f)
        os.replace(tmp, p)   # atomic: a crash never corrupts the offset


class TopicPublisher:
    """Durable counterpart of `NDArrayPublisher`: publish(array) appends
    to the topic log."""

    def __init__(self, topic: FileTopic):
        self.topic = topic

    def publish(self, arr: np.ndarray) -> int:
        return self.topic.append(NDArraySerde.to_bytes(arr))

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class TopicConsumer:
    """Durable counterpart of `NDArrayConsumer`: take() reads the next
    record from this group's position; commit() persists it. A consumer
    restarted after a crash resumes from the last committed offset —
    records consumed but not committed are redelivered (at-least-once,
    Kafka's default contract)."""

    def __init__(self, topic: FileTopic, group: str = "default",
                 from_beginning: bool = False):
        self.topic = topic
        self.group = group
        self.position = (topic.begin_offset() if from_beginning
                         else topic.committed(group))

    def seek(self, offset: int):
        self.position = int(offset)

    def take(self, timeout: Optional[float] = None) -> Optional[np.ndarray]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            data = self.topic.read(self.position)
            if data is not None:
                self.position += 1
                return NDArraySerde.from_bytes(data)
            if deadline is None or time.monotonic() >= deadline:
                return None
            time.sleep(0.02)

    def commit(self):
        self.topic.commit(self.group, self.position)

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
