"""Production inference plane (ROADMAP open item 1 — the "millions of
users" leg).

Four pieces, layered:
  * `ModelRegistry` (registry.py) — named, versioned servable models with
    **atomic hot-swap** from fault/-verified checkpoint sources (sha256
    manifest zips, committed checkpoint directories, Keras HDF5, live
    model objects). Every (model, shape-bucket, precision) forward is
    jit-lowered AND compiled at registration — the request path only ever
    invokes finished XLA executables, never a cold compile.
  * int8 weight-only quantization + bf16 casting (quantize.py) — the
    reduced-precision serving paths.
  * `DynamicBatcher` (batcher.py) — coalesces concurrent requests into
    padded fixed-shape batches (the PadToBatch row shaping from
    datasets/pipeline.py, applied to traffic instead of datasets) with
    max-wait-µs / max-batch knobs; per-row scatter back to waiters.
  * `InferenceServer` (server.py) — the HTTP front end (`/v1/models`,
    `/v1/models/<name>/predict`, `/v1/models/<name>/swap`, `/healthz`,
    Prometheus `/metrics` via the telemetry registry).

`serving/bench.py` drives concurrent closed-loop clients through the
data plane and reports p50/p99 latency + requests/s, batched vs
unbatched (surfaced as bench.py extras["Serving-latency"]).
"""
from .batcher import BatcherClosedError, DynamicBatcher
from .bench import run_serving_bench
from .quantize import QuantizedTree, cast_tree, quantize_tree
from .registry import (AotCompileError, CanaryState, DEFAULT_BUCKETS,
                       ModelRegistry, PRECISIONS, ServableVersion,
                       ServingError, UnknownModelError, load_source)
from .server import ClientError, InferenceServer

__all__ = [
    "ModelRegistry", "ServableVersion", "ServingError", "UnknownModelError",
    "AotCompileError", "CanaryState",
    "DEFAULT_BUCKETS", "PRECISIONS", "load_source",
    "DynamicBatcher", "BatcherClosedError",
    "InferenceServer", "ClientError",
    "QuantizedTree", "quantize_tree", "cast_tree",
    "run_serving_bench",
]
