"""Token-granularity continuous batching (Orca, Yu et al., OSDI 2022)
over the paged decode engine.

One worker thread per generate-enabled servable runs the generation
loop: admit waiting sequences, grow/evict KV blocks, run ONE decode
tick, sample, retire finished rows, repeat. The load-bearing property
is WHERE admission happens: between every tick (token granularity), so
a new request starts decoding the moment a batch slot and KV blocks
exist instead of waiting for the whole current batch to drain — that
is the continuous-vs-static tokens/s gap the bench measures.

Invariant per sequence: ``ctx`` is prompt + every sampled token, and
``cached`` counts how many of ctx's K/V live in the arena. Prefill
caches all of ctx at once and samples token ``len(ctx)``; each tick
feeds ``ctx[cached]`` at position ``cached`` and samples the next.
Eviction (KV-block pressure) just frees the blocks and sets
``cached = 0`` — on re-admission the sequence re-prefills its whole ctx
and continues, so a greedy sequence is reproducible across evictions.

Batch composition per tick goes through the serving batcher's
`FlushEma` (per-bucket tick-wall-time EMAs): with `avail` live rows it
either pads up to the next decode bucket or runs the largest full
bucket now, whichever maximizes rows/s — the DynamicBatcher flush
policy generalized to the decode plane. A rotating offset keeps row
selection fair when only a sub-batch runs.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ...telemetry.recorder import flight_recorder
from ..batcher import FlushEma
from ..registry import ServingError
from .cache import OutOfBlocksError
from .engine import DecodeEngine

__all__ = ["GenerationScheduler", "GenerationError"]


class GenerationError(ServingError):
    """A generation request failed (bad arguments, scheduler closed, or
    a sequence could not hold its KV blocks)."""


class _Seq:
    __slots__ = ("sid", "ctx", "prompt_len", "max_tokens", "temperature",
                 "stop_ids", "rng", "blocks", "cached", "event", "result",
                 "error", "trace", "enqueued_at")

    def __init__(self, sid, prompt, max_tokens, temperature, stop_ids, seed,
                 trace=None):
        self.sid = sid
        self.ctx: List[int] = list(prompt)
        self.prompt_len = len(prompt)
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.stop_ids = frozenset(stop_ids)
        self.rng = np.random.default_rng(sid if seed is None else seed)
        self.blocks: List[int] = []
        self.cached = 0                 # ctx tokens whose K/V are cached
        self.event = threading.Event()
        self.result: Optional[Dict] = None
        self.error: Optional[Exception] = None
        self.trace = trace              # TraceContext, or None
        self.enqueued_at = time.perf_counter()

    @property
    def generated(self) -> List[int]:
        return self.ctx[self.prompt_len:]


class GenerationScheduler:
    """Continuous-batching generation loop for one servable.

    `mode="continuous"` admits between every tick; `mode="static"`
    (the bench's control arm) only refills once the running set fully
    drains — classic request-level batching."""

    def __init__(self, registry, name: str, *, mode: str = "continuous",
                 block_len: int = 16, num_blocks: Optional[int] = None,
                 kv_dtype: str = "fp32",
                 decode_buckets: Sequence[int] = (1, 2, 4, 8),
                 prompt_buckets: Optional[Sequence[int]] = None,
                 metrics=None, idle_wait_s: float = 0.02,
                 arm: str = "stable"):
        if mode not in ("continuous", "static"):
            raise GenerationError(f"mode must be continuous|static, "
                                  f"got {mode!r}")
        self.name = name
        self.mode = mode
        # canary arm this scheduler serves: a "canary" scheduler
        # resolves the candidate version each tick (falling back to
        # stable after a rollback — the existing flush-on-version-change
        # path then restarts its running sequences on the stable version)
        self.arm = arm
        self.registry = registry
        self.engine = DecodeEngine(
            registry, name, block_len=block_len, num_blocks=num_blocks,
            kv_dtype=kv_dtype, decode_buckets=decode_buckets,
            prompt_buckets=prompt_buckets)
        self.pool = self.engine.new_pool(metrics)
        self._ema = FlushEma()
        self._idle_wait_s = idle_wait_s
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._waiting: deque = deque()
        self._running: List[_Seq] = []
        self._closed = False
        self._ids = itertools.count(1)
        self._rotate = 0
        self._version = None
        self._tokens_c = self._admit_c = self._evict_c = None
        self._phase_h = None
        if metrics is not None:
            self._tokens_c = metrics.counter(
                "dl4j_decode_tokens_total", "generated tokens",
                labels=("model",))
            self._admit_c = metrics.counter(
                "dl4j_decode_admissions_total",
                "sequences admitted to the decode batch", labels=("model",))
            self._evict_c = metrics.counter(
                "dl4j_decode_evictions_total",
                "sequences preempted for KV-block pressure",
                labels=("model",))
            self._phase_h = metrics.histogram(
                "dl4j_decode_phase_seconds",
                "wall seconds per compiled generation step",
                labels=("model", "phase"))
        self._worker = threading.Thread(
            target=self._run,
            name=(f"dl4j-decode-sched-{name}" if arm == "stable"
                  else f"dl4j-decode-sched-{name}-{arm}"),
            daemon=True)
        self._worker.start()

    # -- client side -----------------------------------------------------
    def submit(self, prompt: Sequence[int], *, max_tokens: int = 16,
               temperature: float = 0.0, stop: Sequence[int] = (),
               seed: Optional[int] = None,
               timeout: Optional[float] = None, ctx=None) -> Dict:
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise GenerationError("prompt must be non-empty")
        if max_tokens < 1:
            raise GenerationError("max_tokens must be >= 1")
        if len(prompt) >= self.engine.max_context:
            raise GenerationError(
                f"prompt of {len(prompt)} tokens leaves no room in the "
                f"context window ({self.engine.max_context})")
        with self._lock:
            if self._closed:
                raise GenerationError(f"{self.name}: scheduler is stopped")
            seq = _Seq(next(self._ids), prompt, int(max_tokens),
                       float(temperature), [int(t) for t in stop], seed,
                       trace=ctx)
            self._waiting.append(seq)
        self._wake.set()
        if not seq.event.wait(timeout):
            raise TimeoutError(f"{self.name}: generation timed out")
        if seq.error is not None:
            raise seq.error
        return seq.result

    def stop(self, drain: bool = True):
        with self._lock:
            self._closed = True
            if not drain:
                while self._waiting:
                    self._fail(self._waiting.popleft(),
                               GenerationError("scheduler stopped"))
        self._wake.set()
        self._worker.join()

    # -- worker side -----------------------------------------------------
    def _finish(self, seq: _Seq, reason: str):
        t0 = time.perf_counter()
        self.pool.release(seq.blocks)
        seq.blocks = []
        seq.result = {"tokens": seq.generated, "finish_reason": reason,
                      "prompt_tokens": seq.prompt_len,
                      "generated_tokens": len(seq.generated)}
        if seq.trace is not None:
            # emitted BEFORE event.set(): the waiter wakes to a complete
            # trace (queue -> prefill -> ticks -> scatter) in the buffer
            seq.trace.emit("scatter", t0, time.perf_counter(),
                           model=self.name, finish_reason=reason,
                           generated=len(seq.generated))
        seq.event.set()

    def _fail(self, seq: _Seq, err: Exception):
        self.pool.release(seq.blocks)
        seq.blocks = []
        seq.error = err
        seq.event.set()

    def _sample(self, seq: _Seq, logits: np.ndarray) -> int:
        if seq.temperature <= 0.0:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / seq.temperature
        z -= z.max()
        p = np.exp(z)
        return int(seq.rng.choice(len(p), p=p / p.sum()))

    def _append_sample(self, seq: _Seq, logits: np.ndarray) -> bool:
        """Sample the next token; True if the sequence is finished."""
        tok = self._sample(seq, logits)
        if self._tokens_c is not None:
            self._tokens_c.inc(model=self.name)
        if tok in seq.stop_ids:
            self._finish(seq, "stop")
            return True
        seq.ctx.append(tok)
        if len(seq.generated) >= seq.max_tokens:
            self._finish(seq, "length")
            return True
        if len(seq.ctx) >= self.engine.max_context:
            self._finish(seq, "context")
            return True
        return False

    def _evict_one(self, keep: _Seq) -> bool:
        """Preempt the NEWEST running sequence other than `keep` back to
        the waiting queue (its blocks freed; it will re-prefill)."""
        victims = [s for s in self._running if s is not keep]
        if not victims:
            return False
        victim = max(victims, key=lambda s: s.sid)
        self._running.remove(victim)
        self.pool.release(victim.blocks)
        victim.blocks = []
        victim.cached = 0
        victim.enqueued_at = time.perf_counter()   # re-queued: wait restarts
        with self._lock:
            self._waiting.appendleft(victim)
        if self._evict_c is not None:
            self._evict_c.inc(model=self.name)
        rec = flight_recorder()
        if rec.enabled:
            rec.record("decode/evict", model=self.name, sid=victim.sid,
                       kept_sid=keep.sid, ctx_len=len(victim.ctx),
                       free_blocks=self.pool.free_blocks())
        return True

    def _reserve(self, seq: _Seq, n_tokens: int) -> bool:
        """Grow seq's block table to cover `n_tokens` cache slots,
        evicting neighbours under pressure. False = impossible even
        alone (seq is failed)."""
        while True:
            need = self.engine.spec.blocks_for(n_tokens) - len(seq.blocks)
            if need <= 0:
                return True
            try:
                seq.blocks.extend(self.pool.alloc(need))
                return True
            except OutOfBlocksError as e:
                if not self._evict_one(seq):
                    if seq in self._running:
                        self._running.remove(seq)
                    self._fail(seq, GenerationError(str(e)))
                    return False

    def _flush_running(self):
        """Version swapped under us: preempt everything (sequences keep
        their ctx and re-prefill against the new weights)."""
        rec = flight_recorder()
        if rec.enabled and self._running:
            rec.record("decode/swap_flush", model=self.name,
                       preempted=len(self._running),
                       free_blocks=self.pool.free_blocks())
        for seq in list(self._running):
            self._running.remove(seq)
            self.pool.release(seq.blocks)
            seq.blocks = []
            seq.cached = 0
            seq.enqueued_at = time.perf_counter()
            with self._lock:
                self._waiting.appendleft(seq)

    def _admit(self, v):
        cap = self.engine.decode_buckets[-1]
        while True:
            with self._lock:
                if not self._waiting or len(self._running) >= cap:
                    return
                if self.mode == "static" and self._running:
                    return
                seq = self._waiting.popleft()
            if not self._reserve(seq, len(seq.ctx)):
                continue
            t0 = time.perf_counter()
            if seq.trace is not None:
                # enqueue (or eviction re-queue) -> admission
                seq.trace.emit("queue_wait", seq.enqueued_at, t0,
                               model=self.name, sid=seq.sid,
                               ctx_len=len(seq.ctx))
            try:
                logits = self.engine.run_prefill(v, self.pool, seq.ctx,
                                                 seq.blocks, ctx=seq.trace)
            except Exception as e:          # noqa: BLE001 - fail the seq
                self._fail(seq, e)
                continue
            if self._phase_h is not None:
                self._phase_h.observe(time.perf_counter() - t0,
                                      model=self.name, phase="prefill")
            if self._admit_c is not None:
                self._admit_c.inc(model=self.name)
            rec = flight_recorder()
            if rec.enabled:
                # KV-pool pressure at the admission decision point
                rec.record("decode/admit", model=self.name, sid=seq.sid,
                           prompt_len=seq.prompt_len,
                           blocks=len(seq.blocks),
                           free_blocks=self.pool.free_blocks())
            seq.cached = len(seq.ctx)
            if not self._append_sample(seq, logits):
                self._running.append(seq)

    def _tick(self, v):
        # room for each row's next slot BEFORE composing the batch, so
        # an eviction never invalidates a row already in the padded step
        for seq in list(self._running):
            if seq in self._running:        # _reserve may evict/fail rows
                self._reserve(seq, seq.cached + 1)
        if not self._running:
            return
        avail = len(self._running)
        rows = self._ema.pick_rows(avail, list(self.engine.decode_buckets),
                                   self.engine.decode_buckets[-1])
        order = (self._running[self._rotate % avail:]
                 + self._running[:self._rotate % avail])
        batch = order[:rows]
        self._rotate += rows
        bucket = self.engine.decode_bucket_for(len(batch))
        t0 = time.perf_counter()
        logits = self.engine.run_tick(
            v, self.pool, [s.ctx[s.cached] for s in batch],
            [s.cached for s in batch], [s.blocks for s in batch], bucket,
            ctxs=[s.trace for s in batch])
        dt = time.perf_counter() - t0
        self._ema.observe(bucket, dt)
        if self._phase_h is not None:
            self._phase_h.observe(dt, model=self.name, phase="decode")
        for seq, row in zip(batch, logits):
            seq.cached += 1
            if self._append_sample(seq, row):
                self._running.remove(seq)

    def _resolve_version(self):
        """The version this scheduler's arm serves this tick. Canary
        schedulers resolve through the registry's arm routing (which
        falls back to stable once the canary is promoted or rolled
        back); registries without the canary surface (ducks in tests)
        resolve the plain current version."""
        arm_version = getattr(self.registry, "arm_version", None)
        if arm_version is not None:
            return arm_version(self.name, self.arm)
        return self.registry.get(self.name)

    def _run(self):
        while True:
            # idle wait happens on the Event, never under self._lock, so
            # submit()/stop() can always get in to enqueue or close
            while True:
                with self._lock:
                    idle = not self._waiting and not self._running
                    closed = self._closed
                if not idle:
                    break
                if closed:
                    return
                self._wake.wait(self._idle_wait_s)
                self._wake.clear()
            try:
                v = self._resolve_version()
                if self._version is not v:
                    self._flush_running()
                    self._version = v
                self._admit(v)
                self._tick(v)
            except Exception as e:          # noqa: BLE001 - never die quietly
                for seq in list(self._running):
                    self._running.remove(seq)
                    self._fail(seq, e)
                with self._lock:
                    while self._waiting:
                        self._fail(self._waiting.popleft(), e)
