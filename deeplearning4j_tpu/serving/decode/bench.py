"""Closed-loop generation bench: continuous vs static batching through
the server's `/generate` data plane.

Concurrent client threads each run a closed loop of generation requests
(random prompt lengths, random `max_tokens`) against
`InferenceServer.generate` — the exact method the HTTP handler invokes,
minus stdlib-HTTP parsing, matching `serving/bench.py`'s engine-only
protocol. The two arms run the SAME workload in ALTERNATING paired
windows (the repo's standard guard against sandbox load swings):

  * `continuous` — token-granularity admission: a finished sequence's
    batch slot refills between decode ticks;
  * `static` — request-level batching: the batch only refills once
    every running sequence drains (the classic serving baseline).

With length-varied requests the static arm spends its tail ticks at
batch 1 while finished clients wait, so the paired tokens/s ratio
(median over pairs) must exceed 1 — that ratio, plus p50/p99 request
latency and a zero-failed-requests count per arm, is the
`Serving-decode-tokens-per-s` extras block.

Two more verdicts ride along, mirroring the stateless plane's bench:
a same-architecture hot-swap lands mid-window in the first continuous
window (running sequences re-prefill against the new weights; no
request may fail), and the CompileWatcher must report exactly ONE XLA
compile per (model, phase, bucket) across the whole run — both arms,
swap included, share the registry's decode executables.
"""
from __future__ import annotations

import json
import tempfile
import threading
import time
from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["run_decode_bench"]

_VOCAB = 48


def _lm(seed=7, vocab=_VOCAB, width=32, heads=4, t=64, blocks=2):
    from ... import (Adam, EmbeddingSequenceLayer, InputType,
                     MultiLayerNetwork, NeuralNetConfiguration,
                     RnnOutputLayer, TransformerBlock)
    b = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
         .list().layer(EmbeddingSequenceLayer(n_in=vocab, n_out=width)))
    for _ in range(blocks):
        b = b.layer(TransformerBlock(n_heads=heads))
    conf = (b.layer(RnnOutputLayer(n_out=vocab, activation="softmax",
                                   loss="mcxent"))
            .set_input_type(InputType.recurrent(1, t)).build())
    return MultiLayerNetwork(conf).init()


def _window(server, name: str, n_clients: int, requests: int,
            seed: int, swap_source: Optional[str] = None) -> Dict:
    """One measurement window: every client runs `requests` generation
    calls with seed-determined prompt/max_tokens (identical across the
    paired windows). Optionally lands a hot-swap mid-window."""
    lat = [[] for _ in range(n_clients)]
    toks = [0] * n_clients
    errors = []
    barrier = threading.Barrier(n_clients + 1 + (1 if swap_source else 0))

    def client(i):
        r = np.random.default_rng(1000 + i)   # NOT seed-dependent: the
        # paired windows must replay the identical request sequence
        barrier.wait()
        for _ in range(requests):
            prompt = r.integers(0, _VOCAB, int(r.integers(4, 12))).tolist()
            mt = int(r.integers(4, 28))
            t0 = time.perf_counter()
            try:
                res = server.generate(name, prompt, max_tokens=mt,
                                      timeout=600)
            except Exception as e:   # pragma: no cover - surfaced in dict
                errors.append(f"{type(e).__name__}: {e}")
                return
            lat[i].append(time.perf_counter() - t0)
            toks[i] += res["generated_tokens"]

    def swapper():
        barrier.wait()
        time.sleep(0.05)             # land mid-window
        server.registry.swap(name, swap_source)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(n_clients)]
    if swap_source:
        threads.append(threading.Thread(target=swapper, daemon=True))
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    all_lat = np.asarray([v for row in lat for v in row])
    out = {"tokens_per_s": round(sum(toks) / wall, 1) if wall > 0 else 0.0,
           "requests": int(len(all_lat)), "failed": len(errors)}
    if len(all_lat):
        out["p50_ms"] = round(float(np.percentile(all_lat, 50)) * 1e3, 2)
        out["p99_ms"] = round(float(np.percentile(all_lat, 99)) * 1e3, 2)
    if errors:
        out["errors"] = errors[:3]
    return out


def run_decode_bench(n_clients: int = 8, requests_per_client: int = 3,
                     pairs: int = 3, block_len: int = 8,
                     decode_buckets: Sequence[int] = (1, 2, 4, 8),
                     kv_dtype: str = "fp32",
                     swap_check: bool = True) -> Dict:
    """The `Serving-decode-tokens-per-s` extras block for bench.py (see
    module docstring): per-arm tokens/s + p50/p99 per paired window, the
    median continuous/static ratio, the swap-under-generation verdict,
    and the one-compile-per-(phase, bucket) verdict."""
    from ...telemetry import enabled
    from ...util.serializer import ModelSerializer
    from ..registry import ModelRegistry
    from ..server import InferenceServer

    name = "gen"
    opts = dict(block_len=block_len, decode_buckets=tuple(decode_buckets),
                kv_dtype=kv_dtype)
    results: Dict = {"n_clients": n_clients,
                     "requests_per_client": requests_per_client,
                     "pairs": pairs, "kv_dtype": kv_dtype,
                     "decode_buckets": list(decode_buckets)}
    with enabled() as sess, \
            tempfile.TemporaryDirectory() as tmp:
        registry = ModelRegistry(metrics=sess.registry)
        server = InferenceServer(registry, batching=False)
        # engine-only: the HTTP thread is never started; server.generate
        # IS the /generate handler's data plane
        try:
            registry.register(name, _lm(seed=7), buckets=(1,))
            swap_src = None
            if swap_check:
                swap_src = f"{tmp}/swap.zip"
                ModelSerializer.write_model(_lm(seed=8), swap_src)
            # unmeasured warmup pair: pays every decode/prefill compile
            # and hosts the swap-under-generation check, so the measured
            # windows compare pure steady-state scheduling
            warm: Dict = {}
            for mode in ("continuous", "static"):
                server.enable_generation(name, mode=mode, **opts)
                try:
                    warm[mode] = _window(server, name, n_clients,
                                         requests_per_client, seed=-1,
                                         swap_source=(swap_src
                                                      if mode
                                                      == "continuous"
                                                      else None))
                finally:
                    server.disable_generation(name)
            if swap_check:
                results["swap_under_generation"] = {
                    "failed": warm["continuous"]["failed"],
                    "errors": warm["continuous"].get("errors", [])}
            windows, ratios = [], []
            for p in range(pairs):
                pair: Dict = {}
                for mode in ("continuous", "static"):
                    server.enable_generation(name, mode=mode, **opts)
                    try:
                        pair[mode] = _window(
                            server, name, n_clients, requests_per_client,
                            seed=p)
                    finally:
                        server.disable_generation(name)
                windows.append(pair)
                if pair["static"]["tokens_per_s"]:
                    ratios.append(round(pair["continuous"]["tokens_per_s"]
                                        / pair["static"]["tokens_per_s"],
                                        2))
            results["windows"] = windows
            results["paired_ratios"] = ratios
            results["continuous_vs_static"] = (
                sorted(ratios)[len(ratios) // 2] if ratios else None)
            results["failed_requests"] = sum(
                w[m]["failed"] for w in [warm] + windows
                for m in ("continuous", "static"))
            # compile accounting: both arms + the swap share the decode
            # executables — exactly one XLA compile per (phase, bucket)
            prefix = f"serving/{name}:b"
            compiles = {k[len(prefix):]: v["count"]
                        for k, v in sess.compiles.report().items()
                        if k.startswith(prefix)}
            results["compiles_per_phase_bucket"] = compiles
            results["one_compile_per_phase_bucket"] = (
                bool(compiles)
                and all(v == 1 for v in compiles.values()))
        finally:
            server.stop()
    return results


def main(argv=None):
    """`python -m deeplearning4j_tpu.serving.decode.bench` — one JSON
    line."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="deeplearning4j_tpu.serving.decode.bench")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--requests", type=int, default=3)
    ap.add_argument("--pairs", type=int, default=3)
    ap.add_argument("--kv-dtype", default="fp32")
    ap.add_argument("--no-swap", action="store_true")
    args = ap.parse_args(argv)
    out = run_decode_bench(n_clients=args.clients,
                           requests_per_client=args.requests,
                           pairs=args.pairs, kv_dtype=args.kv_dtype,
                           swap_check=not args.no_swap)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
