"""Paged KV-cache arena + block-pool allocator (vLLM/PagedAttention,
Kwon et al., SOSP 2023, applied to our layer stack).

One preallocated arena per servable holds EVERY concurrent sequence's
keys and values:

    arena  [num_blocks, block_len, 2*L, H, Dh]

where channel ``2l`` is layer l's keys and ``2l+1`` its values. A
sequence owns an ordered list of block ids (its BLOCK TABLE); cache slot
``t`` of a sequence lives at ``(table[t // block_len], t % block_len)``.
The compiled steps scatter new K/V by block index and gather a
sequence's whole cache view through its table — HBM is shared at block
granularity, so thousands of sequences with wildly different lengths
pack the arena with at most ``block_len - 1`` wasted slots each, instead
of every sequence reserving a max-context rectangle.

Block 0 is RESERVED (the "trash" block): padded batch slots and
overflow prompt positions write there and their reads are always masked
by the per-row valid length, so the compiled step needs no branches for
dead rows. Allocation never hands out block 0.

int8 KV (``kv_dtype="int8"``): the arena stores int8 plus a per-slot
scale arena ``[num_blocks, block_len, 2*L]`` — `serving/quantize.py`'s
per-tensor symmetric scheme (scale = absmax / 127) applied per cached
(position, layer, K|V) vector, quantized at scatter time and
dequantized inside the gather. Halves-of-halves memory for the cache at
~1e-2-level logit drift; the equivalence/bit-exactness contracts are
asserted on the fp32 cache only.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List

import jax.numpy as jnp

__all__ = ["KvCacheSpec", "BlockPool", "OutOfBlocksError"]


class OutOfBlocksError(RuntimeError):
    """Allocation against an exhausted pool — the scheduler's cue to
    evict (preempt) a running sequence."""


@dataclass(frozen=True)
class KvCacheSpec:
    """Static shape contract of one servable's paged cache. Part of the
    compiled signature: every decode executable is specialized to it."""

    n_layers: int          # transformer blocks L (arena channels = 2L)
    n_heads: int
    d_head: int
    block_len: int         # cache slots per block
    num_blocks: int        # arena height, INCLUDING the reserved block 0
    max_context: int       # hard cap (the positional table length)
    kv_dtype: str = "fp32"   # "fp32" | "int8"

    def __post_init__(self):
        if self.kv_dtype not in ("fp32", "int8"):
            raise ValueError(f"kv_dtype must be fp32|int8, got "
                             f"{self.kv_dtype!r}")
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is the "
                             "reserved trash block)")
        if self.block_len < 1 or self.max_context < 1:
            raise ValueError("block_len and max_context must be >= 1")

    @property
    def table_width(self) -> int:
        """Block-table columns per sequence: enough for max_context."""
        return -(-self.max_context // self.block_len)

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks a sequence of `n_tokens` cache slots occupies."""
        return -(-max(1, n_tokens) // self.block_len)

    def arena_nbytes(self) -> int:
        slots = self.num_blocks * self.block_len * 2 * self.n_layers
        per = self.n_heads * self.d_head
        if self.kv_dtype == "int8":
            return slots * per + slots * 4      # int8 data + f32 scales
        return slots * per * 4


def make_cache(spec: KvCacheSpec) -> Dict[str, jnp.ndarray]:
    """Fresh zeroed cache pytree — ONE donated argument of the compiled
    steps. fp32: {"kv": arena}; int8 adds the per-slot scale arena."""
    shape = (spec.num_blocks, spec.block_len, 2 * spec.n_layers,
             spec.n_heads, spec.d_head)
    if spec.kv_dtype == "int8":
        return {"kv": jnp.zeros(shape, jnp.int8),
                "scale": jnp.ones(shape[:3], jnp.float32)}
    return {"kv": jnp.zeros(shape, jnp.float32)}


def pack_kv(spec: KvCacheSpec, x):
    """Prepare K or V slices [..., H, Dh] for a cache scatter. Returns
    (values, scales_or_None): int8 quantizes per leading-index vector
    (per-tensor symmetric over the trailing [H, Dh])."""
    if spec.kv_dtype != "int8":
        return x, None
    absmax = jnp.max(jnp.abs(x), axis=(-2, -1))
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x / scale[..., None, None]), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def unpack_kv(spec: KvCacheSpec, q, scale):
    """Dequantize a gathered cache view (inverse of `pack_kv`)."""
    if spec.kv_dtype != "int8":
        return q
    return q.astype(jnp.float32) * scale[..., None, None]


class BlockPool:
    """Host-side free-list allocator over the arena's block ids.

    The pool owns the DEVICE cache arrays too (`cache` — replaced after
    every compiled step with the donated step's output), so eviction,
    reuse and accounting share one lock. Thread-safe; the scheduler
    worker is the only writer of `cache`."""

    def __init__(self, spec: KvCacheSpec, metrics=None, name: str = "model"):
        self.spec = spec
        self.name = name
        self.cache = make_cache(spec)
        self._lock = threading.Lock()
        # LIFO free list: a just-freed (hot, possibly still resident)
        # block is reused first — also what makes the reuse-after-evict
        # bit-exactness test deterministic about WHICH blocks recycle
        self._free: List[int] = list(range(spec.num_blocks - 1, 0, -1))
        self._blocks_g = None
        if metrics is not None:
            self._blocks_g = metrics.gauge(
                "dl4j_decode_kv_blocks",
                "paged KV arena blocks by state (block 0 reserved)",
                labels=("model", "state"))
            self._report()

    def _report(self):
        if self._blocks_g is not None:
            free = len(self._free)
            self._blocks_g.set(free, model=self.name, state="free")
            self._blocks_g.set(self.spec.usable_blocks - free,
                               model=self.name, state="used")

    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def used_blocks(self) -> int:
        return self.spec.usable_blocks - self.free_blocks()

    def alloc(self, n: int) -> List[int]:
        """Take `n` blocks or raise OutOfBlocksError (all-or-nothing: a
        partial grab under pressure would deadlock two growing
        sequences against each other)."""
        with self._lock:
            if n > len(self._free):
                raise OutOfBlocksError(
                    f"{self.name}: need {n} KV blocks, {len(self._free)} "
                    f"free of {self.spec.usable_blocks}")
            taken = [self._free.pop() for _ in range(n)]
            self._report()
            return taken

    def release(self, blocks: List[int]):
        with self._lock:
            for b in blocks:
                if not 0 < b < self.spec.num_blocks:
                    raise ValueError(f"bad KV block id {b}")
            self._free.extend(reversed(blocks))
            self._report()
