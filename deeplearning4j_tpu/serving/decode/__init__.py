"""Autoregressive decode plane (ISSUE 16): KV-cache generation beside
the stateless serving plane.

  * `cache`     — the paged KV-block arena + host-side block-pool
                  allocator (vLLM/PagedAttention-style block tables).
  * `engine`    — the KV-cache forward: AOT-compiled prefill and
                  decode-tick steps over TransformerBlock's decode mode.
  * `scheduler` — Orca-style token-granularity continuous batching:
                  sequences join and leave the decode batch between
                  ticks.
  * `bench`     — the closed-loop continuous-vs-static generation bench.
"""
from .cache import BlockPool, KvCacheSpec, OutOfBlocksError
from .engine import DecodeEngine
from .scheduler import GenerationError, GenerationScheduler

__all__ = ["BlockPool", "KvCacheSpec", "OutOfBlocksError", "DecodeEngine",
           "GenerationScheduler", "GenerationError"]
