"""The KV-cache generation forward: AOT-compiled prefill + decode-tick
steps over the transformer stack's decode mode.

Two compiled signatures per servable, both AOT-lowered through the
registry's shared executable cache (`ModelRegistry.compile_cached`, keys
namespaced ("decode", sig, phase, bucket)) so the server-lifetime
invariant of the stateless plane extends to generation: ONE XLA compile
per (model, bucket, phase), no cold compile on any request path, and a
same-architecture hot-swap reuses every decode executable.

  prefill(data, cache, tokens [1, Tp], lengths [1], tables [1, W])
      -> (cache', next_logits [1, V])
    The whole (right-padded) prompt runs as one causal forward — the
    standard full-sequence math, row-masked by `lengths` — while every
    layer's K/V projections scatter into the paged arena through the
    sequence's block table. Prompt attention uses the LOCAL (exact)
    projections, so int8 cache quantization only affects later ticks.

  decode(data, cache, tokens [B], positions [B], tables [B, W])
      -> (cache', logits [B, V])
    One token per row: embed at its absolute position, scatter its K/V
    into the arena, gather the row's whole cache view through its block
    table, attend with causal offsets + per-row valid length
    (`kernels.attention` kv_length path), project logits.

The cache pytree is DONATED: the arena updates in place on device, so a
tick costs one [B,*] pass plus the table gathers, never an arena copy.
Rows are independent throughout (no cross-row reductions), which is
what makes token-granularity join/leave bit-exact for the rows that
stay — the continuous-batching isolation contract the tests assert.
"""
from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...telemetry.compile_watch import watch_compiles
from ..registry import ServingError, _abstract_sig
from .cache import BlockPool, KvCacheSpec, make_cache, pack_kv, unpack_kv

__all__ = ["DecodeEngine", "build_prefill_fn", "build_decode_fn",
           "split_decode_layers"]


def split_decode_layers(model):
    """(embedding, [blocks...], head) of a generate-capable stack, or
    ServingError. The decode plane supports exactly the GPT shape:
    EmbeddingSequenceLayer -> TransformerBlock* -> an output layer with
    `preout` (logits before the softmax activation)."""
    from ...nn.layers.transformer import (EmbeddingSequenceLayer,
                                          TransformerBlock)

    layers = getattr(model, "layers", None)
    if not layers or len(layers) < 3 \
            or not isinstance(layers[0], EmbeddingSequenceLayer) \
            or not all(isinstance(b, TransformerBlock)
                       for b in layers[1:-1]) \
            or not hasattr(layers[-1], "preout"):
        raise ServingError(
            "generation needs an EmbeddingSequenceLayer -> "
            "TransformerBlock* -> output-layer stack; got "
            f"{[type(l).__name__ for l in (layers or [])]}")
    if getattr(model.conf, "preprocessors", None):
        raise ServingError(
            "generation does not support input preprocessors between "
            "decode layers")
    return layers[0], list(layers[1:-1]), layers[-1]


def _cache_arg_specs(spec: KvCacheSpec):
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), make_cache(spec))


def _scatter(spec, kv, sc, values, blk, off, channel):
    """Write K or V `values` (leading index shape == blk/off) into the
    arena at (blk, off, channel), quantizing for int8 caches."""
    vals, scales = pack_kv(spec, values)
    kv = kv.at[blk, off, channel].set(vals)
    if scales is not None:
        sc = sc.at[blk, off, channel].set(scales)
    return kv, sc


def _gather(spec, kv, sc, tables, channel):
    """Sequence-major cache view [B, W*block_len, H, Dh] of one channel,
    dequantized: every row reads its own blocks through its table (dead
    table slots point at the trash block; always length-masked)."""
    view = kv[:, :, channel][tables]            # [B, W, bl, H, Dh]
    b = tables.shape[0]
    view = view.reshape(b, -1, spec.n_heads, spec.d_head)
    if sc is None:
        return view
    scale = sc[:, :, channel][tables].reshape(b, -1)
    return unpack_kv(spec, view, scale)


def _repack(cache, kv, sc):
    return {"kv": kv, "scale": sc} if "scale" in cache else {"kv": kv}


def build_prefill_fn(model, snapshot, spec: KvCacheSpec):
    """Pure prefill step (see module docstring). Closed over the layer
    configs and the snapshot's dequantization structure only — the flat
    `data` tuple stays a runtime argument, so re-quantized checkpoints
    share the executable (the stateless plane's convention)."""
    emb, blocks, head = split_decode_layers(model)

    def prefill(data, cache, tokens, lengths, tables):
        params = snapshot.rebuild(data)
        b, tp = tokens.shape
        pos = jnp.broadcast_to(jnp.arange(tp, dtype=jnp.int32), (b, tp))
        x = emb.decode_embed(params[0], tokens, pos)
        kv, sc = cache["kv"], cache.get("scale")
        tidx = jnp.arange(tp, dtype=jnp.int32)
        # right-padded prompt slots scatter too (their K/V derive
        # deterministically from the pad token, and table slots past the
        # allocation point at the trash block), so a reused block is
        # overwritten wholesale — reuse is bit-identical to fresh
        blk = tables[:, tidx // spec.block_len]
        off = jnp.broadcast_to(tidx % spec.block_len, (b, tp))
        for i, layer in enumerate(blocks):
            q, k, v = layer.decode_qkv(params[1 + i], x)
            kv, sc = _scatter(spec, kv, sc, k, blk, off, 2 * i)
            kv, sc = _scatter(spec, kv, sc, v, blk, off, 2 * i + 1)
            a = layer.decode_attend(q, k, v, pos, lengths)
            x = layer.decode_finish(params[1 + i], x, a)
        logits = head.preout(params[-1], {}, x)          # [B, Tp, V]
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
        return _repack(cache, kv, sc), last.astype(jnp.float32)

    return prefill


def build_decode_fn(model, snapshot, spec: KvCacheSpec):
    """Pure one-token decode tick (see module docstring)."""
    emb, blocks, head = split_decode_layers(model)

    def decode(data, cache, tokens, positions, tables):
        params = snapshot.rebuild(data)
        b = tokens.shape[0]
        lengths = positions + 1          # pad rows: position 0 -> length 1
        x = emb.decode_embed(params[0], tokens[:, None], positions[:, None])
        kv, sc = cache["kv"], cache.get("scale")
        blk = tables[jnp.arange(b), positions // spec.block_len]
        off = positions % spec.block_len
        for i, layer in enumerate(blocks):
            q, k, v = layer.decode_qkv(params[1 + i], x)
            kv, sc = _scatter(spec, kv, sc, k[:, 0], blk, off, 2 * i)
            kv, sc = _scatter(spec, kv, sc, v[:, 0], blk, off, 2 * i + 1)
            k_all = _gather(spec, kv, sc, tables, 2 * i)
            v_all = _gather(spec, kv, sc, tables, 2 * i + 1)
            a = layer.decode_attend(q, k_all, v_all, positions[:, None],
                                    lengths)
            x = layer.decode_finish(params[1 + i], x, a)
        logits = head.preout(params[-1], {}, x)[:, 0]
        return _repack(cache, kv, sc), logits.astype(jnp.float32)

    return decode


def _pow2_buckets(lo: int, hi: int) -> Tuple[int, ...]:
    out, b = [], lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(sorted(set(out)))


class DecodeEngine:
    """Compiled-step frontend for one servable's generation plane.

    Owns the static cache geometry (`spec`) and the bucket ladders; the
    executables live in the registry's per-model cache so swaps and the
    compile accounting behave exactly like the stateless runners. The
    scheduler calls `run_prefill` / `run_tick` with host data; both only
    ever invoke finished executables."""

    def __init__(self, registry, name: str, *, block_len: int = 16,
                 num_blocks: Optional[int] = None, kv_dtype: str = "fp32",
                 decode_buckets: Sequence[int] = (1, 2, 4, 8),
                 prompt_buckets: Optional[Sequence[int]] = None):
        self.registry = registry
        self.name = name
        v = registry.get(name)
        if v.model is None:
            raise ServingError(
                f"{name}: servable holds no live model object — "
                "generation needs the layer stack")
        emb, blocks, head = split_decode_layers(v.model)
        d = emb.n_out
        heads = blocks[0].n_heads
        if any(blk.n_heads != heads for blk in blocks):
            raise ServingError(f"{name}: blocks disagree on n_heads")
        max_context = int(np.asarray(v.model.params[0]["P"]).shape[0])
        self.decode_buckets = tuple(sorted(int(b) for b in decode_buckets))
        if num_blocks is None:
            # default: full residency for a max-bucket batch of
            # max-context sequences, plus the reserved trash block
            per_seq = -(-max_context // block_len)
            num_blocks = 1 + per_seq * self.decode_buckets[-1]
        self.spec = KvCacheSpec(
            n_layers=len(blocks), n_heads=heads, d_head=d // heads,
            block_len=int(block_len), num_blocks=int(num_blocks),
            max_context=max_context, kv_dtype=kv_dtype)
        self.prompt_buckets = (tuple(sorted(int(b) for b in prompt_buckets))
                               if prompt_buckets else
                               _pow2_buckets(min(8, max_context),
                                             max_context))
        if self.prompt_buckets[-1] > max_context:
            raise ServingError(
                f"{name}: prompt bucket {self.prompt_buckets[-1]} exceeds "
                f"the positional table ({max_context})")

    # -- geometry --------------------------------------------------------
    @property
    def max_context(self) -> int:
        return self.spec.max_context

    def new_pool(self, metrics=None) -> BlockPool:
        return BlockPool(self.spec, metrics=metrics, name=self.name)

    def prompt_bucket_for(self, n: int) -> int:
        for b in self.prompt_buckets:
            if n <= b:
                return b
        raise ServingError(
            f"{self.name}: prompt of {n} tokens exceeds the context "
            f"window {self.max_context}")

    def decode_bucket_for(self, rows: int) -> int:
        for b in self.decode_buckets:
            if rows <= b:
                return b
        raise ServingError(
            f"{self.name}: decode batch {rows} exceeds bucket "
            f"{self.decode_buckets[-1]}")

    # -- AOT executables -------------------------------------------------
    def _check_version(self, v):
        # a hot-swap to a different architecture would silently change
        # the cache geometry under live sequences — fail loudly instead
        emb, blocks, _ = split_decode_layers(v.model)
        if (len(blocks) != self.spec.n_layers
                or blocks[0].n_heads != self.spec.n_heads
                or emb.n_out != self.spec.n_heads * self.spec.d_head):
            raise ServingError(
                f"{self.name}: swapped architecture no longer matches the "
                "generation cache geometry; re-enable generation")
        return v

    def prefill_exec(self, v, t_bucket: int):
        sig = _abstract_sig(v.snapshot, v.state, v.precision)
        spec = self.spec

        def build():
            prefill_step = watch_compiles(
                jax.jit(build_prefill_fn(v.model, v.snapshot, spec),
                        donate_argnums=(1,)),
                f"serving/decode:{self.name}/prefill-t{t_bucket}").__wrapped__
            i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)
            return prefill_step.lower(
                v.snapshot.data, _cache_arg_specs(spec),
                i32(1, t_bucket), i32(1), i32(1, spec.table_width)
            ).compile()

        return self.registry.compile_cached(
            self.name, ("decode", sig, "prefill", t_bucket), build,
            f"prefill-t{t_bucket}")

    def decode_exec(self, v, bucket: int):
        sig = _abstract_sig(v.snapshot, v.state, v.precision)
        spec = self.spec

        def build():
            decode_step = watch_compiles(
                jax.jit(build_decode_fn(v.model, v.snapshot, spec),
                        donate_argnums=(1,)),
                f"serving/decode:{self.name}/tick-b{bucket}").__wrapped__
            i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)
            return decode_step.lower(
                v.snapshot.data, _cache_arg_specs(spec),
                i32(bucket), i32(bucket), i32(bucket, spec.table_width)
            ).compile()

        return self.registry.compile_cached(
            self.name, ("decode", sig, "tick", bucket), build,
            f"decode-b{bucket}")

    # -- host-facing phases ----------------------------------------------
    def _pad_table(self, table: Sequence[int]) -> List[int]:
        w = self.spec.table_width
        if len(table) > w:
            raise ServingError(f"{self.name}: block table of {len(table)} "
                               f"exceeds width {w}")
        return list(table) + [0] * (w - len(table))

    def run_prefill(self, v, pool: BlockPool, prompt: Sequence[int],
                    table: Sequence[int], ctx=None) -> np.ndarray:
        """Write `prompt`'s K/V through `table`, return the next-token
        logits [V]. Batch 1: one compile per prompt bucket. `ctx` is an
        optional TraceContext — bucket_select + prefill child spans."""
        self._check_version(v)
        n = len(prompt)
        t_sel = time.perf_counter()
        tb = self.prompt_bucket_for(n)
        if ctx is not None:
            ctx.emit("bucket_select", t_sel, time.perf_counter(),
                     model=self.name, phase="prefill", bucket=tb, tokens=n)
        tokens = np.zeros((1, tb), np.int32)
        tokens[0, :n] = np.asarray(prompt, np.int32)
        exec_ = self.prefill_exec(v, tb)
        t0 = time.perf_counter()
        pool.cache, logits = exec_(
            v.snapshot.data, pool.cache, jnp.asarray(tokens),
            jnp.asarray([n], jnp.int32),
            jnp.asarray([self._pad_table(table)], jnp.int32))
        out = np.asarray(logits)[0]      # host sync: span covers real work
        if ctx is not None:
            ctx.emit("prefill", t0, time.perf_counter(),
                     model=self.name, bucket=tb, tokens=n)
        return out

    def run_tick(self, v, pool: BlockPool, tokens: Sequence[int],
                 positions: Sequence[int], tables: Sequence[Sequence[int]],
                 bucket: int, ctxs=None) -> np.ndarray:
        """One decode tick over `len(tokens)` live rows padded up to
        `bucket` (pad rows park at the trash block, length 1, and their
        logits are discarded by the caller). Returns logits [rows, V].
        `ctxs` is an optional per-row TraceContext list — every traced
        row gets a decode_tick child span for this shared step."""
        self._check_version(v)
        rows = len(tokens)
        if rows > bucket:
            raise ServingError(f"{rows} rows > decode bucket {bucket}")
        tok = np.zeros(bucket, np.int32)
        pos = np.zeros(bucket, np.int32)
        tab = np.zeros((bucket, self.spec.table_width), np.int32)
        tok[:rows] = np.asarray(tokens, np.int32)
        pos[:rows] = np.asarray(positions, np.int32)
        for i, t in enumerate(tables):
            tab[i] = self._pad_table(t)
        exec_ = self.decode_exec(v, bucket)
        t0 = time.perf_counter()
        pool.cache, logits = exec_(
            v.snapshot.data, pool.cache, jnp.asarray(tok),
            jnp.asarray(pos), jnp.asarray(tab))
        out = np.asarray(logits)[:rows]  # host sync: span covers real work
        if ctxs:
            t1 = time.perf_counter()
            for i, c in enumerate(ctxs[:rows]):
                if c is not None:
                    c.emit("decode_tick", t0, t1, model=self.name,
                           bucket=bucket, rows=rows,
                           position=int(positions[i]))
        return out
