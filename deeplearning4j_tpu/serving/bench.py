"""Serving latency/throughput bench: concurrent closed-loop clients
against the registry+batcher data plane, batched vs unbatched.

Each client thread runs a closed loop (send one request, wait for the
response, repeat) of single-row predicts against the SAME engine the
HTTP server fronts (`InferenceServer.predict`) — so the numbers measure
the serving data plane (validation, queue wait, padded compiled forward,
scatter) without conflating stdlib-HTTP parsing overhead. Reported per
concurrency level: p50/p99 latency (ms) and aggregate requests/s, for
the batched path (DynamicBatcher coalescing) and the unbatched path
(per-request padded forward on the same compiled bucket-1 executable —
the toy-server architecture, but with its compile already amortized).

Two configs ship: `lenet` (the zoo conv model — on a CPU sandbox its
per-row conv compute scales nearly linearly with batch size, so batching
mostly amortizes dispatch; on a real accelerator the conv itself
amortizes) and `mlp128` (a dispatch-bound 784->128->10 head — the
regime, on any hardware, where coalescing wins big). At the top
concurrency level the two arms run in ALTERNATING paired reps and the
speedup is the median of per-pair ratios (the repo's standard guard
against this sandbox's load swings — a contaminated capture shows up as
spread in the artifact).

Two invariants are checked and reported alongside the numbers:
  * exactly ONE XLA compile per (model, shape-bucket) across the whole
    run — hot-swaps included — via the CompileWatcher;
  * a hot-swap under sustained 16-client load completes with zero failed
    requests and per-client monotonically non-decreasing versions.
"""
from __future__ import annotations

import json
import tempfile
import threading
import time
from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["run_serving_bench"]


def _make_lenet():
    from ..models.zoo import lenet_mnist
    return lenet_mnist(seed=7).init()


def _make_mlp128():
    from .. import (DenseLayer, InputType, MultiLayerNetwork,
                    NeuralNetConfiguration, OutputLayer, Sgd)
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=128, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(784)).build())
    return MultiLayerNetwork(conf).init()


_MODELS = {"lenet": _make_lenet, "mlp128": _make_mlp128}


def _closed_loop(predict, n_clients: int, n_requests: int,
                 make_row) -> Dict:
    """Run `n_clients` closed-loop threads of `n_requests` each; returns
    p50/p99 per-request latency (ms) and aggregate requests/s."""
    lat = [[] for _ in range(n_clients)]
    errors = []
    barrier = threading.Barrier(n_clients + 1)

    def client(i):
        x = make_row(i)
        barrier.wait()
        for _ in range(n_requests):
            t0 = time.perf_counter()
            try:
                predict(x)
            except Exception as e:   # pragma: no cover - surfaced in dict
                errors.append(f"{type(e).__name__}: {e}")
                return
            lat[i].append(time.perf_counter() - t0)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(n_clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    all_lat = np.asarray([v for row in lat for v in row])
    if not len(all_lat):
        return {"req_s": 0.0, "p50_ms": 0.0, "p99_ms": 0.0,
                "errors": errors[:3]}
    out = {"req_s": round(len(all_lat) / wall, 1) if wall > 0 else 0.0,
           "p50_ms": round(float(np.percentile(all_lat, 50)) * 1e3, 3),
           "p99_ms": round(float(np.percentile(all_lat, 99)) * 1e3, 3)}
    if errors:
        out["errors"] = errors[:3]
    return out


def _median(vals):
    vals = sorted(vals)
    return vals[len(vals) // 2]


def _swap_under_load(server, registry, name: str, swap_source,
                     n_clients: int = 16, n_requests: int = 60) -> Dict:
    """Hammer the batched path while a hot-swap lands mid-flight; no
    request may fail and each client must observe non-decreasing
    versions."""
    errors = []
    monotonic = [True] * n_clients
    versions_seen = set()
    barrier = threading.Barrier(n_clients + 2)
    shape = registry.get(name).example_shape

    def client(i):
        x = np.random.default_rng(i).normal(
            size=(1,) + shape).astype(np.float32)
        last = 0
        barrier.wait()
        for _ in range(n_requests):
            try:
                _, version, _ = server.predict(name, x, batched=True)
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")
                return
            if version < last:
                monotonic[i] = False
            last = version
            versions_seen.add(version)

    def swapper():
        barrier.wait()
        time.sleep(0.05)     # land mid-flight
        registry.swap(name, swap_source)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(n_clients)]
    sw = threading.Thread(target=swapper, daemon=True)
    for t in threads:
        t.start()
    sw.start()
    barrier.wait()
    for t in threads + [sw]:
        t.join()
    return {"requests": n_clients * n_requests,
            "failed": len(errors), "errors": errors[:3],
            "versions_seen": sorted(versions_seen),
            "versions_monotonic": all(monotonic)}


def _bench_model(server, registry, sess, name: str, builder,
                 clients: Sequence[int], requests_per_client: int,
                 buckets: Sequence[int], pairs_at_top: int,
                 swap_check: bool) -> Dict:
    registry.register(name, builder())
    shape = registry.get(name).example_shape

    def make_row(i):
        return np.random.default_rng(i).normal(
            size=(1,) + shape).astype(np.float32)

    def unbatched(x):
        return server.predict(name, x, batched=False)

    def batched(x):
        return server.predict(name, x, batched=True)

    # warm both paths (dispatch warmth, NOT compile — compiles all
    # happened at register() and are asserted below)
    unbatched(make_row(0))
    batched(make_row(0))

    out: Dict = {}
    top = max(clients)
    for c in clients:
        if c != top:
            out[str(c)] = {"unbatched": _closed_loop(
                unbatched, c, requests_per_client, make_row),
                "batched": _closed_loop(
                    batched, c, requests_per_client, make_row)}
            continue
        # top level: alternating paired reps, median-of-ratios
        unb_reps, bat_reps, ratios = [], [], []
        for _ in range(pairs_at_top):
            u = _closed_loop(unbatched, c, requests_per_client, make_row)
            b = _closed_loop(batched, c, requests_per_client, make_row)
            unb_reps.append(u)
            bat_reps.append(b)
            if u["req_s"]:
                ratios.append(round(b["req_s"] / u["req_s"], 2))
        by_rate = lambda reps: sorted(  # noqa: E731 - median-rate rep
            reps, key=lambda r: r["req_s"])[len(reps) // 2]
        out[str(c)] = {
            "unbatched": by_rate(unb_reps),
            "batched": by_rate(bat_reps),
            "req_s_spread": {
                "unbatched": [min(r["req_s"] for r in unb_reps),
                              max(r["req_s"] for r in unb_reps)],
                "batched": [min(r["req_s"] for r in bat_reps),
                            max(r["req_s"] for r in bat_reps)]},
            "paired_ratios": ratios,
        }
        out["batched_vs_unbatched_speedup"] = _median(ratios) if ratios \
            else None

    if swap_check:
        with tempfile.TemporaryDirectory() as d:
            ckpt = f"{d}/swap.zip"
            from ..util.serializer import ModelSerializer
            ModelSerializer.write_model(builder(), ckpt)
            out["swap_under_load"] = _swap_under_load(
                server, registry, name, ckpt)

    # compile accounting: exactly one XLA compile per (model, bucket)
    # across the whole run, swaps included (same-architecture swaps
    # reuse executables)
    prefix = f"serving/{name}:b"
    compiles = {k[len(prefix):]: v["count"]
                for k, v in sess.compiles.report().items()
                if k.startswith(prefix)}
    out["compiles_per_bucket"] = compiles
    out["one_compile_per_bucket"] = (
        set(compiles) == {str(b) for b in buckets}
        and all(v == 1 for v in compiles.values()))
    return out


def run_serving_bench(clients: Sequence[int] = (1, 8, 32),
                      requests_per_client: int = 150,
                      buckets: Sequence[int] = (1, 8, 32),
                      max_wait_us: int = 5000,
                      models: Sequence[str] = ("lenet", "mlp128"),
                      pairs_at_top: int = 3,
                      swap_check: bool = True) -> Dict:
    """The `Serving-latency` extras block for bench.py: per-model
    batched/unbatched p50/p99 + req/s at each concurrency level, the
    median paired speedup at the top level, hot-swap-under-load and
    one-compile-per-bucket verdicts."""
    from ..telemetry import enabled
    from .registry import ModelRegistry
    from .server import InferenceServer

    results: Dict = {"clients": list(clients),
                     "rows_per_request": 1,
                     "requests_per_client": requests_per_client,
                     "buckets": list(buckets),
                     "max_wait_us": max_wait_us}
    with enabled() as sess:
        registry = ModelRegistry(buckets=buckets, metrics=sess.registry)
        server = InferenceServer(registry, batching=True,
                                 max_wait_us=max_wait_us)
        # engine-only: the HTTP thread is never started; server.predict
        # IS the handler's data plane
        try:
            for name in models:
                results[name] = _bench_model(
                    server, registry, sess, name, _MODELS[name], clients,
                    requests_per_client, buckets, pairs_at_top,
                    swap_check=swap_check and name == "lenet")
        finally:
            server.stop()
    results["speedup_at_max_clients"] = {
        name: results[name].get("batched_vs_unbatched_speedup")
        for name in models}
    return results


def main(argv=None):
    """`python -m deeplearning4j_tpu.serving.bench` — one JSON line."""
    import argparse

    ap = argparse.ArgumentParser(prog="deeplearning4j_tpu.serving.bench")
    ap.add_argument("--clients", default="1,8,32")
    ap.add_argument("--requests", type=int, default=150)
    ap.add_argument("--max-wait-us", type=int, default=5000)
    ap.add_argument("--models", default="lenet,mlp128")
    ap.add_argument("--pairs", type=int, default=3)
    args = ap.parse_args(argv)
    out = run_serving_bench(
        clients=tuple(int(c) for c in args.clients.split(",")),
        requests_per_client=args.requests,
        max_wait_us=args.max_wait_us,
        models=tuple(args.models.split(",")),
        pairs_at_top=args.pairs)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
