"""Production inference HTTP plane: the registry + batcher behind a
threaded stdlib server (the grown-up version of `modelimport/server.py`'s
toy `/output` endpoint).

Endpoints
    GET  /v1/models                   -> {"models": [info, ...]}
    GET  /v1/models/<name>            -> info
    POST /v1/models/<name>/predict    {"features": [[...]], "batched": bool?}
                                      -> {"output": ..., "version": N,
                                          "batched": bool}
    POST /v1/models/<name>/swap       {"source": "/ckpt.zip"|dir|h5,
                                       "precision"?, "buckets"?,
                                       "input_shape"?}
                                      -> {"model":, "version":, ...}
    POST /v1/models/<name>/generate   {"prompt": [ids], "max_tokens"?,
                                       "temperature"?, "stop"?: [ids],
                                       "seed"?}
                                      -> {"tokens": [ids],
                                          "finish_reason": ..., ...}
    GET  /v1/models/<name>/canary     -> {"active": bool, "version"?,
                                          "fraction"?, "arms"?: {...}}
    POST /v1/models/<name>/canary     {"action": "start"|"promote"|
                                       "rollback", "source"? (start),
                                       "fraction"?, "precision"?,
                                       "buckets"?, "input_shape"?}
                                      -> candidate/stable info

Canary routing: while a canary is active (started by the continual
plane's ContinualTrainer or via POST /canary), a deterministic fraction
of predict/generate traffic serves on the candidate version through its
OWN batcher/scheduler (per-arm queues: retiring the candidate never
touches in-flight stable requests), and every request's latency, error,
and SLO-breach outcome is observed per arm into the registry's
CanaryState — the signal that drives automatic promotion or rollback.
    GET  /healthz                     -> {"status": "ok", "models": {...}}
    GET  /metrics                     -> Prometheus text (0.0.4)
    GET  /debug/flightrecord          -> flight-recorder view: last guard
                                         dump + the live event ring

Tracing: every request gets a `TraceContext` (trace id + SLO tier from
the `X-DL4J-SLO-Tier` header); the trace id comes back on EVERY
response as the `X-DL4J-Trace` header and inside every structured error
body, and the request's spans (root + queue_wait/bucket_select/prefill/
decode_tick/scatter through the batching planes) land in the active
telemetry session's Tracer as one connected Perfetto track. Latency is
also observed per tier into the SLO surface (`dl4j_slo_latency_seconds`,
`dl4j_slo_burn_rate`).

Error semantics: 400 + {"error": ...} for client mistakes (malformed
JSON, missing keys, shape mismatches, unknown precision), 404 for
unknown models/paths, 500 only for genuine server faults. Hot-swap via
POST /swap compiles the incoming version entirely off the request path
and flips atomically — concurrent predicts never fail or observe a
version decrease during a swap.
"""
from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

import numpy as np

from ..telemetry.recorder import flight_recorder
from ..telemetry.trace_context import DEFAULT_TIER, SloSurface, TraceContext
from .batcher import BatcherClosedError, DynamicBatcher
from .decode.scheduler import GenerationScheduler
from .registry import (ModelRegistry, ServingError, UnknownModelError,
                       _validate_features)

__all__ = ["InferenceServer", "ClientError"]

_MODEL_PATH = re.compile(
    r"^/v1/models/([^/]+)(?:/(predict|swap|generate|canary))?$")


class ClientError(ValueError):
    """Request the client got wrong -> HTTP 400 with a structured body."""


def parse_json_body(handler: BaseHTTPRequestHandler) -> Dict:
    """Read+parse a JSON request body; client mistakes raise ClientError
    (-> 400), never a bare exception (-> 500). Shared with the legacy
    Keras backend server so both planes agree on error semantics."""
    try:
        n = int(handler.headers.get("Content-Length", "0"))
    except ValueError:
        raise ClientError("invalid Content-Length header") from None
    raw = handler.rfile.read(n) if n else b""
    if not raw:
        raise ClientError("empty request body (expected JSON)")
    try:
        body = json.loads(raw)
    except ValueError as e:
        raise ClientError(f"malformed JSON body: {e}") from None
    if not isinstance(body, dict):
        raise ClientError("JSON body must be an object")
    return body


def require(body: Dict, key: str):
    if key not in body:
        raise ClientError(f"missing required key {key!r}")
    return body[key]


class InferenceServer:
    """HTTP front end over a ModelRegistry with per-model dynamic
    batching. `batching=False` serves every request on the direct
    (chunk+pad, still AOT-compiled) path — the bench's unbatched arm."""

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 batching: bool = True, max_wait_us: int = 2000,
                 max_batch: Optional[int] = None,
                 slo_targets: Optional[Dict[str, float]] = None):
        self.registry = registry if registry is not None else ModelRegistry()
        self.batching = bool(batching)
        self.max_wait_us = int(max_wait_us)
        self.max_batch = max_batch
        # both maps are keyed (model name, arm): per-arm queues mean a
        # canary promote/rollback retires the candidate's batcher and
        # scheduler without ever touching in-flight stable requests
        self._batchers: Dict[Tuple[str, str], DynamicBatcher] = {}
        self._batchers_lock = threading.Lock()
        self._schedulers: Dict[Tuple[str, str], GenerationScheduler] = {}
        self._sched_opts: Dict[str, Dict] = {}
        self._stopping = False
        self._started_at = time.time()
        m = self.registry.metrics
        self._requests = m.counter(
            "dl4j_serving_requests_total",
            "serving HTTP requests by endpoint and status code",
            labels=("model", "endpoint", "code"))
        self._latency = m.histogram(
            "dl4j_serving_latency_seconds",
            "request latency through the serving data plane (queue wait + "
            "forward) by path", labels=("model", "path"))
        self.slo = SloSurface(m, targets=slo_targets)
        self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    # -- data plane (also driven directly by serving/bench.py) ----------
    def _batcher(self, name: str, arm: str = "stable") -> DynamicBatcher:
        b = self._batchers.get((name, arm))  # GIL-atomic fast path, no mutex
        if b is not None:
            return b
        with self._batchers_lock:
            if self._stopping:
                # an in-flight request racing stop() must not install a
                # fresh batcher after the drain pass — its worker would
                # leak. Checked INSIDE the lock: stop() sets the flag
                # before taking this lock for the drain, so a creator
                # either finishes first (and gets drained) or sees it
                raise BatcherClosedError("server is stopping")
            b = self._batchers.get((name, arm))
            if b is None:
                reg = self.registry

                def runner(x_padded, bucket, _name=name, _arm=arm):
                    # per-flush arm resolution: a canary batcher serves
                    # the candidate while one is active and falls back
                    # to stable the moment it is promoted/rolled back
                    v = reg.arm_version(_name, _arm)
                    if bucket in v.runners:
                        return v.run_padded(x_padded, bucket), v.version
                    # a swap changed the bucket set between enqueue and
                    # flush: serve via the direct path (pad rows ride
                    # along; the batcher scatters only the real rows)
                    return reg.predict(_name, x_padded, arm=_arm)

                v = reg.arm_version(name, arm)
                b = DynamicBatcher(
                    runner,
                    bucket_for=lambda rows, _n=name, _a=arm:
                        reg.arm_version(_n, _a).bucket_for(rows),
                    # clamped: a flush can never exceed the largest
                    # compiled bucket, and requests beyond it must route
                    # to the direct path (which chunks) instead
                    max_batch=min(self.max_batch or v.buckets[-1],
                                  v.buckets[-1]),
                    max_wait_us=self.max_wait_us,
                    name=name if arm == "stable" else f"{name}:{arm}",
                    metrics=reg.metrics, buckets=v.buckets, arm=arm)
                self._batchers[(name, arm)] = b
            return b

    # -- generation plane ------------------------------------------------
    def enable_generation(self, name: str, arm: str = "stable",
                          **opts) -> GenerationScheduler:
        """Attach a GenerationScheduler (continuous batching + paged KV
        cache) to servable `name`. `opts` pass through to the scheduler
        (mode, block_len, num_blocks, kv_dtype, decode_buckets, ...).
        Idempotent for a given (name, arm); called lazily with defaults
        by the first /generate request if never called explicitly. The
        stable arm's opts are remembered so a canary scheduler created
        lazily for candidate traffic mirrors them."""
        with self._batchers_lock:
            if self._stopping:
                raise BatcherClosedError("server is stopping")
            sched = self._schedulers.get((name, arm))
            if sched is None:
                if arm == "stable":
                    self._sched_opts[name] = dict(opts)
                sched = GenerationScheduler(
                    self.registry, name, metrics=self.registry.metrics,
                    arm=arm, **opts)
                self._schedulers[(name, arm)] = sched
            return sched

    def disable_generation(self, name: str):
        """Drain and detach `name`'s schedulers, both arms (bench windows
        swap continuous/static schedulers on one server this way)."""
        with self._batchers_lock:
            scheds = [self._schedulers.pop((name, a), None)
                      for a in ("stable", "canary")]
            self._sched_opts.pop(name, None)
        for sched in scheds:
            if sched is not None:
                sched.stop(drain=True)

    def generate(self, name: str, prompt, *, max_tokens: int = 16,
                 temperature: float = 0.0, stop=(), seed=None,
                 timeout: Optional[float] = None, ctx=None) -> Dict:
        self.registry.get(name)                     # -> 404 if unknown
        arm = self.registry.route_arm(name)
        sched = self._schedulers.get((name, arm))
        if sched is None:
            # canary decode traffic mirrors the stable scheduler's opts
            sched = self.enable_generation(
                name, arm=arm,
                **(self._sched_opts.get(name, {}) if arm != "stable"
                   else {}))
        t0 = time.perf_counter()
        try:
            res = sched.submit(prompt, max_tokens=max_tokens,
                               temperature=temperature, stop=stop,
                               seed=seed, timeout=timeout, ctx=ctx)
        except BaseException:
            self._observe_arm(name, arm, time.perf_counter() - t0, ctx,
                              error=True)
            raise
        self._observe_arm(name, arm, time.perf_counter() - t0, ctx,
                          error=False)
        return res

    def predict(self, name: str, features, batched: Optional[bool] = None,
                ctx=None) -> Tuple[np.ndarray, int, str]:
        """(outputs, version, path) where path is 'batched' | 'direct'.
        Oversize requests (rows > largest bucket) always go direct — the
        direct path chunks; the batcher never splits a request. While a
        canary is active, a deterministic fraction of requests serves on
        the candidate arm, and every request's latency/error/SLO-breach
        outcome feeds the canary's per-arm stats."""
        v = self.registry.get(name)                 # -> 404 if unknown
        try:
            x = _validate_features(v, features)
        except ServingError as e:
            raise ClientError(str(e)) from None
        arm = self.registry.route_arm(name)
        use_batch = self.batching if batched is None else bool(batched)
        t0 = time.perf_counter()
        try:
            out, version, path = self._predict_arm(name, x, arm,
                                                   use_batch, ctx)
        except BaseException:
            self._observe_arm(name, arm, time.perf_counter() - t0, ctx,
                              error=True)
            raise
        self._observe_arm(name, arm, time.perf_counter() - t0, ctx,
                          error=False)
        return out, version, path

    def _predict_arm(self, name: str, x: np.ndarray, arm: str,
                     use_batch: bool, ctx) -> Tuple[np.ndarray, int, str]:
        path, batcher = "direct", None
        if use_batch:
            batcher = self._batcher(name, arm)
            # route by the BATCHER's own row budget (it may be smaller
            # than the largest bucket, or stale after a bucket-changing
            # swap) — oversize requests go direct, which chunks, instead
            # of bouncing off submit()'s max_batch validation
            if x.shape[0] <= batcher.max_batch:
                path = "batched"
        with self._latency.time(model=name, path=path):
            if path == "batched":
                try:
                    out, version = batcher.submit(x, ctx=ctx)
                except BatcherClosedError:
                    if arm == "canary" and not self._stopping:
                        # the canary batcher was retired by a concurrent
                        # promote/rollback — fall back to the stable arm
                        # rather than fail an accepted request
                        out, version = self._batcher(name).submit(x, ctx=ctx)
                    else:
                        raise
            else:
                if ctx is not None:
                    with ctx.span("direct_forward", model=name,
                                  rows=int(x.shape[0]), arm=arm):
                        out, version = self.registry.predict(name, x,
                                                             arm=arm)
                else:
                    out, version = self.registry.predict(name, x, arm=arm)
        return out, version, path

    def _observe_arm(self, name: str, arm: str, dt: float, ctx,
                     error: bool):
        """Feed one request outcome into the live canary's per-arm stats
        (latency, error, SLO breach against the request's tier target).
        No-op when no canary is active."""
        if self.registry.canary_state(name) is None:
            return
        tier = ctx.tier if ctx is not None else DEFAULT_TIER
        target = self.slo.targets.get(tier)
        self.registry.observe_canary(
            name, arm, latency_s=dt, error=error,
            breach=target is not None and dt > target)

    def _retire_canary(self, name: str):
        """Drain and drop the candidate arm's batcher/scheduler after a
        promote or rollback. In-flight canary requests finish first (the
        runner resolves through `arm_version`, which already falls back
        to the post-decision version); requests racing the retirement
        fall back to the stable batcher."""
        with self._batchers_lock:
            b = self._batchers.pop((name, "canary"), None)
            s = self._schedulers.pop((name, "canary"), None)
        if b is not None:
            b.stop(drain=True)
        if s is not None:
            s.stop(drain=True)

    # -- HTTP plumbing ---------------------------------------------------
    def _make_handler(self):
        srv = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):   # quiet
                pass

            def _reply(self, code: int, payload, content_type=None,
                       endpoint="", model=""):
                ctx = getattr(self, "_trace_ctx", None)
                if (ctx is not None and isinstance(payload, dict)
                        and "error" in payload):
                    # every structured error body carries the trace id so
                    # a client-side failure correlates with server spans
                    payload = dict(payload, trace_id=ctx.trace_id)
                if isinstance(payload, (dict, list)):
                    data = json.dumps(payload).encode()
                    content_type = content_type or "application/json"
                else:
                    data = payload if isinstance(payload, bytes) \
                        else str(payload).encode()
                    content_type = content_type or "text/plain"
                if ctx is not None:
                    # root span + SLO observation land BEFORE the response
                    # bytes: a client that reads the tracer the moment its
                    # request returns always finds the connected trace
                    ctx.emit_root(f"http/{endpoint or 'other'}",
                                  code=code, model=model)
                    srv.slo.observe(ctx.tier, ctx.elapsed())
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                if ctx is not None:
                    self.send_header("X-DL4J-Trace", ctx.trace_id)
                if code >= 400:
                    # error paths may not have consumed the request body;
                    # leaving it unread on an HTTP/1.1 keep-alive socket
                    # desynchronizes every later request on it — close
                    self.send_header("Connection", "close")
                    self.close_connection = True
                self.end_headers()
                self.wfile.write(data)
                srv._requests.inc(model=model, endpoint=endpoint or "other",
                                  code=str(code))

            def _dispatch(self, method: str):
                endpoint, model = "other", ""
                self._trace_ctx = ctx = TraceContext.begin(
                    tier=self.headers.get("X-DL4J-SLO-Tier", DEFAULT_TIER))
                try:
                    m = _MODEL_PATH.match(self.path)
                    if self.path == "/healthz" and method == "GET":
                        endpoint = "healthz"
                        self._reply(200, srv.health(), endpoint=endpoint)
                    elif (self.path.partition("?")[0] == "/debug/flightrecord"
                            and method == "GET"):
                        endpoint = "flightrecord"
                        rec = flight_recorder()
                        self._reply(200,
                                    {"enabled": rec.enabled,
                                     "capacity": rec.capacity,
                                     "total_events": rec.total_written(),
                                     "last_dump": rec.last_dump,
                                     "events": rec.snapshot()},
                                    endpoint=endpoint)
                    elif self.path == "/metrics" and method == "GET":
                        endpoint = "metrics"
                        self._reply(
                            200, srv.registry.metrics.prometheus_text(),
                            content_type=(
                                "text/plain; version=0.0.4; charset=utf-8"),
                            endpoint=endpoint)
                    elif self.path == "/v1/models" and method == "GET":
                        endpoint = "models"
                        self._reply(200, {"models": srv.registry.models()},
                                    endpoint=endpoint)
                    elif m and m.group(2) is None and method == "GET":
                        endpoint, model = "model", m.group(1)
                        self._reply(200, srv.registry.get(model).info(),
                                    endpoint=endpoint, model=model)
                    elif m and m.group(2) == "predict" and method == "POST":
                        endpoint, model = "predict", m.group(1)
                        body = parse_json_body(self)
                        out, version, path = srv.predict(
                            model, require(body, "features"),
                            batched=body.get("batched"), ctx=ctx)
                        self._reply(200, {"model": model,
                                          "version": version,
                                          "batched": path == "batched",
                                          "output": out.tolist()},
                                    endpoint=endpoint, model=model)
                    elif m and m.group(2) == "generate" and method == "POST":
                        endpoint, model = "generate", m.group(1)
                        body = parse_json_body(self)
                        try:
                            prompt = [int(t) for t in require(body, "prompt")]
                            max_tokens = int(body.get("max_tokens", 16))
                            temperature = float(body.get("temperature", 0.0))
                            stop = [int(t) for t in (body.get("stop") or ())]
                            seed = body.get("seed")
                            seed = None if seed is None else int(seed)
                        except ClientError:
                            raise
                        except (TypeError, ValueError) as e:
                            raise ClientError(
                                f"invalid generate parameters: {e}") \
                                from None
                        with srv._latency.time(model=model, path="generate"):
                            res = srv.generate(
                                model, prompt, max_tokens=max_tokens,
                                temperature=temperature, stop=stop,
                                seed=seed, ctx=ctx)
                        self._reply(200, dict(
                            model=model,
                            version=srv.registry.get(model).version, **res),
                            endpoint=endpoint, model=model)
                    elif m and m.group(2) == "swap" and method == "POST":
                        endpoint, model = "swap", m.group(1)
                        body = parse_json_body(self)
                        try:
                            v = srv.registry.swap(
                                model, require(body, "source"),
                                precision=body.get("precision"),
                                buckets=body.get("buckets"),
                                input_shape=body.get("input_shape"))
                        except (TypeError, ValueError) as e:
                            # non-numeric buckets/input_shape etc. are
                            # the client's mistake, not a server fault
                            raise ClientError(
                                f"invalid swap parameters: {e}") from None
                        self._reply(200, v.info(), endpoint=endpoint,
                                    model=model)
                    elif m and m.group(2) == "canary" and method == "GET":
                        endpoint, model = "canary", m.group(1)
                        srv.registry.get(model)     # -> 404 if unknown
                        cs = srv.registry.canary_state(model)
                        payload = {"model": model, "active": cs is not None}
                        if cs is not None:
                            payload.update(cs.stats())
                        self._reply(200, payload, endpoint=endpoint,
                                    model=model)
                    elif m and m.group(2) == "canary" and method == "POST":
                        endpoint, model = "canary", m.group(1)
                        body = parse_json_body(self)
                        action = require(body, "action")
                        if action == "start":
                            try:
                                v = srv.registry.start_canary(
                                    model, require(body, "source"),
                                    fraction=float(
                                        body.get("fraction", 0.1)),
                                    precision=body.get("precision"),
                                    buckets=body.get("buckets"),
                                    input_shape=body.get("input_shape"))
                            except ClientError:
                                raise
                            except (TypeError, ValueError) as e:
                                raise ClientError(
                                    f"invalid canary parameters: {e}") \
                                    from None
                            self._reply(200, dict(v.info(), canary=True),
                                        endpoint=endpoint, model=model)
                        elif action == "promote":
                            v = srv.registry.promote_canary(model)
                            srv._retire_canary(model)
                            self._reply(200, dict(v.info(), promoted=True),
                                        endpoint=endpoint, model=model)
                        elif action == "rollback":
                            v = srv.registry.rollback_canary(model)
                            srv._retire_canary(model)
                            self._reply(200,
                                        dict(v.info(), rolled_back=True),
                                        endpoint=endpoint, model=model)
                        else:
                            raise ClientError(
                                f"unknown canary action {action!r}; "
                                "expected start|promote|rollback")
                    else:
                        self._reply(404, {"error": f"unknown path "
                                          f"{method} {self.path}"},
                                    endpoint=endpoint, model=model)
                except UnknownModelError as e:
                    self._reply(404, {"error": f"unknown model "
                                      f"{e.args[0]!r}"},
                                endpoint=endpoint, model=model)
                except (ClientError, ServingError) as e:
                    self._reply(400, {"error": str(e)},
                                endpoint=endpoint, model=model)
                except (BatcherClosedError, TimeoutError) as e:
                    self._reply(503, {"error": str(e)},
                                endpoint=endpoint, model=model)
                except Exception as e:   # genuine server fault
                    self._reply(500, {"error":
                                      f"{type(e).__name__}: {e}"},
                                endpoint=endpoint, model=model)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

        return Handler

    def health(self) -> Dict:
        return {"status": "ok",
                "models": {n: self.registry.get(n).version
                           for n in self.registry.names()},
                "uptime_s": round(time.time() - self._started_at, 3)}

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "InferenceServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dl4j-serving-http",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Stop accepting connections, then drain batchers (accepted
        requests finish). The _stopping flag closes the race where an
        in-flight handler would lazily recreate a batcher after the
        drain pass."""
        self._stopping = True
        if self._thread is not None:
            # shutdown() handshakes with serve_forever — calling it when
            # the serve thread never started blocks forever
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
        with self._batchers_lock:
            batchers = list(self._batchers.values())
            self._batchers.clear()
            schedulers = list(self._schedulers.values())
            self._schedulers.clear()
        for b in batchers:
            b.stop(drain=True)
        for s in schedulers:
            s.stop(drain=True)
        self._httpd.server_close()
        self._thread = None


def _smoke() -> int:
    """End-to-end smoke for CI (`runtests.sh serving`): ephemeral port,
    register, predict (batched + direct), hot-swap, scrape /metrics,
    clean shutdown. Prints PASS/FAIL, returns an exit code."""
    import tempfile
    import urllib.request

    from ..models.zoo import mlp_mnist
    from ..util.serializer import ModelSerializer

    def http(method, url, body=None, timeout=60):
        req = urllib.request.Request(
            url, None if body is None else json.dumps(body).encode(),
            {"Content-Type": "application/json"}, method=method)
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            ct = resp.headers.get("Content-Type", "")
            data = resp.read()
            return json.loads(data) if "json" in ct else data.decode()

    srv = InferenceServer().start()
    try:
        base = f"http://{srv.host}:{srv.port}"
        model = mlp_mnist(seed=3).init()
        srv.registry.register("mnist", model, buckets=(1, 8))
        x = np.zeros((3, 784), np.float32).tolist()
        out = http("POST", f"{base}/v1/models/mnist/predict",
                   {"features": x})
        assert np.asarray(out["output"]).shape == (3, 10), out
        assert out["version"] == 1 and out["batched"], out
        with tempfile.TemporaryDirectory() as d:
            ckpt = f"{d}/swap.zip"
            ModelSerializer.write_model(mlp_mnist(seed=4).init(), ckpt)
            info = http("POST", f"{base}/v1/models/mnist/swap",
                        {"source": ckpt})
        assert info["version"] == 2, info
        out = http("POST", f"{base}/v1/models/mnist/predict",
                   {"features": x, "batched": False})
        assert out["version"] == 2 and not out["batched"], out
        metrics = http("GET", f"{base}/metrics")
        for family in ("dl4j_serving_requests_total",
                       "dl4j_serving_swaps_total",
                       "dl4j_serving_latency_seconds"):
            assert family in metrics, f"{family} missing from /metrics"
        health = http("GET", f"{base}/healthz")
        assert health["status"] == "ok" and health["models"] == {"mnist": 2}
        print("serving smoke: PASS "
              f"(predict+swap+metrics on http://{srv.host}:{srv.port})")
        return 0
    except AssertionError as e:
        print(f"serving smoke: FAIL — {e}")
        return 1
    finally:
        srv.stop()


def main(argv=None):
    """`python -m deeplearning4j_tpu.serving.server --port 8999`
    (`--smoke` runs the CI end-to-end check and exits)."""
    import argparse

    ap = argparse.ArgumentParser(prog="deeplearning4j_tpu.serving.server")
    ap.add_argument("--port", type=int, default=8999)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--no-batching", action="store_true")
    ap.add_argument("--model", action="append", default=[], metavar
                    ="NAME=SOURCE", help="register NAME from SOURCE "
                    "(checkpoint zip/dir or keras h5) at startup")
    ap.add_argument("--smoke", action="store_true",
                    help="run the CI smoke (ephemeral port) and exit")
    args = ap.parse_args(argv)
    if args.smoke:
        raise SystemExit(_smoke())
    srv = InferenceServer(host=args.host, port=args.port,
                          batching=not args.no_batching)
    for spec in args.model:
        name, _, source = spec.partition("=")
        if not source:
            raise SystemExit(f"--model expects NAME=SOURCE, got {spec!r}")
        v = srv.registry.register(name, source)
        print(f"registered '{name}' v{v.version} from {source} "
              f"(buckets {list(v.buckets)}, {v.precision})")
    srv.start()
    print(f"inference server on http://{srv.host}:{srv.port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
