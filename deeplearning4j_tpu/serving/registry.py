"""Multi-model registry with versioned atomic hot-swap and AOT-compiled
inference runners.

The serving plane's core invariants:

  * **No cold compile on the request path.** Every (model, shape-bucket,
    precision) forward is jit-lowered AND compiled at registration/swap
    time (`jax.jit(...).lower(...).compile()`); request threads only ever
    invoke finished XLA executables. A compiled executable *cannot*
    retrace — a shape drifting past the bucket contract raises instead of
    silently recompiling, which is exactly the failure mode the
    CompileWatcher exists to catch in training.
  * **Atomic hot-swap.** A `ServableVersion` is an immutable snapshot
    (parameters, layer state, compiled runners). `swap()` builds and
    compiles the new version completely OFF the request path, then flips
    one pointer under the registry lock. In-flight requests keep the
    version object they already grabbed (old executables + old params
    stay alive via refcount) and finish on it; requests admitted after
    the flip see the new version. Nothing is ever dropped, and no request
    can observe half-old/half-new parameters.
  * **Verified sources.** Checkpoint sources go through the fault/
    machinery: zip checkpoints verify their sha256 manifest on restore
    (`CorruptCheckpointError` on bit rot / torn copy), checkpoint
    directories only trust `ckpt_*.zip` files (whose atomic-rename
    existence is the commit marker) and fall back past corrupt ones,
    newest first.

Executable reuse across swaps: compiled runners are cached per model
entry keyed by the *abstract* signature (param/state shapes+dtypes,
bucket, precision). Swapping in a same-architecture checkpoint reuses the
existing executables with the new parameter snapshot — zero new XLA
compiles, which the serving bench asserts (exactly one compile per
(model, bucket) across a run with swaps).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..datasets.pipeline import pad_rows
from .quantize import QuantizedTree, cast_tree, quantize_tree

__all__ = ["ModelRegistry", "ServableVersion", "UnknownModelError",
           "ServingError", "AotCompileError", "CanaryState",
           "DEFAULT_BUCKETS", "PRECISIONS", "load_source"]

DEFAULT_BUCKETS = (1, 8, 32)
PRECISIONS = ("fp32", "bf16", "int8")


class ServingError(RuntimeError):
    """Client-facing serving failure (bad shape, unknown precision, ...)."""


class AotCompileError(ServingError):
    """A candidate version failed its AOT lower+compile during
    `swap()`/`start_canary()`. Structured: carries the model name, the
    batch bucket that failed, and the underlying compiler exception. The
    registry guarantees the failed build is fully discarded — the live
    version keeps serving and the shared executable cache holds no entry
    from the rejected candidate."""

    def __init__(self, model: str, bucket, cause: BaseException):
        self.model = model
        self.bucket = bucket
        self.cause = cause
        super().__init__(
            f"{model}: AOT compile failed for bucket {bucket}: "
            f"{type(cause).__name__}: {cause}")


class UnknownModelError(KeyError):
    """Request for a model name the registry doesn't hold."""


# ---------------------------------------------------------------------------
# Source loading (fault/-verified checkpoint paths, keras h5, live models)
# ---------------------------------------------------------------------------
def load_source(source):
    """Resolve a servable source to a live model.

    Accepts: a model object (anything with `predict_fn`/`params`/`state`),
    a ModelSerializer zip path (sha256-manifest-verified on restore), a
    Keras HDF5 path, or a `fault.resume.CheckpointManager` directory
    (newest committed `ckpt_*.zip` wins; corrupt ones are skipped)."""
    if hasattr(source, "predict_fn"):
        return source, "object"
    if not isinstance(source, (str, os.PathLike)):
        raise ServingError(
            f"unsupported model source {type(source).__name__}: expected a "
            "model object, a checkpoint zip/h5 path, or a checkpoint "
            "directory")
    path = os.fspath(source)
    if os.path.isdir(path):
        import zipfile

        from ..fault.atomic import CorruptCheckpointError
        from ..fault.resume import CheckpointManager
        from ..util.serializer import ModelSerializer

        mgr = CheckpointManager(path)
        last_err = None
        for _, ckpt in reversed(mgr.entries()):
            try:
                return ModelSerializer.restore(ckpt), ckpt
            except (CorruptCheckpointError, OSError, KeyError,
                    ValueError, zipfile.BadZipFile) as e:
                last_err = e
        raise ServingError(
            f"no loadable committed checkpoint in {path!r}"
            + (f" (last error: {type(last_err).__name__}: {last_err})"
               if last_err else ""))
    if not os.path.exists(path):
        raise ServingError(f"model source {path!r} does not exist")
    from ..util.serializer import ModelGuesser
    return ModelGuesser.load(path), path


def _example_shape(model, override: Optional[Sequence[int]]) -> Tuple[int, ...]:
    """Per-example feature shape the compiled buckets are fixed to."""
    if override is not None:
        return tuple(int(d) for d in override)
    conf = getattr(model, "conf", None)
    it = getattr(conf, "input_type", None)
    if it is None:
        its = getattr(conf, "input_types", None)   # ComputationGraph conf
        if its:
            it = its[0]
    if it is not None:
        kind = getattr(it, "kind", None)
        if kind in ("ff", "cnn_flat"):
            return (int(it.flat_size()),)
        if kind == "cnn":
            return (int(it.height), int(it.width), int(it.channels))
        if kind in ("rnn", "cnn1d") and it.timesteps:
            return (int(it.timesteps), int(it.size))
    raise ServingError(
        "cannot derive a fixed per-example input shape from the model "
        "configuration — pass input_shape=(...) at register()/swap() time "
        "(serving compiles fixed-shape buckets, so the shape must be known "
        "up front)")


# ---------------------------------------------------------------------------
# Servable versions
# ---------------------------------------------------------------------------
class ServableVersion:
    """Immutable snapshot of one model version: transformed parameters,
    layer state, and one compiled XLA executable per shape bucket.
    Request threads hold a reference across their whole forward, so a
    concurrent swap can never tear outputs or free buffers under them."""

    __slots__ = ("name", "version", "precision", "buckets", "example_shape",
                 "snapshot", "state", "runners", "model_kind", "source",
                 "created_at", "param_bytes", "model")

    def __init__(self, name, precision, buckets, example_shape, snapshot,
                 state, runners, model_kind, source, model=None):
        self.name = name
        self.version = 0            # assigned at the atomic flip
        self.precision = precision
        self.buckets = buckets
        self.example_shape = example_shape
        self.snapshot = snapshot
        self.state = state
        self.runners = runners      # {bucket: compiled XLA executable}
        self.model_kind = model_kind
        self.source = source
        self.created_at = time.time()
        self.param_bytes = snapshot.nbytes()
        # the live model object (layer configs + predict_fn): the decode
        # plane walks its layer stack to build the KV-cache step; the
        # stateless runners already close over it via predict_fn, so
        # keeping the reference here costs nothing extra
        self.model = model

    def bucket_for(self, rows: int) -> int:
        for b in self.buckets:
            if rows <= b:
                return b
        raise ServingError(
            f"{self.name}: request of {rows} rows exceeds the largest "
            f"compiled batch bucket {self.buckets[-1]}")

    def run_padded(self, x_padded: np.ndarray, bucket: int) -> np.ndarray:
        """One compiled forward over a bucket-shaped batch. Never compiles."""
        out = self.runners[bucket](self.snapshot.data, self.state, x_padded)
        return np.asarray(out)

    def info(self) -> Dict:
        return {
            "name": self.name, "version": self.version,
            "precision": self.precision, "buckets": list(self.buckets),
            "input_shape": list(self.example_shape),
            "model_kind": self.model_kind,
            "source": self.source if isinstance(self.source, str) else
            type(self.source).__name__,
            "param_mb": round(self.param_bytes / 1e6, 3),
            "created_at": self.created_at,
        }


class CanaryState:
    """Live canary for one model: the candidate version, its routing
    fraction, and per-arm observations (requests, errors, latency, SLO
    breaches) that the continual plane's promotion policy reads.

    Routing is DETERMINISTIC: a per-model admission counter sends request
    `i` to the candidate iff ``i % 100 < round(fraction * 100)`` — the
    same request sequence always splits the same way, so canary drills
    are replayable. The internal lock is a leaf lock (nothing else is
    ever acquired under it), touched only for a counter bump or a stats
    write — nanoseconds on the request path."""

    __slots__ = ("version", "fraction", "started_at", "_slice",
                 "_counter", "_lock", "_arms")

    def __init__(self, version: ServableVersion, fraction: float):
        if not 0.0 < fraction < 1.0:
            raise ServingError(
                f"canary fraction must be in (0, 1), got {fraction}")
        self.version = version
        self.fraction = float(fraction)
        self.started_at = time.time()
        self._slice = max(1, round(self.fraction * 100))
        self._counter = 0
        self._lock = threading.Lock()
        self._arms = {arm: {"requests": 0, "errors": 0, "breaches": 0,
                            "latency_sum": 0.0, "latency_max": 0.0}
                      for arm in ("stable", "canary")}

    def route_arm(self) -> str:
        with self._lock:
            i = self._counter
            self._counter += 1
        return "canary" if i % 100 < self._slice else "stable"

    def observe(self, arm: str, latency_s: Optional[float] = None,
                error: bool = False, breach: bool = False):
        s = self._arms[arm]
        with self._lock:
            s["requests"] += 1
            if error:
                s["errors"] += 1
            if breach:
                s["breaches"] += 1
            if latency_s is not None:
                s["latency_sum"] += latency_s
                if latency_s > s["latency_max"]:
                    s["latency_max"] = latency_s

    def stats(self) -> Dict:
        with self._lock:
            arms = {a: dict(s) for a, s in self._arms.items()}
        for s in arms.values():
            n = max(1, s["requests"] - s["errors"])
            s["latency_mean"] = s["latency_sum"] / n
        return {"version": self.version.version, "fraction": self.fraction,
                "started_at": self.started_at, "arms": arms}


class _Entry:
    """Per-model-name mutable registry slot: the current version pointer,
    the executable cache (abstract-signature keyed, survives swaps), an
    optional live canary, and a swap lock serializing rebuilds of this
    one model."""

    __slots__ = ("current", "version_counter", "compiled", "swap_lock",
                 "sig_history", "canary")

    def __init__(self):
        self.current: Optional[ServableVersion] = None
        self.version_counter = 0
        self.compiled: Dict[tuple, object] = {}
        self.sig_history: list = []   # newest-first abstract sigs, max 2
        self.swap_lock = threading.Lock()
        self.canary: Optional[CanaryState] = None


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------
class ModelRegistry:
    """Named, versioned, hot-swappable servable models.

    `metrics` defaults to the active telemetry session's registry (so the
    serving counters land next to training telemetry) or a fresh
    `MetricsRegistry`; `InferenceServer` exposes it at `/metrics`.
    """

    def __init__(self, buckets: Sequence[int] = DEFAULT_BUCKETS,
                 precision: str = "fp32", metrics=None):
        self.default_buckets = tuple(sorted(int(b) for b in buckets))
        if precision not in PRECISIONS:
            raise ServingError(
                f"unknown precision {precision!r}; expected one of "
                f"{PRECISIONS}")
        self.default_precision = precision
        if metrics is None:
            from ..telemetry import runtime
            tel = runtime.active()
            if tel is not None:
                metrics = tel.registry
            else:
                from ..telemetry.registry import MetricsRegistry
                metrics = MetricsRegistry()
        self.metrics = metrics
        self._lock = threading.RLock()
        self._entries: Dict[str, _Entry] = {}
        self._swaps = metrics.counter(
            "dl4j_serving_swaps_total", "model version swaps committed",
            labels=("model",))
        self._version_g = metrics.gauge(
            "dl4j_serving_model_version", "currently served model version",
            labels=("model",))
        self._compiles = metrics.counter(
            "dl4j_serving_compiles_total",
            "XLA inference compiles per (model, bucket) — flat after "
            "startup/swap means the request path never cold-compiles",
            labels=("model", "bucket"))
        self._compile_s = metrics.histogram(
            "dl4j_serving_compile_seconds",
            "wall seconds per serving AOT lower+compile",
            labels=("model",))
        self._canary_req = metrics.counter(
            "dl4j_continual_canary_requests_total",
            "requests observed per arm while a canary is active",
            labels=("model", "arm"))

    # -- registration / swap --------------------------------------------
    def register(self, name: str, source, *, precision: Optional[str] = None,
                 buckets: Optional[Sequence[int]] = None,
                 input_shape: Optional[Sequence[int]] = None
                 ) -> ServableVersion:
        """Load, transform, and AOT-compile `source`, then atomically
        install it as the current version of `name` (creating the model on
        first call — `register` and `swap` are the same operation; two
        names for intent)."""
        with self._lock:
            entry = self._entries.setdefault(name, _Entry())
        with entry.swap_lock:
            if entry.canary is not None:
                raise ServingError(
                    f"{name}: a canary (candidate v"
                    f"{entry.canary.version.version}) is active — promote "
                    "or roll it back before swapping a new version in")
            return self._register_locked(entry, name, source,
                                         precision=precision,
                                         buckets=buckets,
                                         input_shape=input_shape)

    swap = register

    def _register_locked(self, entry: _Entry, name: str, source,
                         **kw) -> ServableVersion:
        version = self._build_version(entry, name, source, **kw)
        # the atomic flip: everything above ran off the request path
        with self._lock:
            entry.version_counter += 1
            version.version = entry.version_counter
            entry.current = version
        self._swaps.inc(model=name)
        self._version_g.set(version.version, model=name)
        return version

    def ensure(self, name: str, source, **kw) -> ServableVersion:
        """register() only if `name` isn't already served (the legacy
        /output route: first request loads+compiles, the rest hit cache).
        Concurrent ensure() calls on a new name serialize on the entry's
        swap lock — exactly one builds, the rest return its version."""
        v = self._current(name)
        if v is not None:
            return v
        with self._lock:
            entry = self._entries.setdefault(name, _Entry())
        with entry.swap_lock:
            if entry.current is not None:
                return entry.current
            return self._register_locked(entry, name, source, **kw)

    def unregister(self, name: str):
        with self._lock:
            self._entries.pop(name, None)

    def aot_executables(self):
        """Snapshot of every live AOT-compiled executable as
        (model name, batch bucket, compiled) tuples — the graftlint IR
        tier (analysis/ir.py) audits exactly these: what serves is what
        is checked (collective schedule, buffer aliasing), not a
        re-lowered approximation."""
        with self._lock:
            entries = list(self._entries.items())
        out = []
        for name, entry in entries:
            with entry.swap_lock:
                version = entry.current
                if version is None:
                    continue
                seen = set()
                for bucket in version.buckets:
                    out.append((name, bucket, version.runners[bucket]))
                    seen.add(id(version.runners[bucket]))
                # a live canary serves traffic too — audit its
                # executables as well (a same-architecture candidate
                # shares the stable executables, so dedupe by identity)
                if entry.canary is not None:
                    cand = entry.canary.version
                    for bucket in cand.buckets:
                        r = cand.runners[bucket]
                        if id(r) not in seen:
                            out.append((name, bucket, r))
        return out

    # -- lookup ---------------------------------------------------------
    def _current(self, name: str) -> Optional[ServableVersion]:
        with self._lock:
            entry = self._entries.get(name)
            return entry.current if entry is not None else None

    def get(self, name: str) -> ServableVersion:
        v = self._current(name)
        if v is None:
            raise UnknownModelError(name)
        return v

    def names(self) -> List[str]:
        with self._lock:
            return sorted(n for n, e in self._entries.items()
                          if e.current is not None)

    def models(self) -> List[Dict]:
        return [self.get(n).info() for n in self.names()]

    def __contains__(self, name: str) -> bool:
        return self._current(name) is not None

    # -- inference (direct, unbatched path) -----------------------------
    def predict(self, name: str, features, arm: str = "stable"
                ) -> Tuple[np.ndarray, int]:
        """Direct single-request forward: chunk by the largest bucket, pad
        each chunk up to its bucket with zero rows (the PadToBatch shape
        discipline), run the compiled executable, strip padding. Returns
        `(outputs, version)`. The whole request runs on ONE version —
        the canary candidate's when `arm="canary"` and a canary is active
        (stable otherwise)."""
        v = self.get(name) if arm == "stable" else self.arm_version(name, arm)
        x = _validate_features(v, features)
        top = v.buckets[-1]
        outs = []
        for lo in range(0, x.shape[0], top):
            chunk = x[lo:lo + top]
            bucket = v.bucket_for(chunk.shape[0])
            out = v.run_padded(pad_rows(chunk, bucket - chunk.shape[0]),
                               bucket)
            outs.append(out[:chunk.shape[0]])
        return (outs[0] if len(outs) == 1 else np.concatenate(outs)), \
            v.version

    # -- canary routing (continual train-to-serve plane) ----------------
    def start_canary(self, name: str, source, *, fraction: float = 0.1,
                     precision: Optional[str] = None,
                     buckets: Optional[Sequence[int]] = None,
                     input_shape: Optional[Sequence[int]] = None
                     ) -> ServableVersion:
        """Build and AOT-compile a CANDIDATE version of `name` and expose
        it to a deterministic `fraction` slice of traffic WITHOUT touching
        the current (stable) version. The candidate gets the next
        monotonic version number immediately — version numbers are never
        reused, even if this canary later rolls back. A same-architecture
        candidate reuses the stable version's executables through the
        shared cache: zero new XLA compiles. Raises `AotCompileError`
        (live version + cache untouched) if the candidate fails to
        compile, and `ServingError` if a canary is already active."""
        if not 0.0 < float(fraction) < 1.0:
            raise ServingError(
                f"canary fraction must be in (0, 1), got {fraction}")
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise UnknownModelError(name)
        with entry.swap_lock:
            if entry.current is None:
                raise UnknownModelError(name)
            if entry.canary is not None:
                raise ServingError(
                    f"{name}: a canary (candidate v"
                    f"{entry.canary.version.version}) is already active")
            version = self._build_version(entry, name, source,
                                          precision=precision,
                                          buckets=buckets,
                                          input_shape=input_shape)
            with self._lock:
                entry.version_counter += 1
                version.version = entry.version_counter
                entry.canary = CanaryState(version, float(fraction))
        return version

    def canary_state(self, name: str) -> Optional[CanaryState]:
        with self._lock:
            entry = self._entries.get(name)
            return entry.canary if entry is not None else None

    def route_arm(self, name: str) -> str:
        """Which arm serves the next request: "canary" for the
        deterministic fraction slice while a canary is active, else
        "stable"."""
        cs = self.canary_state(name)
        return cs.route_arm() if cs is not None else "stable"

    def arm_version(self, name: str, arm: str = "stable") -> ServableVersion:
        """The version serving `arm`. Falls back to the stable version
        when no canary is active — a request routed to "canary" just
        before a rollback still gets a servable version, never an
        error."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is None or entry.current is None:
                raise UnknownModelError(name)
            if arm == "canary" and entry.canary is not None:
                return entry.canary.version
            return entry.current

    def observe_canary(self, name: str, arm: str,
                       latency_s: Optional[float] = None,
                       error: bool = False, breach: bool = False):
        """Feed one request observation into the live canary's per-arm
        stats (and the `dl4j_continual_canary_requests_total` counter).
        No-op when no canary is active."""
        cs = self.canary_state(name)
        if cs is None:
            return
        cs.observe(arm, latency_s=latency_s, error=error, breach=breach)
        self._canary_req.inc(model=name, arm=arm)

    def promote_canary(self, name: str) -> ServableVersion:
        """Atomically make the canary candidate the stable version (the
        same single-pointer flip as `swap()`; in-flight requests finish on
        whichever version they already hold)."""
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise UnknownModelError(name)
        with entry.swap_lock:
            cs = entry.canary
            if cs is None:
                raise ServingError(f"{name}: no canary is active")
            with self._lock:
                entry.current = cs.version
                entry.canary = None
        self._swaps.inc(model=name)
        self._version_g.set(cs.version.version, model=name)
        return cs.version

    def rollback_canary(self, name: str) -> ServableVersion:
        """Drop the canary candidate; the stable version (bit-identical,
        never touched by the canary) keeps serving all traffic. Returns
        the stable version."""
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise UnknownModelError(name)
        with entry.swap_lock:
            if entry.canary is None:
                raise ServingError(f"{name}: no canary is active")
            with self._lock:
                entry.canary = None
            return entry.current

    # -- version building -----------------------------------------------
    def _build_version(self, entry: _Entry, name: str, source, *,
                       precision=None, buckets=None,
                       input_shape=None) -> ServableVersion:
        precision = precision or self.default_precision
        if precision not in PRECISIONS:
            raise ServingError(
                f"unknown precision {precision!r}; expected one of "
                f"{PRECISIONS}")
        buckets = tuple(sorted(int(b) for b in (buckets or
                                                self.default_buckets)))
        if not buckets or buckets[0] < 1:
            raise ServingError(f"invalid batch buckets {buckets}")
        model, src = load_source(source)
        if getattr(model, "params", None) is None:
            model.init()
        shape = _example_shape(model, input_shape)
        snapshot = _snapshot_params(model, precision)
        state = jax.tree_util.tree_map(jnp.asarray, model.state)
        fn = jax.jit(_make_forward(model, snapshot))
        sig = _abstract_sig(snapshot, state, precision)
        runners = {}
        # stage fresh compiles locally and merge only after EVERY bucket
        # compiled: a candidate whose compile fails mid-build must leave
        # the shared executable cache (and the live version still serving
        # from it) bit-for-bit untouched
        staged: Dict[tuple, Tuple[object, float]] = {}
        for b in buckets:
            # namespaced key: the stateless plane and the decode plane
            # (serving/decode, keys ("decode", sig, phase, ...)) share one
            # executable cache per model entry, so the plane tag keeps a
            # generate-capable servable and its stateless twin from ever
            # colliding on (or evicting) each other's executables
            key = ("fwd", sig, b)
            compiled = entry.compiled.get(key)
            if compiled is None:
                x_spec = jax.ShapeDtypeStruct((b,) + shape, jnp.float32)
                t0 = time.perf_counter()
                try:
                    compiled = fn.lower(snapshot.data, state,
                                        x_spec).compile()
                except ServingError:
                    raise
                except Exception as e:
                    raise AotCompileError(name, b, e) from e
                staged[key] = (compiled, time.perf_counter() - t0)
            runners[b] = compiled
        for key, (compiled, wall) in staged.items():
            entry.compiled[key] = compiled
            self._record_compile(name, key[2], wall)
        # bound the executable cache: keep the current and the previous
        # architecture's executables (A/B rollback stays compile-free),
        # drop older — a long-lived server cycling checkpoints must not
        # grow its compiled set without limit. Pruning filters on the SIG
        # element (key[1]) so decode-plane executables for a kept sig
        # survive a stateless swap and vice versa
        if sig in entry.sig_history:
            entry.sig_history.remove(sig)
        entry.sig_history.insert(0, sig)
        if len(entry.sig_history) > 2:
            keep = set(entry.sig_history[:2])
            del entry.sig_history[2:]
            for key in [k for k in entry.compiled if k[1] not in keep]:
                del entry.compiled[key]
        return ServableVersion(name, precision, buckets, shape, snapshot,
                               state, runners, type(model).__name__, src,
                               model=model)

    def compile_cached(self, name: str, key: tuple, build, label: str):
        """AOT-compile through `name`'s shared executable cache: return the
        cached executable under namespaced `key` (("decode", sig, phase,
        bucket) for the generation plane) or run `build()` (a lower+compile
        closure) once under the entry's swap lock and cache it. `label` is
        the compile-accounting bucket tag (e.g. "decode4", "prefill1x32")
        — one `record_aot` per cache miss, so the server-lifetime compile
        invariant ("one XLA compile per signature") is auditable from the
        CompileWatcher report exactly like the stateless buckets."""
        with self._lock:
            entry = self._entries.setdefault(name, _Entry())
        with entry.swap_lock:
            compiled = entry.compiled.get(key)
            if compiled is None:
                t0 = time.perf_counter()
                compiled = build()
                self._record_compile(name, label,
                                     time.perf_counter() - t0)
                entry.compiled[key] = compiled
        return compiled

    def _record_compile(self, name: str, bucket, wall_s: float):
        self._compiles.inc(model=name, bucket=str(bucket))
        self._compile_s.observe(wall_s, model=name)
        from ..telemetry import runtime
        tel = runtime.active()
        if tel is not None:
            tel.compiles.record_aot(f"serving/{name}:b{bucket}", wall_s)


# ---------------------------------------------------------------------------
# Forward builders
# ---------------------------------------------------------------------------
def _snapshot_params(model, precision: str) -> QuantizedTree:
    """Freeze the model's parameters into the serving representation for
    `precision`. Always a QuantizedTree (fp32/bf16 just have no quantized
    leaves) so every runner shares one flat-data calling convention."""
    params = model.params
    if precision == "int8":
        return quantize_tree(params)
    if precision == "bf16":
        params = cast_tree(params, jnp.bfloat16)
    leaves, treedef = jax.tree_util.tree_flatten(
        jax.tree_util.tree_map(jnp.asarray, params))
    return QuantizedTree(tuple(leaves), (None,) * len(leaves), treedef,
                         compute_dtype=jnp.float32)


def _make_forward(model, snapshot: QuantizedTree):
    """The traced serving forward: rebuild params from the flat snapshot
    (dequantizing int8 leaves), cast the padded batch to the snapshot's
    compute dtype, run the model's pure predict fn, emit float32."""
    predict = model.predict_fn
    graph_inputs = getattr(getattr(model, "conf", None),
                           "network_inputs", None)
    if graph_inputs is not None and len(graph_inputs) != 1:
        raise ServingError(
            "serving supports single-input models; this ComputationGraph "
            f"declares inputs {list(graph_inputs)}")
    param_dtypes = {jnp.asarray(d).dtype for d, s in
                    zip(snapshot.data, snapshot.scales) if s is None}
    x_dtype = (jnp.bfloat16 if jnp.bfloat16 in param_dtypes
               else jnp.float32)

    def forward(data, state, x):
        params = snapshot.rebuild(data)
        x = x.astype(x_dtype)
        if graph_inputs is not None:
            name = graph_inputs[0]
            out = predict(params, state, {name: x}, {name: None})
            out = out[0]
        else:
            out = predict(params, state, x, None)
        return out.astype(jnp.float32)

    return forward


def _abstract_sig(snapshot: QuantizedTree, state, precision: str) -> tuple:
    """Hashable (shapes+dtypes) signature of a version's compiled-input
    avals — two versions with equal signatures share XLA executables.
    Quantization SCALES are runtime arguments, deliberately absent: a
    re-quantized same-architecture checkpoint signs identically and
    reuses the executables."""
    def leaf_sig(a):
        a = jnp.asarray(a)
        return (tuple(a.shape), str(a.dtype))

    data_sig = tuple(
        leaf_sig(d) if s is None else (leaf_sig(d[0]), leaf_sig(d[1]))
        for d, s in zip(snapshot.data, snapshot.scales))
    flat_state, state_def = jax.tree_util.tree_flatten(state)
    return (precision, data_sig,
            tuple(s is not None for s in snapshot.scales),
            tuple(leaf_sig(s) for s in flat_state), str(state_def))


def _validate_features(v: ServableVersion, features) -> np.ndarray:
    try:
        x = np.asarray(features, np.float32)
    except (TypeError, ValueError) as e:
        raise ServingError(f"features are not a numeric array: {e}") from None
    if x.ndim == len(v.example_shape):      # single example convenience
        x = x[None]
    if x.ndim != len(v.example_shape) + 1 \
            or tuple(x.shape[1:]) != v.example_shape:
        raise ServingError(
            f"{v.name}: features shape {tuple(x.shape)} does not match "
            f"[rows]{list(v.example_shape)}")
    if x.shape[0] == 0:
        raise ServingError(f"{v.name}: empty features batch")
    return x
