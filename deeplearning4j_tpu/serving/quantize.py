"""Inference-time parameter transforms: bf16 cast and int8 weight-only
quantization.

Weight-only int8 (the LLM.int8()/AWQ-family baseline shape, minus the
outlier handling those papers add): every float weight tensor with >=
`min_elems` elements is stored as int8 plus ONE per-tensor symmetric
scale (`scale = absmax / 127`); activations stay float. Dequantization
(`int8 * scale`) happens INSIDE the compiled forward, so the serving
plane holds a ~4x smaller parameter snapshot and the XLA program sees a
constant-folded-friendly `convert+mul` on the weight path. Small leaves
(biases, BN stats) stay in their original dtype — quantizing a
10-element bias saves nothing and costs accuracy.

This is post-training quantization with no calibration pass: expect
~1e-2-level output drift on softmax heads (tested), NOT bit-exactness.
Accuracy-critical serving should stay on fp32/bf16; int8 is the
memory-bound-throughput knob.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["QuantizedTree", "quantize_tree", "cast_tree"]

_FLOAT_KINDS = ("f",)  # np dtype.kind for floating leaves


def _is_quantizable(leaf: np.ndarray, min_elems: int) -> bool:
    a = np.asarray(leaf)
    return (a.dtype.kind in _FLOAT_KINDS and a.ndim >= 2
            and a.size >= min_elems)


class QuantizedTree:
    """A flattened parameter pytree with int8-quantized weight leaves.

    `data` is the flat tuple handed to the compiled forward: a plain
    array for pass-through leaves, an `(int8_weights, scale_scalar)` pair
    for quantized ones. Keeping the scale a RUNTIME argument (not a
    trace-time constant) means two snapshots of the same architecture
    lower to identical XLA programs — so a hot-swap to a re-quantized
    checkpoint reuses the cached executables instead of recompiling
    every bucket. `scales[i]` records the python-float scale (or None)
    for introspection only. `rebuild(data)` runs under jit and returns
    the original tree structure with every leaf back in `compute_dtype`.
    """

    def __init__(self, data: Tuple, scales: Tuple[Optional[float], ...],
                 treedef, compute_dtype=jnp.float32):
        self.data = tuple(data)
        self.scales = tuple(scales)
        self.treedef = treedef
        self.compute_dtype = compute_dtype

    @property
    def n_quantized(self) -> int:
        return sum(1 for s in self.scales if s is not None)

    def nbytes(self) -> int:
        total = 0
        for d, s in zip(self.data, self.scales):
            if s is not None:
                total += np.asarray(d[0]).nbytes + np.asarray(d[1]).nbytes
            else:
                total += np.asarray(d).nbytes
        return int(total)

    def rebuild(self, data):
        """Dequantize a flat `data` tuple back into the original pytree —
        traceable (called inside the compiled forward)."""
        leaves = []
        for d, s in zip(data, self.scales):
            if s is not None:
                q, scale = d
                d = q.astype(self.compute_dtype) \
                    * scale.astype(self.compute_dtype)
            leaves.append(d)
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


def quantize_tree(tree, min_elems: int = 64,
                  compute_dtype=jnp.float32) -> QuantizedTree:
    """Per-tensor symmetric int8 weight-only quantization of a parameter
    pytree. Leaves below `min_elems` elements or with ndim < 2 pass
    through untouched (biases, scalars, BN running stats)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    data, scales = [], []
    for leaf in leaves:
        a = np.asarray(leaf)
        if _is_quantizable(a, min_elems):
            absmax = float(np.max(np.abs(a)))
            scale = (absmax / 127.0) if absmax > 0 else 1.0
            q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
            data.append((jnp.asarray(q), jnp.asarray(scale, np.float32)))
            scales.append(scale)
        else:
            data.append(jnp.asarray(a))
            scales.append(None)
    return QuantizedTree(tuple(data), tuple(scales), treedef,
                         compute_dtype=compute_dtype)


def cast_tree(tree, dtype):
    """Cast every floating leaf of a pytree to `dtype` (bf16 snapshot for
    the half-precision serving path); non-float leaves pass through."""
    dtype = jnp.dtype(dtype)

    def cast(leaf):
        a = jnp.asarray(leaf)
        if jnp.issubdtype(a.dtype, jnp.floating):
            return a.astype(dtype)
        return a

    return jax.tree_util.tree_map(cast, tree)
