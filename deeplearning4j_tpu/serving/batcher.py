"""Dynamic request batching: coalesce concurrent predict requests into
padded fixed-shape batches (Clipper-style adaptive batching / ORCA-style
request coalescing applied to our bucketed executables).

A request enqueues its rows and blocks on an event; the single worker
thread drains the queue when either (a) enough rows have accumulated to
fill `max_batch`, or (b) the OLDEST queued request has waited
`max_wait_us` — the classic max-wait/max-batch tradeoff knob. The drained
rows are stacked, padded with zero rows up to the smallest compiled
bucket that fits (`datasets.pipeline.pad_rows` — the PadToBatch shaping
reused on the serving path), run through ONE compiled forward, and the
per-row results scatter back to their waiters.

Error isolation: shape validation happens at submit() time on the
CALLER's thread, so a malformed request fails alone with a client error
and never enters a batch. A failure inside the batched forward itself
(a genuine server fault) fails exactly the requests in that batch;
later requests get a fresh batch.

Version consistency: the runner callable is expected to resolve the
current ServableVersion once per FLUSH, so every row in a batch is
served by one version and versions observed by a client are monotonic
(one worker, FIFO flushes)."""
from __future__ import annotations

import collections
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..datasets.pipeline import pad_rows
from .registry import ServingError

__all__ = ["DynamicBatcher", "BatcherClosedError", "FlushEma"]


class FlushEma:
    """Per-bucket EMA of flush wall seconds, shared by the stateless
    DynamicBatcher and the decode plane's GenerationScheduler (which uses
    it to pick the decode-tick bucket).

    `estimate` extrapolates unsampled buckets by LINEAR scaling —
    deliberately pessimistic (assumes zero batching amortization), so an
    unsampled small bucket looks exactly break-even and gets tried, then
    its real cost takes over. Extrapolation prefers the smallest SAMPLED
    bucket ABOVE the target (scaling down from a larger batch), floored
    by any measured smaller bucket — the old nearest-by-absolute-distance
    pick could scale UP from a tiny bucket even when a much more
    representative larger one had been measured (|1-8| < |32-8|),
    estimating an 8-wide flush at 8x a 1-wide one and ignoring the fixed
    per-flush dispatch cost entirely. The floor keeps estimates monotone
    in the bucket size: a bigger batch never flushes faster than a
    measured smaller one in the same executable family."""

    __slots__ = ("_ema",)

    def __init__(self):
        self._ema: dict = {}   # bucket -> EMA flush seconds

    def observe(self, bucket: int, dt: float):
        prev = self._ema.get(bucket)
        self._ema[bucket] = dt if prev is None else 0.5 * prev + 0.5 * dt

    def estimate(self, bucket: int) -> Optional[float]:
        t = self._ema.get(bucket)
        if t is not None:
            return t
        if not self._ema:
            return None
        larger = [b for b in self._ema if b > bucket]
        if larger:
            b0 = min(larger)
            est = self._ema[b0] * bucket / b0
            smaller = [b for b in self._ema if b < bucket]
            if smaller:
                est = max(est, self._ema[max(smaller)])
            return est
        b0 = max(self._ema)
        return self._ema[b0] * bucket / b0

    def pick_rows(self, avail: int, buckets: Tuple[int, ...],
                  cap: int) -> int:
        """Row budget for a flush of `avail` queued rows against compiled
        `buckets`: everything padded up to the next bucket, or only the
        largest FULL bucket's worth — whichever yields more rows/second
        under the EMAs (Clipper-style adaptive batch sizing)."""
        cap = min(cap, buckets[-1])
        if avail >= cap:
            return cap
        up = next((b for b in buckets if b >= avail), buckets[-1])
        full = [b for b in buckets if b <= avail]
        if not full or full[-1] == up:
            return avail
        fb = max(full)
        t_up, t_fb = self.estimate(up), self.estimate(fb)
        if not t_up or not t_fb:
            return avail
        return avail if avail / t_up >= fb / t_fb else fb

# one reusable completion Event per client thread: submit() is blocking,
# so a thread has at most one pending request, and recycling the pthread
# primitives shaves measurable per-request overhead at high concurrency
_tls = threading.local()


def _thread_event() -> threading.Event:
    ev = getattr(_tls, "event", None)
    if ev is None:
        ev = _tls.event = threading.Event()
    ev.clear()
    return ev


class BatcherClosedError(RuntimeError):
    """submit() after stop() — the serving plane is shutting down."""


class _Pending:
    __slots__ = ("x", "rows", "event", "result", "version", "error",
                 "enqueued_at", "ctx")

    def __init__(self, x: np.ndarray, ctx=None):
        self.x = x
        self.rows = int(x.shape[0])
        self.event = _thread_event()
        self.result: Optional[np.ndarray] = None
        self.version: Optional[int] = None
        self.error: Optional[BaseException] = None
        self.enqueued_at = time.perf_counter()
        self.ctx = ctx      # TraceContext riding the request, or None


class DynamicBatcher:
    """Coalesces concurrent `submit()` calls into bucket-shaped batches.

    runner(x_padded, bucket) -> (outputs, version): ONE compiled forward
        over a `[bucket, ...]` batch (registry._predict path).
    bucket_for(rows) -> bucket: smallest compiled bucket holding `rows`
        (raises for oversize requests — validated on the caller's thread).
    max_batch: row budget per flush; defaults to the largest bucket.
    max_wait_us: the oldest request never waits longer than this for
        co-batching before the worker flushes a partial batch.
    """

    def __init__(self, runner: Callable, bucket_for: Callable[[int], int],
                 max_batch: int, max_wait_us: int = 2000,
                 name: str = "model", metrics=None,
                 buckets: Optional[Tuple[int, ...]] = None,
                 arm: str = "stable"):
        self._runner = runner
        self._bucket_for = bucket_for
        self.max_batch = int(max_batch)
        self.max_wait_s = max(0.0, float(max_wait_us) / 1e6)
        self.buckets = tuple(sorted(buckets)) if buckets else None
        self._flush_ema = FlushEma()
        self.name = name
        # which canary arm this batcher serves ("stable" outside a
        # canary); per-arm batchers let the continual plane retire the
        # candidate's queue without touching in-flight stable requests
        self.arm = arm
        self._stop_lock = threading.Lock()
        # enqueue is lock-free: deque.append is atomic under the GIL and
        # the worker is the only consumer, so clients pay one append + one
        # Event.set per request instead of a contended mutex round trip
        self._queue: collections.deque = collections.deque()
        self._wake = threading.Event()
        self._stopped = False
        self._batch_size_h = self._queue_wait_h = self._rows_c = None
        if metrics is not None:
            self._batch_size_h = metrics.histogram(
                "dl4j_serving_batch_size",
                "real (unpadded) rows per batched forward",
                labels=("model",),
                buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
            self._queue_wait_h = metrics.histogram(
                "dl4j_serving_queue_wait_seconds",
                "seconds a request waited in the batching queue before "
                "its flush started", labels=("model",))
            self._rows_c = metrics.counter(
                "dl4j_serving_batch_rows_total",
                "rows through the batched path by kind (real vs padding)",
                labels=("model", "kind"))
        self._worker = threading.Thread(
            target=self._loop, name=f"dl4j-serving-batcher-{name}",
            daemon=True)
        self._worker.start()

    # -- client side -----------------------------------------------------
    def submit(self, x: np.ndarray, timeout: float = 30.0, ctx=None
               ) -> Tuple[np.ndarray, int]:
        """Block until this request's rows come back from a batched
        forward. Returns `(outputs, version)`; raises the batch's error if
        its forward failed, BatcherClosedError after stop(). `ctx` is an
        optional TraceContext: the flush emits queue_wait/batch_forward/
        scatter child spans against it."""
        if int(x.shape[0]) > self.max_batch:   # oversize fails HERE, alone
            raise ServingError(
                f"request of {int(x.shape[0])} rows exceeds max_batch "
                f"{self.max_batch} for '{self.name}' — the direct path "
                "chunks oversize requests; the batcher never splits one")
        if self._stopped:
            raise BatcherClosedError(f"batcher for '{self.name}' is stopped")
        p = _Pending(x, ctx)
        self._queue.append(p)
        self._wake.set()
        if self._stopped and not p.event.is_set():
            # raced a concurrent stop(): the worker may already be gone —
            # reclaim the request instead of blocking out the timeout
            try:
                self._queue.remove(p)
                raise BatcherClosedError(
                    f"batcher for '{self.name}' is stopped")
            except ValueError:
                pass          # the drain took it; wait for its result
        if not p.event.wait(timeout):
            try:
                self._queue.remove(p)   # don't waste a flush on a waiter
            except ValueError:          # that's gone
                pass
            # orphan the thread-local event: an in-flight flush still
            # holds this pending and may set() it later — recycling it
            # into the thread's next request would spuriously wake that
            # unrelated request
            _tls.event = None
            raise TimeoutError(
                f"batched predict on '{self.name}' timed out after "
                f"{timeout:.1f}s")
        if p.error is not None:
            raise p.error
        return p.result, p.version

    def stop(self, drain: bool = True):
        """Stop the worker. With `drain` (default) queued requests are
        flushed first — shutdown never drops accepted work; without it
        they fail with BatcherClosedError. Idempotent and safe to call
        from multiple threads (the canary plane retires arm batchers from
        HTTP handlers while server shutdown may stop them concurrently):
        exactly one caller performs the transition, the rest return once
        the flag is set (the transitioning caller handles the join +
        final drain)."""
        with self._stop_lock:
            if self._stopped:
                return
            if not drain:
                self._fail_queued()
            self._stopped = True
        self._wake.set()
        self._worker.join(timeout=10.0)
        self._fail_queued()   # anything the worker didn't get to

    def _fail_queued(self):
        while True:
            try:
                p = self._queue.popleft()
            except IndexError:
                return
            p.error = BatcherClosedError(
                f"batcher for '{self.name}' stopped")
            p.event.set()

    # -- worker side -----------------------------------------------------
    def _flush_budget(self, avail: int) -> int:
        """Row budget for a deadline flush.

        Padding up is not always right: 18 rows queued against buckets
        (1, 8, 32) would run a 32-wide forward nearly half empty, while
        flushing one full 8 and leaving 10 queued (their original
        enqueue-time deadlines still bind) keeps executable utilization
        high. Which choice wins depends on the measured per-bucket flush
        cost, so the batcher delegates to the FlushEma's adaptive pick.
        A flush can never exceed the largest compiled bucket — a
        max_batch configured above it must not poison whole batches with
        bucket_for() failures at flush time."""
        if self.buckets is None:
            return self.max_batch
        return self._flush_ema.pick_rows(avail, self.buckets,
                                         self.max_batch)

    def _queued_rows(self) -> int:
        # worker-side snapshot; clients only append, so this can lag but
        # never overcounts what the take loop will find. Iterating the
        # deque races concurrent appends ("deque mutated during
        # iteration") — retry, then fall back to len() (an undercount
        # only for multi-row requests, which just means one earlier
        # flush; the take loop re-reads the real rows)
        for _ in range(3):
            try:
                return sum(p.rows for p in self._queue)
            except RuntimeError:
                continue
        return len(self._queue)

    def _take_batch(self) -> Optional[List[_Pending]]:
        """Wait for work, then for either a full batch or the oldest
        request's max-wait deadline; dequeue FIFO without splitting any
        request. Returns None when stopped and drained."""
        queue, wake = self._queue, self._wake
        while not queue:
            if self._stopped:
                return None
            wake.wait(timeout=0.05)
            wake.clear()
        deadline = queue[0].enqueued_at + self.max_wait_s
        avail = self._queued_rows()
        while avail < self.max_batch and not self._stopped:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            wake.wait(timeout=remaining)
            wake.clear()
            avail = self._queued_rows()
        budget = self._flush_budget(min(avail, self.max_batch))
        taken, rows = [], 0
        while queue and rows + queue[0].rows <= budget:
            p = queue.popleft()
            rows += p.rows
            taken.append(p)
        if not taken and queue:
            # head request alone exceeds the budget (multi-row request
            # against a small-bucket budget) — flush it by itself rather
            # than deadlock on it
            taken.append(queue.popleft())
        return taken

    def _flush(self, taken: List[_Pending]):
        rows = sum(p.rows for p in taken)
        t_flush = time.perf_counter()
        scattered = 0
        if self._queue_wait_h is not None:
            for p in taken:
                self._queue_wait_h.observe(t_flush - p.enqueued_at,
                                           model=self.name)
        for p in taken:
            if p.ctx is not None:
                # enqueue -> flush start, stamped with the enqueue time
                # captured on the client's thread
                p.ctx.emit("queue_wait", p.enqueued_at, t_flush,
                           model=self.name, rows=p.rows)
        try:
            x = (taken[0].x if len(taken) == 1
                 else np.concatenate([p.x for p in taken], axis=0))
            bucket = self._bucket_for(rows)
            t0 = time.perf_counter()
            out, version = self._runner(pad_rows(x, bucket - rows), bucket)
            dt = time.perf_counter() - t0
            self._flush_ema.observe(bucket, dt)  # worker-thread-only state
            for p in taken:
                if p.ctx is not None:
                    p.ctx.emit("batch_forward", t0, t0 + dt,
                               model=self.name, bucket=bucket,
                               batch_rows=rows)
            if self._batch_size_h is not None:
                self._batch_size_h.observe(rows, model=self.name)
                self._rows_c.inc(rows, model=self.name, kind="real")
                if bucket - rows:
                    self._rows_c.inc(bucket - rows, model=self.name,
                                     kind="pad")
            lo = 0
            t_scatter = time.perf_counter()
            for p in taken:
                p.result = out[lo:lo + p.rows]
                p.version = version
                lo += p.rows
                scattered += 1
                if p.ctx is not None:
                    # emitted BEFORE event.set(): once the waiter wakes,
                    # its whole trace is already in the buffer
                    p.ctx.emit("scatter", t_scatter, time.perf_counter(),
                               model=self.name, rows=p.rows)
                p.event.set()
        except BaseException as e:   # fail THIS batch, keep serving
            # fail exactly the requests not yet scattered — a scattered
            # request's client may already have recycled its thread-local
            # event into a NEW pending, so touching its event again would
            # spuriously wake that unrelated request
            for p in taken[scattered:]:
                p.error = e
                p.event.set()

    def _loop(self):
        while True:
            try:
                taken = self._take_batch()
            except (IndexError, RuntimeError):
                # IndexError: a timed-out client's queue.remove() emptied
                # the queue between the emptiness check and the head
                # peek. RuntimeError: belt-and-suspenders for any deque
                # mutation race — the worker must NEVER die, or every
                # batched request times out forever
                continue
            if taken is None:
                return
            if taken:
                self._flush(taken)
