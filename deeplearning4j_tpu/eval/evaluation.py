"""Classification evaluation.

Parity with `eval/Evaluation.java:46` (eval:163-194) and
`eval/ConfusionMatrix.java`: accuracy, per-class precision/recall/F1, micro/
macro averages, confusion matrix, top-N accuracy, masked time-series eval,
and a `stats()` text report. Accumulation is a single [C, C] numpy matrix
updated from device arrays once per batch (no per-example host loop).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Evaluation", "ConfusionMatrix", "Prediction"]


class ConfusionMatrix:
    """Counts matrix, rows = actual class, cols = predicted class."""

    def __init__(self, num_classes: int):
        self.matrix = np.zeros((num_classes, num_classes), dtype=np.int64)

    def add(self, actual: np.ndarray, predicted: np.ndarray,
            weights: Optional[np.ndarray] = None):
        n = self.matrix.shape[0]
        flat = actual * n + predicted
        counts = np.bincount(flat, weights=weights, minlength=n * n)
        self.matrix += counts.reshape(n, n).astype(np.int64)

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def to_csv(self) -> str:
        n = self.matrix.shape[0]
        lines = ["," + ",".join(str(i) for i in range(n))]
        for i in range(n):
            lines.append(f"{i}," + ",".join(str(x) for x in self.matrix[i]))
        return "\n".join(lines)


class Prediction:
    """One example's outcome + its metadata (reference
    `eval/meta/Prediction.java`)."""

    __slots__ = ("actual", "predicted", "meta")

    def __init__(self, actual: int, predicted: int, meta):
        self.actual = actual
        self.predicted = predicted
        self.meta = meta

    def __repr__(self):
        return (f"Prediction(actual={self.actual}, "
                f"predicted={self.predicted}, meta={self.meta!r})")


class Evaluation:
    def __init__(self, num_classes: Optional[int] = None,
                 labels: Optional[Sequence[str]] = None, top_n: int = 1):
        if labels is not None and num_classes is None:
            num_classes = len(labels)
        self.num_classes = num_classes
        self.label_names = list(labels) if labels is not None else None
        self.top_n = int(top_n)
        self.confusion: Optional[ConfusionMatrix] = None
        self.top_n_correct = 0
        self.top_n_total = 0
        # per-example metadata attribution (reference eval/meta/ —
        # Prediction records linking outcomes back to example metadata)
        self.predictions: list = []

    # ------------------------------------------------------------------
    def _ensure(self, c: int):
        if self.num_classes is None:
            self.num_classes = c
        if self.confusion is None:
            self.confusion = ConfusionMatrix(self.num_classes)

    @staticmethod
    def _to_index(arr: np.ndarray) -> np.ndarray:
        arr = np.asarray(arr)
        if arr.ndim >= 2 and arr.shape[-1] > 1:
            return np.argmax(arr, axis=-1)
        if arr.ndim >= 2:
            # single-column output: binary, threshold at 0.5 (DL4J Evaluation
            # semantics for sigmoid/single-unit outputs)
            return (arr[..., 0] > 0.5).astype(np.int64)
        if np.issubdtype(arr.dtype, np.floating) and not np.all(arr == arr.astype(np.int64)):
            return (arr > 0.5).astype(np.int64)
        return arr.astype(np.int64)

    def eval(self, labels, predictions, mask: Optional[np.ndarray] = None,
             meta_data: Optional[Sequence] = None):
        """labels: one-hot [N,C] (or [N,T,C] time series), single-column binary
        [N,1], or index array; predictions: probabilities/scores of same shape.
        mask: [N] or [N,T]. meta_data: optional per-example records (length
        N) kept with each prediction for error attribution (reference
        `eval/meta/` — `evaluate(..., List<RecordMetaData>)`)."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim >= 2 and labels.shape[-1] > 1:
            c = labels.shape[-1]
        elif predictions.ndim >= 2 and predictions.shape[-1] > 1:
            c = predictions.shape[-1]
        else:
            c = 2  # single-column / index arrays => binary
        self._ensure(int(c))
        actual = self._to_index(labels).ravel()
        pred = self._to_index(predictions).ravel()
        if meta_data is not None and labels.ndim >= 3:
            # time series: each example contributes T per-timestep
            # predictions — expand per-example metadata to match before any
            # mask filtering
            T = labels.shape[1]
            meta_data = [md for md in meta_data for _ in range(T)]
        if mask is not None:
            m = np.asarray(mask).ravel().astype(bool)
            actual, pred = actual[m], pred[m]
            if meta_data is not None:
                meta_data = [md for md, keep in zip(meta_data, m) if keep]
        if meta_data is not None and len(meta_data) != len(actual):
            # validate BEFORE mutating any accumulator so a caught error
            # leaves the evaluation consistent
            raise ValueError(
                f"meta_data length {len(meta_data)} != examples "
                f"{len(actual)}")
        self.confusion.add(actual, pred)
        if meta_data is not None:
            self.predictions.extend(
                Prediction(int(a), int(p), md)
                for a, p, md in zip(actual, pred, meta_data))
        # top-N accuracy (reference Evaluation topN support)
        if self.top_n > 1 and predictions.ndim >= 2:
            p2 = predictions.reshape(-1, predictions.shape[-1])
            a2 = self._to_index(labels).ravel()
            if mask is not None:
                m = np.asarray(mask).ravel().astype(bool)
                p2, a2 = p2[m], a2[m]
            topk = np.argsort(-p2, axis=1)[:, :self.top_n]
            self.top_n_correct += int((topk == a2[:, None]).any(axis=1).sum())
            self.top_n_total += len(a2)

    def eval_time_series(self, labels, predictions, labels_mask=None):
        self.eval(labels, predictions, mask=labels_mask)

    # -- per-example attribution (reference EvaluationUtils meta queries) --
    def get_prediction_errors(self) -> list:
        """Misclassified examples with their metadata."""
        return [p for p in self.predictions if p.actual != p.predicted]

    def get_predictions_by_actual_class(self, cls: int) -> list:
        return [p for p in self.predictions if p.actual == cls]

    def get_predictions_by_predicted_class(self, cls: int) -> list:
        return [p for p in self.predictions if p.predicted == cls]

    def get_predictions(self, actual: int, predicted: int) -> list:
        """Examples in one confusion-matrix cell."""
        return [p for p in self.predictions
                if p.actual == actual and p.predicted == predicted]

    def merge(self, other: "Evaluation"):
        if other.confusion is None:
            return
        self._ensure(other.num_classes)
        self.confusion.matrix += other.confusion.matrix
        self.top_n_correct += other.top_n_correct
        self.top_n_total += other.top_n_total
        self.predictions.extend(other.predictions)

    # ------------------------------------------------------------------
    @property
    def _m(self) -> np.ndarray:
        return self.confusion.matrix if self.confusion is not None else np.zeros((0, 0))

    def num_examples(self) -> int:
        return int(self._m.sum())

    def true_positives(self) -> np.ndarray:
        return np.diag(self._m)

    def false_positives(self) -> np.ndarray:
        return self._m.sum(axis=0) - np.diag(self._m)

    def false_negatives(self) -> np.ndarray:
        return self._m.sum(axis=1) - np.diag(self._m)

    def accuracy(self) -> float:
        total = self._m.sum()
        return float(np.diag(self._m).sum() / total) if total else 0.0

    def top_n_accuracy(self) -> float:
        return self.top_n_correct / self.top_n_total if self.top_n_total else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        tp, fp = self.true_positives(), self.false_positives()
        if cls is not None:
            d = tp[cls] + fp[cls]
            return float(tp[cls] / d) if d else 0.0
        per = [self.precision(i) for i in range(self.num_classes)
               if (tp[i] + fp[i] + self.false_negatives()[i]) > 0]
        return float(np.mean(per)) if per else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        tp, fn = self.true_positives(), self.false_negatives()
        if cls is not None:
            d = tp[cls] + fn[cls]
            return float(tp[cls] / d) if d else 0.0
        per = [self.recall(i) for i in range(self.num_classes)
               if (tp[i] + fn[i] + self.false_positives()[i]) > 0]
        return float(np.mean(per)) if per else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def stats(self) -> str:
        lines = ["", "========================Evaluation Metrics========================"]
        lines.append(f" # of classes:    {self.num_classes}")
        lines.append(f" Examples:        {self.num_examples()}")
        lines.append(f" Accuracy:        {self.accuracy():.4f}")
        if self.top_n > 1:
            lines.append(f" Top {self.top_n} Accuracy:  {self.top_n_accuracy():.4f}")
        lines.append(f" Precision:       {self.precision():.4f}")
        lines.append(f" Recall:          {self.recall():.4f}")
        lines.append(f" F1 Score:        {self.f1():.4f}")
        lines.append("")
        lines.append("=========================Confusion Matrix=========================")
        n = self.num_classes or 0
        names = self.label_names or [str(i) for i in range(n)]
        lines.append("   " + " ".join(f"{i:>6}" for i in range(n)))
        for i in range(n):
            lines.append(f"{i:>2} " + " ".join(f"{self._m[i, j]:>6}" for j in range(n))
                         + f"  | {names[i]}")
        lines.append("==================================================================")
        return "\n".join(lines)

    def confusion_to_string(self) -> str:
        return self.confusion.to_csv() if self.confusion else ""
