"""Evaluation export helpers (reference `evaluation/EvaluationTools.java` —
ROC/PR chart HTML export built on the UI component DSL).
"""
from __future__ import annotations

from typing import Optional

from .evaluation import Evaluation
from .roc import ROC, ROCMultiClass

__all__ = ["EvaluationTools"]


class EvaluationTools:
    @staticmethod
    def roc_chart_html(roc: ROC, title: str = "ROC") -> str:
        from ..ui.components import (ChartLine, ComponentText, StyleChart,
                                     render_page)

        curve = roc.get_roc_curve()          # [(threshold, fpr, tpr)]
        fpr = [p[1] for p in curve]
        tpr = [p[2] for p in curve]
        chart = (ChartLine(f"{title} (AUC={roc.calculate_auc():.4f})",
                           StyleChart(520, 320))
                 .add_series("ROC", fpr, tpr)
                 .add_series("chance", [0.0, 1.0], [0.0, 1.0]))
        pr = roc.get_precision_recall_curve()
        comps = [chart]
        if pr:
            rec = [p[1] for p in pr]
            prec = [p[2] for p in pr]
            comps.append(
                ChartLine(f"Precision-Recall "
                          f"(AUPRC={roc.calculate_auprc():.4f})",
                          StyleChart(520, 320))
                .add_series("PR", rec, prec))
        comps.append(ComponentText(
            f"AUC: {roc.calculate_auc():.6f} — "
            f"AUPRC: {roc.calculate_auprc():.6f}"))
        return render_page(title, comps)

    @staticmethod
    def export_roc_charts_to_html_file(roc: ROC, path: str,
                                       title: str = "ROC"):
        """`EvaluationTools.exportRocChartsToHtmlFile` parity."""
        with open(path, "w") as f:
            f.write(EvaluationTools.roc_chart_html(roc, title))

    @staticmethod
    def roc_multi_class_chart_html(roc: ROCMultiClass,
                                   title: str = "ROC (one-vs-all)") -> str:
        from ..ui.components import ChartLine, StyleChart, render_page

        chart = ChartLine(title, StyleChart(560, 340))
        for cls in range(roc.num_classes):
            curve = roc.get_roc_curve(cls)
            chart.add_series(
                f"class {cls} (AUC={roc.calculate_auc(cls):.3f})",
                [p[1] for p in curve], [p[2] for p in curve])
        return render_page(title, [chart])

    @staticmethod
    def export_confusion_matrix_html_file(ev: Evaluation, path: str,
                                          title: str = "Evaluation"):
        from ..ui.components import (ComponentTable, ComponentText,
                                     render_page)

        m = ev._m   # empty (0, 0) matrix when nothing evaluated yet
        n = m.shape[0]
        if n == 0:
            comps = [ComponentText("accuracy n/a — no examples evaluated")]
            with open(path, "w") as f:
                f.write(render_page(title, comps))
            return
        names = (ev.label_names
                 if ev.label_names and len(ev.label_names) == n
                 else [str(i) for i in range(n)])
        header = ["actual \\ predicted"] + list(names)
        rows = [[names[i]] + [int(v) for v in m[i]] for i in range(n)]
        comps = [ComponentText(
            f"accuracy {ev.accuracy():.4f} — precision "
            f"{ev.precision():.4f} — recall {ev.recall():.4f} — F1 "
            f"{ev.f1():.4f}"), ComponentTable(header, rows)]
        with open(path, "w") as f:
            f.write(render_page(title, comps))
