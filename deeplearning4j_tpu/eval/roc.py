"""ROC / AUC evaluation.

Parity with `eval/ROC.java:34` (thresholded binary ROC with configurable step
count) and `eval/ROCMultiClass.java` (one-vs-all per class). Accumulates
per-threshold TP/FP/TN/FN counts batch-by-batch (device arrays reduced once
per batch), so AUC is exact for the chosen threshold grid, like the reference.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

__all__ = ["ROC", "ROCMultiClass"]


class ROC:
    def __init__(self, threshold_steps: int = 100):
        self.threshold_steps = int(threshold_steps)
        self.thresholds = np.linspace(0.0, 1.0, self.threshold_steps + 1)
        self.tp = np.zeros(self.threshold_steps + 1, np.int64)
        self.fp = np.zeros_like(self.tp)
        self.fn = np.zeros_like(self.tp)
        self.tn = np.zeros_like(self.tp)

    def eval(self, labels, probs, mask: Optional[np.ndarray] = None):
        """labels: [N] or [N,1] in {0,1} or one-hot [N,2]; probs: same shape
        (probability of the positive class; for [N,2] the 2nd column)."""
        labels = np.asarray(labels)
        probs = np.asarray(probs)
        if labels.ndim >= 2 and labels.shape[-1] == 2:
            labels = labels[..., 1]
            probs = probs[..., 1]
        labels = labels.reshape(-1)
        probs = probs.reshape(-1)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, probs = labels[m], probs[m]
        pos = labels > 0.5
        # vectorized: predictions at each threshold
        pred = probs[None, :] >= self.thresholds[:, None]  # [T+1, N]
        self.tp += (pred & pos[None, :]).sum(axis=1)
        self.fp += (pred & ~pos[None, :]).sum(axis=1)
        self.fn += (~pred & pos[None, :]).sum(axis=1)
        self.tn += (~pred & ~pos[None, :]).sum(axis=1)

    def get_roc_curve(self) -> List[Tuple[float, float, float]]:
        """[(threshold, fpr, tpr)]."""
        out = []
        for i, t in enumerate(self.thresholds):
            p = self.tp[i] + self.fn[i]
            n = self.fp[i] + self.tn[i]
            tpr = self.tp[i] / p if p else 0.0
            fpr = self.fp[i] / n if n else 0.0
            out.append((float(t), float(fpr), float(tpr)))
        return out

    def calculate_auc(self) -> float:
        pts = self.get_roc_curve()
        fprs = np.array([p[1] for p in pts])
        tprs = np.array([p[2] for p in pts])
        order = np.lexsort((tprs, fprs))  # ties in fpr ordered by tpr
        x = np.concatenate([[0.0], fprs[order], [1.0]])
        y = np.concatenate([[0.0], tprs[order], [1.0]])
        return float(np.trapezoid(y, x))

    def get_precision_recall_curve(self):
        """[(threshold, recall, precision)] per threshold (reference
        `getPrecisionRecallCurve`)."""
        out = []
        for i, t in enumerate(self.thresholds):
            denom_p = self.tp[i] + self.fp[i]
            denom_r = self.tp[i] + self.fn[i]
            prec = self.tp[i] / denom_p if denom_p else 1.0
            rec = self.tp[i] / denom_r if denom_r else 0.0
            out.append((float(t), float(rec), float(prec)))
        return out

    def calculate_auprc(self) -> float:
        """Area under precision-recall curve (trapezoid over the grid)."""
        pts = self.get_precision_recall_curve()
        pairs = sorted((r, p) for _, r, p in pts)
        auc = 0.0
        for (r0, p0), (r1, p1) in zip(pairs[:-1], pairs[1:]):
            auc += (r1 - r0) * (p1 + p0) / 2.0
        return float(auc)


class ROCMultiClass:
    """One-vs-all ROC per class (reference `eval/ROCMultiClass.java`)."""

    def __init__(self, threshold_steps: int = 100):
        self.threshold_steps = int(threshold_steps)
        self._rocs: List[ROC] = []

    def eval(self, labels, probs, mask: Optional[np.ndarray] = None):
        labels = np.asarray(labels)
        probs = np.asarray(probs)
        c = labels.shape[-1]
        if not self._rocs:
            self._rocs = [ROC(self.threshold_steps) for _ in range(c)]
        lab2 = labels.reshape(-1, c)
        pr2 = probs.reshape(-1, c)
        m = None if mask is None else np.asarray(mask).reshape(-1)
        for i in range(c):
            self._rocs[i].eval(lab2[:, i], pr2[:, i], mask=m)

    @property
    def num_classes(self) -> int:
        return len(self._rocs)

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs]))

    def get_roc_curve(self, cls: int):
        return self._rocs[cls].get_roc_curve()
