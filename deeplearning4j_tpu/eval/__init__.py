from .evaluation import Evaluation, ConfusionMatrix
from .roc import ROC, ROCMultiClass
from .regression import RegressionEvaluation

__all__ = ["Evaluation", "ConfusionMatrix", "ROC", "ROCMultiClass",
           "RegressionEvaluation"]
