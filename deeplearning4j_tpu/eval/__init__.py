from .evaluation import Evaluation, ConfusionMatrix

__all__ = ["Evaluation", "ConfusionMatrix"]
