"""Regression evaluation.

Parity with `eval/RegressionEvaluation.java:26`: per-column MSE, MAE, RMSE,
RSE (relative squared error), and Pearson correlation, with a `stats()` text
report and column labels. Streaming accumulation of sufficient statistics.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = ["RegressionEvaluation"]


class RegressionEvaluation:
    def __init__(self, column_names: Optional[Sequence[str]] = None,
                 n_columns: Optional[int] = None):
        if column_names is not None:
            n_columns = len(column_names)
        self.column_names = list(column_names) if column_names else None
        self.n = None if n_columns is None else int(n_columns)
        self._init_done = False

    def _ensure(self, c):
        if self._init_done:
            return
        self.n = c if self.n is None else self.n
        z = np.zeros(self.n, np.float64)
        self.count = z.copy()
        self.sum_sq_err = z.copy()
        self.sum_abs_err = z.copy()
        self.sum_label = z.copy()
        self.sum_label_sq = z.copy()
        self.sum_pred = z.copy()
        self.sum_pred_sq = z.copy()
        self.sum_label_pred = z.copy()
        self._init_done = True

    def eval(self, labels, predictions, mask: Optional[np.ndarray] = None):
        labels = np.asarray(labels, np.float64)
        preds = np.asarray(predictions, np.float64)
        c = labels.shape[-1]
        self._ensure(c)
        lab = labels.reshape(-1, c)
        pr = preds.reshape(-1, c)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            lab, pr = lab[m], pr[m]
        err = pr - lab
        self.count += lab.shape[0]
        self.sum_sq_err += (err ** 2).sum(axis=0)
        self.sum_abs_err += np.abs(err).sum(axis=0)
        self.sum_label += lab.sum(axis=0)
        self.sum_label_sq += (lab ** 2).sum(axis=0)
        self.sum_pred += pr.sum(axis=0)
        self.sum_pred_sq += (pr ** 2).sum(axis=0)
        self.sum_label_pred += (lab * pr).sum(axis=0)

    def eval_time_series(self, labels, predictions, labels_mask=None):
        self.eval(labels, predictions, mask=labels_mask)

    # ------------------------------------------------------------------
    def mean_squared_error(self, col: int) -> float:
        return float(self.sum_sq_err[col] / self.count[col])

    def mean_absolute_error(self, col: int) -> float:
        return float(self.sum_abs_err[col] / self.count[col])

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def relative_squared_error(self, col: int) -> float:
        n = self.count[col]
        mean_label = self.sum_label[col] / n
        denom = self.sum_label_sq[col] - n * mean_label ** 2
        return float(self.sum_sq_err[col] / denom) if denom else float("inf")

    def pearson_correlation(self, col: int) -> float:
        n = self.count[col]
        cov = self.sum_label_pred[col] - self.sum_label[col] * self.sum_pred[col] / n
        vl = self.sum_label_sq[col] - self.sum_label[col] ** 2 / n
        vp = self.sum_pred_sq[col] - self.sum_pred[col] ** 2 / n
        d = np.sqrt(vl * vp)
        return float(cov / d) if d else 0.0

    def average_mean_squared_error(self) -> float:
        return float(np.mean([self.mean_squared_error(i) for i in range(self.n)]))

    def average_mean_absolute_error(self) -> float:
        return float(np.mean([self.mean_absolute_error(i) for i in range(self.n)]))

    def average_root_mean_squared_error(self) -> float:
        return float(np.mean([self.root_mean_squared_error(i)
                              for i in range(self.n)]))

    def average_pearson_correlation(self) -> float:
        return float(np.mean([self.pearson_correlation(i) for i in range(self.n)]))

    def stats(self) -> str:
        names = self.column_names or [f"col_{i}" for i in range(self.n)]
        lines = ["", f"{'Column':<16}{'MSE':>12}{'MAE':>12}{'RMSE':>12}"
                     f"{'RSE':>12}{'R':>12}"]
        for i, name in enumerate(names):
            lines.append(
                f"{name:<16}{self.mean_squared_error(i):>12.5f}"
                f"{self.mean_absolute_error(i):>12.5f}"
                f"{self.root_mean_squared_error(i):>12.5f}"
                f"{self.relative_squared_error(i):>12.5f}"
                f"{self.pearson_correlation(i):>12.5f}")
        return "\n".join(lines)
