"""Shape-stable, device-prefetched input pipeline.

Two composable stages between any `DataSetIterator` and the fit loops:

  * `PadToBatchIterator` — shape stabilization. Ragged final batches are
    padded up to the fixed batch size with weight-zero rows (every batch
    carries a labels mask whose padded rows are zero), and optionally the
    time axis of sequence data is padded up to a small set of buckets.
    One batch signature per epoch instead of 2+ means ONE XLA compile of
    the train step instead of one per distinct shape — the recompile
    pathology PR 2's CompileWatcher made visible.
  * `DevicePrefetchIterator` — device prefetch. A background thread runs
    `DataSet.device_tuple()` (the host->device transfer) one batch ahead,
    double-buffered, so H2D overlaps the previous step's device compute —
    the same pipeline `AsyncDataSetIterator` (and the reference's
    JVM-side double buffering) provides for host batch ASSEMBLY, extended
    to the transfer itself.

Padding is a provable learning no-op (see `pad_dataset`): the loss is a
masked mean normalized by the REAL (mask-live) entry count, and the
models' regularization term is normalized by the live ROW count whenever
a labels mask is present — so padded rows contribute neither loss nor
gradient, and the denominator matches the unpadded run. Caveats that
break exactness: BatchNorm in train mode (batch statistics see the pad
rows) and dropout (mask shapes differ, so the per-element randomness
differs) — both stay correct in expectation but are not bitwise-equal to
the unpadded run.

Donation safety: the jitted train steps donate ONLY params/state/updater
state (`donate_argnums=(0, 1, 2)`); batch tensors are never donated, so
buffers transferred by the prefetch thread are never aliased with (or
invalidated by) a donated argument.

Telemetry (when a session is active): `dl4j_pipeline_rows_total{kind=
real|pad}` (pad_fraction), `dl4j_pipeline_prefetch_wait_seconds` (how
long the consumer stalled waiting on the prefetch thread — ~0 means the
transfer fully overlapped compute), and
`dl4j_pipeline_bucket_hits_total{bucket=...}` for time bucketing.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from .iterators import (AsyncDataSetIterator, DataSet, DataSetIterator,
                        MultiDataSet)

__all__ = ["PadToBatchIterator", "DevicePrefetchIterator",
           "MicrobatchSplitIterator", "pad_dataset", "pad_rows",
           "build_pipeline", "split_microbatches", "stage_window",
           "batch_nbytes", "split_xy"]


# ---------------------------------------------------------------------------
# Telemetry plumbing (no-op when no session is active)
# ---------------------------------------------------------------------------
def _pipeline_metrics():
    """(rows counter, prefetch-wait timer, bucket counter) of the active
    session's registry, or None."""
    from ..telemetry import runtime
    tel = runtime.active()
    if tel is None:
        return None
    reg = tel.registry
    return (reg.counter("dl4j_pipeline_rows_total",
                        "input-pipeline rows by kind (real vs padding)",
                        labels=("kind",)),
            reg.timer("dl4j_pipeline_prefetch_wait_seconds",
                      "seconds the consumer stalled on the prefetch queue"),
            reg.counter("dl4j_pipeline_bucket_hits_total",
                        "batches landing in each time-axis bucket",
                        labels=("bucket",)))


def _count_rows(real: int, pad: int):
    m = _pipeline_metrics()
    if m is not None:
        m[0].inc(real, kind="real")
        if pad:
            m[0].inc(pad, kind="pad")


def _count_bucket(bucket: int):
    m = _pipeline_metrics()
    if m is not None:
        m[2].inc(1, bucket=str(bucket))


# ---------------------------------------------------------------------------
# Shape stabilization
# ---------------------------------------------------------------------------
def _per_example_mask_shape(labels: np.ndarray) -> tuple:
    """Shape of the per-example loss the losses module reduces over —
    `[B]` for flat labels, `[B, T]` for sequence labels (losses._apply_mask
    broadcasts the mask over the trailing feature axis)."""
    return labels.shape[:-1] if labels.ndim >= 2 else (labels.shape[0],)


def pad_rows(a, n_pad):
    """Append `n_pad` zero rows along axis 0 (the PadToBatch row shaping,
    shared with the serving plane's DynamicBatcher: requests coalesce into
    fixed-shape buckets by padding with zero rows, and the pad rows are
    stripped before results scatter back to waiters)."""
    if a is None or n_pad == 0:
        return a
    return np.concatenate(
        [a, np.zeros((n_pad,) + a.shape[1:], dtype=a.dtype)], axis=0)


_pad_rows = pad_rows


def split_xy(record: np.ndarray, feature_width: int
             ) -> Tuple[np.ndarray, np.ndarray]:
    """Split a tokenized topic record `[rows, feature_width + ny]` into
    `(features, labels)` float32 arrays — the streaming plane publishes
    each training window as one such concatenated array (the continual
    trainer's default record decoder). A 1-D record is treated as a
    single row."""
    a = np.asarray(record, np.float32)
    if a.ndim == 1:
        a = a[None]
    if a.ndim != 2 or a.shape[1] <= feature_width:
        raise ValueError(
            f"record shape {tuple(a.shape)} cannot split into "
            f"features[:{feature_width}] + labels — expected "
            f"[rows, > {feature_width}]")
    return a[:, :feature_width], a[:, feature_width:]


def _pad_time(a, t_pad, axis=1):
    if a is None or t_pad == 0 or a.ndim <= axis:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, t_pad)
    return np.pad(a, widths)


def pad_dataset(ds, target_rows: int, time_target: Optional[int] = None):
    """Pad `ds` (DataSet or MultiDataSet) up to `target_rows` rows (and,
    for rank>=3 features, up to `time_target` timesteps) with weight-zero
    entries. Returns `(padded, n_real, n_pad)`.

    The padded dataset ALWAYS carries a labels mask (ones over real
    entries, zeros over padding) so every batch of an epoch shares one
    signature and the loss/regularization normalize by the real count.
    A features mask is synthesized only when the time axis is padded
    (row-only padding leaves absent features masks absent, preserving
    the network's unmasked forward path)."""
    if isinstance(ds, MultiDataSet):
        return _pad_multi(ds, target_rows)
    n = ds.num_examples()
    n_pad = target_rows - n
    if n_pad < 0:
        raise ValueError(
            f"batch of {n} rows exceeds the pipeline batch size "
            f"{target_rows}; PadToBatchIterator only pads, never splits")
    feats = np.asarray(ds.features)
    labels = None if ds.labels is None else np.asarray(ds.labels)
    fmask = None if ds.features_mask is None else np.asarray(ds.features_mask)
    lmask = None if ds.labels_mask is None else np.asarray(ds.labels_mask)

    t_pad = 0
    if time_target is not None and feats.ndim >= 3:
        t = feats.shape[1]
        t_pad = time_target - t
        if t_pad < 0:
            raise ValueError(
                f"sequence length {t} exceeds the largest time bucket "
                f"{time_target}")
        if t_pad:
            feats = _pad_time(feats, t_pad)
            if labels is not None and labels.ndim >= 3:
                labels = _pad_time(labels, t_pad)
            lmask = _pad_time(lmask, t_pad)
        # a padded time axis needs a features mask so recurrent layers see
        # the true lengths; synthesize one even for t_pad == 0 batches so
        # bucketed epochs stay signature-stable
        if fmask is None:
            fmask = np.zeros(feats.shape[:2], np.float32)
            fmask[:n, :feats.shape[1] - t_pad] = 1.0
        else:
            fmask = _pad_time(fmask, t_pad)

    if lmask is None and labels is not None:
        shape = _per_example_mask_shape(labels)
        lmask = np.ones(shape, np.float32)
        if t_pad and len(shape) >= 2:
            lmask[:, shape[1] - t_pad:] = 0.0
    feats = _pad_rows(feats, n_pad)
    labels = _pad_rows(labels, n_pad)
    fmask = _pad_rows(fmask, n_pad)
    lmask = _pad_rows(lmask, n_pad)
    return DataSet(feats, labels, fmask, lmask), n, n_pad


def _pad_multi(ds: MultiDataSet, target_rows: int):
    """Row padding for MultiDataSet (time bucketing is single-DataSet
    only): every output gets a labels mask with zero pad rows."""
    n = ds.num_examples()
    n_pad = target_rows - n
    if n_pad < 0:
        raise ValueError(
            f"batch of {n} rows exceeds the pipeline batch size "
            f"{target_rows}; PadToBatchIterator only pads, never splits")
    feats = [_pad_rows(np.asarray(a), n_pad) for a in ds.features]
    labels = [_pad_rows(np.asarray(a), n_pad) for a in ds.labels]
    fmasks = None
    if ds.features_masks is not None:
        fmasks = [None if m is None else _pad_rows(np.asarray(m), n_pad)
                  for m in ds.features_masks]
    lmasks = list(ds.labels_masks) if ds.labels_masks is not None \
        else [None] * len(ds.labels)
    for i, (lab, m) in enumerate(zip(ds.labels, lmasks)):
        if m is None:
            m = np.ones(_per_example_mask_shape(np.asarray(lab)), np.float32)
        else:
            m = np.asarray(m)
        lmasks[i] = _pad_rows(m, n_pad)
    return MultiDataSet(features=feats, labels=labels,
                        features_masks=fmasks, labels_masks=lmasks), n, n_pad


class PadToBatchIterator(DataSetIterator):
    """Pads every batch of `source` up to a fixed row count (and optional
    time buckets) with weight-zero entries — see `pad_dataset` for the
    no-op argument. Batch size comes from `batch_size`, else
    `source.batch()`, else lazily from the first batch of the epoch
    (standard iterators emit full batches first, ragged batch last).

    `time_buckets`: ascending sequence of allowed sequence lengths; each
    rank-3 batch is padded up to the smallest bucket >= its length, so an
    epoch of arbitrary lengths produces at most `len(time_buckets)`
    signatures."""

    def __init__(self, source: DataSetIterator, batch_size: Optional[int] = None,
                 time_buckets: Optional[Sequence[int]] = None):
        self.source = source
        declared = int(batch_size) if batch_size else 0
        if declared <= 0:
            declared = int(getattr(source, "batch", lambda: 0)() or 0)
        self._target = declared if declared > 0 else None
        self._target_inferred = self._target is None
        self.time_buckets = (tuple(sorted(int(b) for b in time_buckets))
                             if time_buckets else None)
        self.pad_rows = 0
        self.real_rows = 0

    def _bucket_for(self, t: int) -> int:
        for b in self.time_buckets:
            if t <= b:
                return b
        raise ValueError(
            f"sequence length {t} exceeds the largest time bucket "
            f"{self.time_buckets[-1]}")

    def reset(self):
        self.source.reset()

    def has_next(self) -> bool:
        return self.source.has_next()

    def next(self) -> DataSet:
        ds = self.source.next()
        n = ds.num_examples()
        if self._target is None:
            self._target = n
        elif self._target_inferred and n > self._target:
            # the lazy inference assumed full-batches-first (the standard
            # iterator layout); a growing batch means it guessed wrong
            raise ValueError(
                f"batch of {n} rows exceeds the batch size {self._target} "
                "inferred from this epoch's first batch; pass "
                "PadToBatchIterator(batch_size=...) explicitly for sources "
                "whose batch() is unknown and whose first batch is not "
                "full-size")
        time_target = None
        if (self.time_buckets is not None and not isinstance(ds, MultiDataSet)
                and np.asarray(ds.features).ndim >= 3):
            time_target = self._bucket_for(np.asarray(ds.features).shape[1])
            _count_bucket(time_target)
        padded, n_real, n_pad = pad_dataset(ds, self._target, time_target)
        self.real_rows += n_real
        self.pad_rows += n_pad
        _count_rows(n_real, n_pad)
        return padded

    def batch(self) -> int:
        return self._target or self.source.batch()

    @property
    def pad_fraction(self) -> float:
        total = self.real_rows + self.pad_rows
        return self.pad_rows / total if total else 0.0


# ---------------------------------------------------------------------------
# Device prefetch
# ---------------------------------------------------------------------------
class DevicePrefetchIterator(AsyncDataSetIterator):
    """Background-thread DEVICE prefetch: one batch ahead, the worker runs
    `device_tuple()` — dispatching the host->device transfer — so the
    consumer's `device_tuple()` call is a cache hit and H2D overlaps the
    previous step's compute (double buffering, the `AsyncDataSetIterator`
    contract extended from host assembly to the transfer).

    Donation-safe by construction: the fit paths donate only
    params/state/updater-state to the jitted step; batch tensors (the only
    thing this thread touches) are never donated. Accepts DataSet and
    MultiDataSet sources alike (both expose `device_tuple`)."""

    def _prepare(self, ds):
        ds.device_tuple()   # async dispatch: transfer starts NOW
        return ds

    def _fetch(self):
        m = _pipeline_metrics()
        if m is None:
            return super()._fetch()
        with m[1].time():
            return super()._fetch()


# ---------------------------------------------------------------------------
# Microbatch splitting (gradient accumulation input side)
# ---------------------------------------------------------------------------
class MicrobatchSplitIterator(DataSetIterator):
    """Slice every batch of `source` into consecutive microbatches of
    `microbatch_size` rows (zero-copy numpy views) — the input-side half
    of gradient accumulation. A big-batch pipeline composes with
    `fit(grad_accumulation=M)` as::

        it = split_microbatches(big_batch_iterator, b)   # B = M·b rows
        model.fit(it, grad_accumulation=M)

    and trains the IDENTICAL [M, b, ...] stacked windows a native
    microbatch iterator over the same rows would: staging M contiguous
    row-slices of one array equals reshaping that array to [M, b, ...],
    so "one batch of M·b rows" and "M microbatches of b rows" are the
    same bits by construction (tests/test_accumulation.py asserts the
    equivalence). A source batch whose row count is not a multiple of
    `microbatch_size` yields a smaller final slice — a signature change
    that closes the accumulation group early, exactly like a ragged tail
    (pad_ragged upstream keeps every slice full)."""

    def __init__(self, source: DataSetIterator, microbatch_size: int):
        if int(microbatch_size) < 1:
            raise ValueError(
                f"microbatch_size must be a positive int, got "
                f"{microbatch_size!r}")
        self.source = source
        self.microbatch_size = int(microbatch_size)
        self._pending = []

    def _slices(self, ds):
        n = ds.num_examples()
        b = self.microbatch_size
        if n <= b:
            return [ds]
        cut = lambda a, lo, hi: None if a is None else np.asarray(a)[lo:hi]
        out = []
        for lo in range(0, n, b):
            hi = min(lo + b, n)
            if isinstance(ds, MultiDataSet):
                cl = lambda xs: (None if xs is None
                                 else [cut(a, lo, hi) for a in xs])
                out.append(MultiDataSet(features=cl(ds.features),
                                        labels=cl(ds.labels),
                                        features_masks=cl(ds.features_masks),
                                        labels_masks=cl(ds.labels_masks)))
            else:
                out.append(DataSet(cut(ds.features, lo, hi),
                                   cut(ds.labels, lo, hi),
                                   cut(ds.features_mask, lo, hi),
                                   cut(ds.labels_mask, lo, hi)))
        return out

    def reset(self):
        self._pending = []
        self.source.reset()

    def has_next(self) -> bool:
        return bool(self._pending) or self.source.has_next()

    def next(self):
        if not self._pending:
            self._pending = self._slices(self.source.next())
        return self._pending.pop(0)

    def batch(self) -> int:
        return self.microbatch_size

    def set_epoch(self, epoch: int):
        if hasattr(self.source, "set_epoch"):
            self.source.set_epoch(epoch)


def split_microbatches(source: DataSetIterator, microbatch_size: int
                       ) -> MicrobatchSplitIterator:
    """Convenience constructor for `MicrobatchSplitIterator`."""
    return MicrobatchSplitIterator(source, microbatch_size)


# ---------------------------------------------------------------------------
# Superstep window staging
# ---------------------------------------------------------------------------
def stage_window(batch_trees):
    """Stack a superstep window's per-batch pytrees (tuples/dicts of
    arrays, with None leaves for absent masks) along a new leading window
    axis — the [K, batch, ...] input of the jitted superstep
    (`nn/superstep.py`).

    None leaves stay None, so the scan body sees the same static absence
    the per-batch train step does. Host numpy batches pay ONE fused
    host->device transfer for the whole window; batches a
    `DevicePrefetchIterator` already staged on device stack with a device
    op instead of a second H2D copy. Under the pipelined superstep loop
    this call runs while the PREVIOUS window computes, so the transfer
    overlaps device compute exactly like the per-batch prefetch did."""
    import jax
    import jax.numpy as jnp

    def stack(*leaves):
        return None if leaves[0] is None else jnp.stack(leaves)

    return jax.tree_util.tree_map(stack, *batch_trees,
                                  is_leaf=lambda x: x is None)


def batch_nbytes(arrays) -> int:
    """Byte size of one batch's arrays (None entries skipped) WITHOUT
    materializing device buffers on host — `superstep="auto"` window
    sizing reads shapes/dtypes only."""
    total = 0
    for a in arrays:
        shape = getattr(a, "shape", None)
        if a is None or shape is None:
            continue
        dt = np.dtype(getattr(a, "dtype", np.float32))
        total += int(np.prod(shape)) * dt.itemsize
    return total


# ---------------------------------------------------------------------------
# Fit-path assembly
# ---------------------------------------------------------------------------
def build_pipeline(data: DataSetIterator, *, pad_ragged: bool = False,
                   prefetch: bool = False,
                   batch_size: Optional[int] = None,
                   time_buckets: Optional[Sequence[int]] = None,
                   queue_size: int = 2) -> Tuple[DataSetIterator, callable]:
    """Wrap `data` with the requested pipeline stages. Returns
    `(iterator, close)`; callers MUST invoke `close()` when done so the
    prefetch thread shuts down instead of leaking across fits."""
    it = data
    if pad_ragged or time_buckets:
        it = PadToBatchIterator(it, batch_size=batch_size,
                                time_buckets=time_buckets)
    if prefetch and getattr(data, "async_supported", True):
        it = DevicePrefetchIterator(it, queue_size=queue_size)
        return it, it.close
    return it, lambda: None
