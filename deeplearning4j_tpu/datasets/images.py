"""Image record-reader tier — the DataVec image pipeline analog.

Reference: `datavec-data-image` `NativeImageLoader` (JavaCV decode +
resize + NCHW tensorize) consumed by `ImageRecordReader` and the dataset
iterators (`deeplearning4j-core/.../datasets/iterator/impl/
CifarDataSetIterator.java:17` runs CIFAR through this tier; LFW likewise).

TPU-first shape: decode runs in the native C++ tier (PNG/BMP/PPM — see
`native/dl4j_native.cpp` image_* functions) with PIL as the fallback for
JPEG and exotic formats; resize is a vectorized numpy bilinear (one
gather per output row/col); tensors are NHWC float32 (TPU's layout),
scaled to [0, 1].
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .iterators import DataSet, DataSetIterator

__all__ = ["ImageLoader", "ImageRecordReader",
           "ImageRecordDataSetIterator"]

_EXTS = (".png", ".bmp", ".ppm", ".pgm", ".jpg", ".jpeg", ".gif", ".webp")


def _decode(path: str) -> np.ndarray:
    """uint8 [H, W, C]: native tier first, PIL fallback."""
    from ..native import image_decode_native, native_available

    if native_available():
        img = image_decode_native(path)
        if img is not None:
            return img
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB") if im.mode not in ("L", "RGB") else im
        arr = np.asarray(im, np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def _resize_bilinear(img: np.ndarray, h: int, w: int) -> np.ndarray:
    """Vectorized bilinear resize, uint8 [H,W,C] -> float32 [h,w,C]."""
    H, W, _ = img.shape
    x = img.astype(np.float32)
    if (H, W) == (h, w):
        return x
    ys = (np.arange(h) + 0.5) * H / h - 0.5
    xs = (np.arange(w) + 0.5) * W / w - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, H - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, W - 1)
    y1 = np.minimum(y0 + 1, H - 1)
    x1 = np.minimum(x0 + 1, W - 1)
    wy = np.clip(ys - y0, 0.0, 1.0).astype(np.float32)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0).astype(np.float32)[None, :, None]
    top = x[y0][:, x0] * (1 - wx) + x[y0][:, x1] * wx
    bot = x[y1][:, x0] * (1 - wx) + x[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


class ImageLoader:
    """NativeImageLoader analog: decode + channel-fix + resize + scale to
    [0,1] float32 NHWC slab."""

    def __init__(self, height: int, width: int, channels: int = 3):
        self.height = int(height)
        self.width = int(width)
        self.channels = int(channels)

    def load(self, path: str) -> np.ndarray:
        img = _decode(path)
        if img.shape[2] == 2:   # gray+alpha (PNG color type 4): drop alpha
            img = img[:, :, :1]
        c = img.shape[2]
        if c != self.channels:
            if self.channels == 3 and c == 1:
                img = np.repeat(img, 3, axis=2)
            elif self.channels == 1 and c >= 3:
                img = np.round(
                    img[:, :, 0] * 0.299 + img[:, :, 1] * 0.587
                    + img[:, :, 2] * 0.114).astype(np.uint8)[:, :, None]
            elif self.channels == 3 and c == 4:
                img = img[:, :, :3]
            else:
                raise ValueError(
                    f"{path}: {c} channels, loader wants {self.channels}")
        return _resize_bilinear(img, self.height, self.width) / 255.0


class ImageRecordReader:
    """Directory-of-images reader with parent-directory labels (the
    reference `ImageRecordReader` + `ParentPathLabelGenerator` pattern):
    root/<label>/<image files>. Deterministic label-sorted order; shuffle
    at the iterator level."""

    def __init__(self, root: str, height: int, width: int,
                 channels: int = 3,
                 allowed_extensions: Sequence[str] = _EXTS):
        self.root = root
        self.loader = ImageLoader(height, width, channels)
        self.labels: List[str] = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        if not self.labels:
            raise ValueError(f"{root}: no label subdirectories")
        exts = tuple(allowed_extensions)
        self.records: List[Tuple[str, int]] = []
        for li, label in enumerate(self.labels):
            d = os.path.join(root, label)
            for f in sorted(os.listdir(d)):
                if f.lower().endswith(exts):
                    self.records.append((os.path.join(d, f), li))
        self._pos = 0

    def num_labels(self) -> int:
        return len(self.labels)

    def reset(self):
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self.records)

    def next(self) -> Tuple[np.ndarray, int]:
        path, label = self.records[self._pos]
        self._pos += 1
        return self.loader.load(path), label


class ImageRecordDataSetIterator(DataSetIterator):
    """Minibatch iterator over an ImageRecordReader: NHWC float32 features
    + one-hot labels (the RecordReaderDataSetIterator-over-images role)."""

    def __init__(self, reader: ImageRecordReader, batch_size: int,
                 shuffle: bool = False, seed: int = 0):
        self.reader = reader
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = int(seed)
        self._order: Optional[np.ndarray] = None
        self._pos = 0
        self._epoch = 0
        self.reset()

    def reset(self):
        n = len(self.reader.records)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            self._order = rng.permutation(n)
        else:
            self._order = np.arange(n)
        self._pos = 0
        self._epoch += 1

    def batch(self) -> int:
        return self.batch_size

    def total_outcomes(self) -> int:
        return self.reader.num_labels()

    def has_next(self) -> bool:
        return self._pos < len(self._order)

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        idx = self._order[self._pos:self._pos + self.batch_size]
        self._pos += len(idx)
        xs, ys = [], []
        for i in idx:
            path, label = self.reader.records[int(i)]
            xs.append(self.reader.loader.load(path))
            ys.append(label)
        x = np.stack(xs).astype(np.float32)
        y = np.eye(self.reader.num_labels(),
                   dtype=np.float32)[np.asarray(ys)]
        return DataSet(x, y)
