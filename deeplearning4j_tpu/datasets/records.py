"""Record readers — the DataVec capability surface (SURVEY.md reference
vitals: DataVec supplies CSV/image record readers feeding
`RecordReaderDataSetIterator`, used by e.g. `CifarDataSetIterator.java:17`).

TPU-first shape: readers parse whole files into dense numpy arrays up front
(the accelerator wants large uniform batches, not per-record Java iterators);
the CSV hot path is the native C++ parser (`native/dl4j_native.cpp`) with a
numpy fallback. `BinaryRecordReader` streams fixed-size records through the
native prefetch ring (the MagicQueue analog).
"""
from __future__ import annotations

import os
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from .iterators import ArrayDataSetIterator, DataSet, DataSetIterator

__all__ = ["CSVRecordReader", "RecordReaderDataSetIterator",
           "BinaryRecordReader", "BinaryRecordDataSetIterator"]


class CSVRecordReader:
    """Numeric CSV -> float32 matrix (DataVec `CSVRecordReader` analog).
    Non-numeric fields parse as 0 (native) — pre-encode categoricals."""

    def __init__(self, skip_num_lines: int = 0):
        self.skip_num_lines = int(skip_num_lines)

    def read_matrix(self, path: str) -> np.ndarray:
        from ..native import csv_read_native, native_available
        if native_available():
            return csv_read_native(path, self.skip_num_lines)
        return np.loadtxt(path, delimiter=",", skiprows=self.skip_num_lines,
                          dtype=np.float32, ndmin=2)


class RecordReaderDataSetIterator(ArrayDataSetIterator):
    """CSV records -> (features, one-hot labels) minibatches. Parity with
    `RecordReaderDataSetIterator(reader, batch, labelIndex, numClasses)`:
    `label_index` selects the class column, `num_classes` one-hot encodes
    it; `regression=True` keeps the label column(s) as real values."""

    def __init__(self, path: str, batch_size: int, label_index: int,
                 num_classes: int = 0, regression: bool = False,
                 reader: Optional[CSVRecordReader] = None,
                 label_count: int = 1):
        reader = reader or CSVRecordReader()
        m = reader.read_matrix(path)
        li = label_index if label_index >= 0 else m.shape[1] + label_index
        label_cols = list(range(li, li + label_count))
        feat_cols = [c for c in range(m.shape[1]) if c not in label_cols]
        x = m[:, feat_cols]
        if regression:
            y = m[:, label_cols]
        else:
            if num_classes <= 0:
                raise ValueError("num_classes required for classification")
            y = np.eye(num_classes, dtype=np.float32)[
                m[:, li].astype(np.int64)]
        super().__init__(x, y, batch_size=batch_size)


class BinaryRecordReader:
    """Fixed-size binary records streamed via the native prefetch ring
    (background C++ reader thread, double-buffered — the file-backed
    MagicQueue/AsyncDataSetIterator analog)."""

    def __init__(self, path: str, record_shape: Sequence[int],
                 dtype=np.uint8, header_bytes: int = 0,
                 total_records: Optional[int] = None, slots: int = 3):
        self.path = path
        self.record_shape = tuple(int(s) for s in record_shape)
        self.dtype = np.dtype(dtype)
        self.header_bytes = int(header_bytes)
        self.record_bytes = int(np.prod(self.record_shape)
                                * self.dtype.itemsize)
        if total_records is None:
            payload = os.path.getsize(path) - self.header_bytes
            total_records = payload // self.record_bytes
        self.total_records = int(total_records)
        self.slots = int(slots)

    def batches(self, batch_records: int) -> Iterator[np.ndarray]:
        from ..native import PrefetchRing, native_available
        if native_available():
            with PrefetchRing(self.path, self.record_bytes,
                              self.total_records, batch_records,
                              header_bytes=self.header_bytes,
                              slots=self.slots) as ring:
                while True:
                    raw = ring.next_batch()
                    if raw is None:
                        return
                    yield (raw.view(self.dtype)
                           .reshape((-1,) + self.record_shape))
        else:   # pure-Python fallback: plain chunked reads
            with open(self.path, "rb") as f:
                f.seek(self.header_bytes)
                done = 0
                while done < self.total_records:
                    n = min(batch_records, self.total_records - done)
                    raw = f.read(n * self.record_bytes)
                    if len(raw) < n * self.record_bytes:
                        return
                    done += n
                    yield (np.frombuffer(raw, self.dtype)
                           .reshape((-1,) + self.record_shape))


class BinaryRecordDataSetIterator(DataSetIterator):
    """DataSetIterator over a binary record file where each record is
    `label_bytes` of label followed by a flat feature payload (the CIFAR-10
    binary layout, `CifarDataSetIterator.java:17` capability analog).
    Features normalize u8 -> [0,1] f32; labels one-hot."""

    def __init__(self, path: str, feature_shape: Sequence[int],
                 num_classes: int, batch_size: int, label_bytes: int = 1,
                 header_bytes: int = 0,
                 label_byte_index: Optional[int] = None):
        self.feature_shape = tuple(int(s) for s in feature_shape)
        self.num_classes = int(num_classes)
        self.batch_size = int(batch_size)
        self.label_bytes = int(label_bytes)
        # default: last label byte — byte 0 for CIFAR-10, the fine label for
        # CIFAR-100's coarse+fine pair
        self.label_byte_index = (self.label_bytes - 1
                                 if label_byte_index is None
                                 else int(label_byte_index))
        if not 0 <= self.label_byte_index < self.label_bytes:
            raise ValueError(
                f"label_byte_index {self.label_byte_index} outside the "
                f"{self.label_bytes} label byte(s)")
        feat_bytes = int(np.prod(self.feature_shape))
        self.reader = BinaryRecordReader(
            path, (self.label_bytes + feat_bytes,), np.uint8,
            header_bytes=header_bytes)
        self._gen = None

    def reset(self):
        self._gen = self.reader.batches(self.batch_size)
        self._peek = None

    def has_next(self) -> bool:
        if self._gen is None:
            self.reset()
        if getattr(self, "_peek", None) is None:
            self._peek = next(self._gen, None)
        return self._peek is not None

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        raw, self._peek = self._peek, None
        labels = raw[:, self.label_byte_index].astype(np.int64)
        feats = raw[:, self.label_bytes:].astype(np.float32) / 255.0
        x = feats.reshape((-1,) + self.feature_shape)
        y = np.eye(self.num_classes, dtype=np.float32)[labels]
        return DataSet(x, y)

    def batch(self) -> int:
        return self.batch_size
