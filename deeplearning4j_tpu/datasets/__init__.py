from .iterators import (
    DataSet, MultiDataSet, DataSetIterator, ListDataSetIterator,
    ArrayDataSetIterator, AsyncDataSetIterator, MultipleEpochsIterator,
    SamplingDataSetIterator, IteratorDataSetIterator, ExistingDataSetIterator,
)
from .pipeline import (
    PadToBatchIterator, DevicePrefetchIterator, pad_dataset, build_pipeline,
)
from .export import (
    export_datasets, export_sharded, load_dataset, PathDataSetIterator,
    ShardedPathDataSetIterator, LocalShardDataSet,
)
from .labeled_point import (
    LabeledPoint, LabeledPointDataSetIterator, labeled_points_to_dataset,
)

__all__ = [
    "DataSet", "MultiDataSet", "DataSetIterator", "ListDataSetIterator",
    "ArrayDataSetIterator", "AsyncDataSetIterator", "MultipleEpochsIterator",
    "SamplingDataSetIterator", "IteratorDataSetIterator",
    "ExistingDataSetIterator",
    "PadToBatchIterator", "DevicePrefetchIterator", "pad_dataset",
    "build_pipeline",
    "export_datasets", "export_sharded", "load_dataset",
    "PathDataSetIterator", "ShardedPathDataSetIterator", "LocalShardDataSet",
    "LabeledPoint", "LabeledPointDataSetIterator",
    "labeled_points_to_dataset",
]
