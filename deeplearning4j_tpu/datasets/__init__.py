from .iterators import (
    DataSet, MultiDataSet, DataSetIterator, ListDataSetIterator,
    ArrayDataSetIterator, AsyncDataSetIterator, MultipleEpochsIterator,
    SamplingDataSetIterator, IteratorDataSetIterator, ExistingDataSetIterator,
)

__all__ = [
    "DataSet", "MultiDataSet", "DataSetIterator", "ListDataSetIterator",
    "ArrayDataSetIterator", "AsyncDataSetIterator", "MultipleEpochsIterator",
    "SamplingDataSetIterator", "IteratorDataSetIterator",
    "ExistingDataSetIterator",
]
