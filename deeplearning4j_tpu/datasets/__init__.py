from .iterators import (
    DataSet, MultiDataSet, DataSetIterator, ListDataSetIterator,
    ArrayDataSetIterator, AsyncDataSetIterator, MultipleEpochsIterator,
    SamplingDataSetIterator, IteratorDataSetIterator, ExistingDataSetIterator,
)
from .export import (
    export_datasets, export_sharded, load_dataset, PathDataSetIterator,
    ShardedPathDataSetIterator, LocalShardDataSet,
)

__all__ = [
    "DataSet", "MultiDataSet", "DataSetIterator", "ListDataSetIterator",
    "ArrayDataSetIterator", "AsyncDataSetIterator", "MultipleEpochsIterator",
    "SamplingDataSetIterator", "IteratorDataSetIterator",
    "ExistingDataSetIterator",
    "export_datasets", "export_sharded", "load_dataset",
    "PathDataSetIterator", "ShardedPathDataSetIterator", "LocalShardDataSet",
]
