"""Concrete dataset iterators over the fetchers.

Reference parity: `datasets/iterator/impl/MnistDataSetIterator.java:30`,
`IrisDataSetIterator.java`, `CifarDataSetIterator.java:17` — thin iterators
binding a fetcher to the DataSetIterator contract, composable with
`AsyncDataSetIterator` for host-side prefetch.
"""
from __future__ import annotations

from typing import Optional

from .fetchers import CifarDataFetcher, IrisDataFetcher, MnistDataFetcher
from .iterators import ArrayDataSetIterator

__all__ = ["MnistDataSetIterator", "IrisDataSetIterator",
           "CifarDataSetIterator"]


class MnistDataSetIterator(ArrayDataSetIterator):
    def __init__(self, batch_size: int = 128,
                 num_examples: Optional[int] = None, train: bool = True,
                 binarize: bool = False, shuffle: bool = False,
                 seed: Optional[int] = None, cache: Optional[str] = None):
        x, y = MnistDataFetcher(binarize=binarize, train=train,
                                shuffle=shuffle, seed=seed,
                                cache=cache).fetch()
        if num_examples is not None:
            x, y = x[:num_examples], y[:num_examples]
        super().__init__(x, y, batch_size=batch_size)


class IrisDataSetIterator(ArrayDataSetIterator):
    def __init__(self, batch_size: int = 150,
                 num_examples: Optional[int] = None, shuffle: bool = True,
                 seed: Optional[int] = 6, normalize: bool = True):
        x, y = IrisDataFetcher(shuffle=shuffle, seed=seed,
                               normalize=normalize).fetch()
        if num_examples is not None:
            x, y = x[:num_examples], y[:num_examples]
        super().__init__(x, y, batch_size=batch_size)


class CifarDataSetIterator(ArrayDataSetIterator):
    def __init__(self, batch_size: int = 128,
                 num_examples: Optional[int] = None, train: bool = True,
                 cache: Optional[str] = None):
        x, y = CifarDataFetcher(train=train, cache=cache).fetch()
        if num_examples is not None:
            x, y = x[:num_examples], y[:num_examples]
        super().__init__(x, y, batch_size=batch_size)
