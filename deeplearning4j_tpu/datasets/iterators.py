"""DataSet + iterator API.

Parity with ND4J's `DataSet`/`DataSetIterator` contract as used throughout the
reference (`datasets/iterator/BaseDatasetIterator.java`,
`AsyncDataSetIterator.java:33`, `MultipleEpochsIterator`,
`SamplingDataSetIterator`, `IteratorDataSetIterator`).

TPU-native notes: batches are host numpy until the jitted train step consumes
them (device transfer happens once per step, overlapped by
`AsyncDataSetIterator`'s background prefetch thread — same double-buffering
the reference does on the JVM side).
"""
from __future__ import annotations

import queue
import threading
import warnings
from dataclasses import dataclass, field
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "DataSet", "MultiDataSet", "DataSetIterator", "ListDataSetIterator",
    "ArrayDataSetIterator", "AsyncDataSetIterator", "AsyncMultiDataSetIterator", "MultipleEpochsIterator",
    "SamplingDataSetIterator", "IteratorDataSetIterator",
    "ExistingDataSetIterator",
]


@dataclass
class DataSet:
    """features/labels (+ optional masks) minibatch. Parity with ND4J DataSet
    (features, labels, featuresMaskArray, labelsMaskArray)."""

    features: np.ndarray
    labels: Optional[np.ndarray] = None
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None
    # device-array cache: (id-key, (features, labels, fmask, lmask) on device)
    _dev_cache: Optional[tuple] = field(default=None, repr=False, compare=False)

    def device_tuple(self):
        """(features, labels, features_mask, labels_mask) as device arrays,
        cached so refitting the same DataSet pays host->device transfer once
        (the transfer, not compute, dominates through a thin host link).

        The cache holds references to the host arrays and is invalidated when
        any field is REASSIGNED (`is` comparison — shuffle() etc. do this).
        In-place mutation of a field (`ds.features[:] = ...`) is not detected;
        DataSet fields are treated as immutable buffers."""
        import jax.numpy as jnp
        arrays = (self.features, self.labels, self.features_mask,
                  self.labels_mask)
        if (self._dev_cache is None
                or any(a is not b
                       for a, b in zip(self._dev_cache[0], arrays))):
            dev = tuple(None if a is None else jnp.asarray(a) for a in arrays)
            self._dev_cache = (arrays, dev)
        return self._dev_cache[1]

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_test_and_train(self, n_train: int) -> Tuple["DataSet", "DataSet"]:
        def cut(a, lo, hi):
            return None if a is None else a[lo:hi]
        n = self.num_examples()
        return (DataSet(*(cut(a, 0, n_train) for a in
                          (self.features, self.labels, self.features_mask, self.labels_mask))),
                DataSet(*(cut(a, n_train, n) for a in
                          (self.features, self.labels, self.features_mask, self.labels_mask))))

    def shuffle(self, seed: Optional[int] = None):
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        self.features = self.features[idx]
        if self.labels is not None:
            self.labels = self.labels[idx]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[idx]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[idx]

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        def cat(xs):
            xs = [x for x in xs if x is not None]
            return np.concatenate(xs, axis=0) if xs else None

        def cat_masks(masks, anchors):
            """Concat masks; datasets lacking one get all-ones so rows stay
            aligned with their examples."""
            if all(m is None for m in masks):
                return None
            proto = next(m for m in masks if m is not None)
            out = []
            for m, anchor in zip(masks, anchors):
                if m is None:
                    m = np.ones((anchor.shape[0],) + proto.shape[1:],
                                dtype=proto.dtype)
                out.append(m)
            return np.concatenate(out, axis=0)

        feats = [d.features for d in datasets]
        labs = [d.labels for d in datasets]
        return DataSet(cat(feats), cat(labs),
                       cat_masks([d.features_mask for d in datasets], feats),
                       cat_masks([d.labels_mask for d in datasets],
                                 [l if l is not None else f
                                  for l, f in zip(labs, feats)]))


@dataclass
class MultiDataSet:
    """Multiple-input/multiple-output minibatch (ND4J MultiDataSet), consumed
    by the ComputationGraph."""

    features: List[np.ndarray] = field(default_factory=list)
    labels: List[np.ndarray] = field(default_factory=list)
    features_masks: Optional[List[Optional[np.ndarray]]] = None
    labels_masks: Optional[List[Optional[np.ndarray]]] = None
    _dev_cache: Optional[tuple] = field(default=None, repr=False, compare=False)

    def device_tuple(self):
        """(features, labels, features_masks, labels_masks) with every array
        on device, cached (see DataSet.device_tuple for invalidation rules)."""
        import jax.numpy as jnp

        def conv(seq):
            if seq is None:
                return None
            return tuple(None if a is None else jnp.asarray(a) for a in seq)

        def flat(seq):
            return tuple(seq) if seq is not None else (None,)

        key = flat(self.features) + flat(self.labels) \
            + flat(self.features_masks) + flat(self.labels_masks)
        if (self._dev_cache is None
                or len(self._dev_cache[0]) != len(key)
                or any(a is not b
                       for a, b in zip(self._dev_cache[0], key))):
            self._dev_cache = (key, (conv(self.features), conv(self.labels),
                                     conv(self.features_masks),
                                     conv(self.labels_masks)))
        return self._dev_cache[1]

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])


class DataSetIterator:
    """Iterator contract: `__iter__` restarts an epoch (calls `reset`)."""

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.next()

    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self) -> DataSet:
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def batch(self) -> int:
        raise NotImplementedError

    @property
    def async_supported(self) -> bool:
        return True


class ArrayDataSetIterator(DataSetIterator):
    """Batches over in-memory arrays (role of ND4J's ListDataSetIterator over a
    pre-split list, but vectorized)."""

    def __init__(self, features, labels=None, batch_size: int = 32,
                 features_mask=None, labels_mask=None, shuffle: bool = False,
                 seed: Optional[int] = None, drop_last: bool = False):
        self.features = np.asarray(features)
        self.labels = None if labels is None else np.asarray(labels)
        self.features_mask = None if features_mask is None else np.asarray(features_mask)
        self.labels_mask = None if labels_mask is None else np.asarray(labels_mask)
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        if drop_last and self.features.shape[0] < self.batch_size:
            # has_next() would be False forever: every epoch yields ZERO
            # batches and fit() silently trains on nothing
            warnings.warn(
                f"ArrayDataSetIterator(drop_last=True) with only "
                f"{self.features.shape[0]} examples < batch_size="
                f"{self.batch_size}: every epoch yields zero batches, so "
                "fit() will train on NOTHING. Lower batch_size, set "
                "drop_last=False, or pad with "
                "datasets.pipeline.PadToBatchIterator",
                UserWarning, stacklevel=2)
        self._epoch = 0
        self._drawn = False   # batches consumed since the last reset?
        self.reset()

    def reset(self):
        # Epoch E shuffles with `seed + E`, E counting CONSUMED epochs:
        # reset() only advances the epoch after a batch was drawn, so the
        # constructor's reset and fit()'s epoch-start reset both leave the
        # first epoch on `seed + 0` (reproducible from `seed=` alone).
        if self._drawn:
            self._epoch += 1
        n = self.features.shape[0]
        if self.shuffle:
            rng = np.random.default_rng(
                None if self.seed is None else self.seed + self._epoch)
            self._order = rng.permutation(n)
        else:
            self._order = np.arange(n)
        self._pos = 0
        self._drawn = False

    def set_epoch(self, epoch: int):
        """Position the shuffle-epoch counter (checkpoint resume): the
        iterator reshuffles as if `epoch` epochs had already been
        consumed, so a resumed fit replays the exact permutation the
        interrupted run would have used (seed + epoch)."""
        self._epoch = int(epoch)
        self._drawn = False
        self.reset()

    def has_next(self) -> bool:
        remaining = len(self._order) - self._pos
        if self.drop_last:
            return remaining >= self.batch_size
        return remaining > 0

    def next(self) -> DataSet:
        idx = self._order[self._pos:self._pos + self.batch_size]
        self._pos += len(idx)
        self._drawn = True

        def take(a):
            return None if a is None else a[idx]
        return DataSet(take(self.features), take(self.labels),
                       take(self.features_mask), take(self.labels_mask))

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return int(self.features.shape[0])


class ListDataSetIterator(DataSetIterator):
    """Iterates a list of pre-built DataSets, re-batched to `batch` examples
    (parity with `datasets/iterator/ListDataSetIterator`)."""

    def __init__(self, datasets: Sequence[DataSet], batch_size: Optional[int] = None):
        self._datasets = list(datasets)
        self._batch = batch_size
        if batch_size is not None:
            merged = DataSet.merge(self._datasets)
            self._datasets = []
            for i in range(0, merged.num_examples(), batch_size):
                self._datasets.append(DataSet(
                    merged.features[i:i + batch_size],
                    None if merged.labels is None else merged.labels[i:i + batch_size],
                    None if merged.features_mask is None else merged.features_mask[i:i + batch_size],
                    None if merged.labels_mask is None else merged.labels_mask[i:i + batch_size]))
        self._pos = 0

    def reset(self):
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._datasets)

    def next(self):
        d = self._datasets[self._pos]
        self._pos += 1
        return d

    def batch(self):
        return self._batch or (self._datasets[0].num_examples() if self._datasets else 0)


class ExistingDataSetIterator(DataSetIterator):
    """Wraps a plain python iterable of DataSets
    (`datasets/iterator/ExistingDataSetIterator.java`)."""

    def __init__(self, iterable: Iterable[DataSet]):
        self._iterable = iterable
        self.reset()

    def reset(self):
        self._it = iter(self._iterable)
        self._peek = None
        self._advance()

    def _advance(self):
        try:
            self._peek = next(self._it)
        except StopIteration:
            self._peek = None

    def has_next(self):
        return self._peek is not None

    def next(self):
        d = self._peek
        self._advance()
        return d

    def batch(self):
        return -1


class IteratorDataSetIterator(DataSetIterator):
    """Re-batches an iterator of DataSets to a fixed minibatch size
    (`datasets/iterator/IteratorDataSetIterator.java`)."""

    def __init__(self, source: DataSetIterator, batch_size: int):
        self.source = source
        self.batch_size = int(batch_size)
        self._buffer: List[DataSet] = []

    def reset(self):
        self.source.reset()
        self._buffer = []

    def has_next(self):
        return bool(self._buffer) or self.source.has_next()

    def next(self):
        have = sum(d.num_examples() for d in self._buffer)
        while have < self.batch_size and self.source.has_next():
            d = self.source.next()
            self._buffer.append(d)
            have += d.num_examples()
        merged = DataSet.merge(self._buffer)

        def cut(a, lo, hi):
            return None if a is None else a[lo:hi]

        b = self.batch_size
        out = DataSet(cut(merged.features, 0, b), cut(merged.labels, 0, b),
                      cut(merged.features_mask, 0, b),
                      cut(merged.labels_mask, 0, b))
        n = merged.num_examples()
        self._buffer = []
        if n > b:
            self._buffer = [DataSet(cut(merged.features, b, n),
                                    cut(merged.labels, b, n),
                                    cut(merged.features_mask, b, n),
                                    cut(merged.labels_mask, b, n))]
        return out

    def batch(self):
        return self.batch_size


class MultipleEpochsIterator(DataSetIterator):
    """Replays an iterator for N epochs (`datasets/iterator/MultipleEpochsIterator.java`)."""

    def __init__(self, epochs: int, source: DataSetIterator):
        self.epochs = int(epochs)
        self.source = source
        self._epoch = 0

    def reset(self):
        self.source.reset()
        self._epoch = 0

    def has_next(self):
        if self.source.has_next():
            return True
        if self._epoch + 1 < self.epochs:
            self._epoch += 1
            self.source.reset()
            return self.source.has_next()
        return False

    def next(self):
        if not self.has_next():
            raise StopIteration
        return self.source.next()

    def batch(self):
        return self.source.batch()


class SamplingDataSetIterator(DataSetIterator):
    """Samples minibatches with replacement from one DataSet
    (`datasets/iterator/SamplingDataSetIterator.java`)."""

    def __init__(self, dataset: DataSet, batch_size: int, total_batches: int,
                 seed: Optional[int] = None):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.total_batches = int(total_batches)
        self.seed = seed
        self.reset()

    def reset(self):
        self._rng = np.random.default_rng(self.seed)
        self._count = 0

    def has_next(self):
        return self._count < self.total_batches

    def next(self):
        idx = self._rng.integers(0, self.dataset.num_examples(), self.batch_size)
        self._count += 1
        return DataSet(self.dataset.features[idx],
                       None if self.dataset.labels is None else self.dataset.labels[idx])

    def batch(self):
        return self.batch_size


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch (double buffering) — parity with
    `datasets/iterator/AsyncDataSetIterator.java:33`, including worker-exception
    propagation to the caller.

    Consumer protocol: queue entries are `(batch, more)` pairs, `more`
    evaluated by the WORKER after drawing the batch — so `next()` hands a
    ready batch over immediately and the consumer only ever blocks when
    the next batch genuinely isn't staged yet (waiting for batch k+1
    before releasing batch k would serialize exactly the work the thread
    exists to overlap), and the last batch's tag ends the epoch without a
    final sentinel round-trip. The worker starts lazily on first
    consumption, so wrapping an iterator (or an epoch-start `reset()`)
    never stages batches that are immediately thrown away."""

    _SENTINEL = object()

    def __init__(self, source: DataSetIterator, queue_size: int = 2):
        self.source = source
        self.queue_size = max(1, int(queue_size))
        self._queue: queue.Queue = queue.Queue(self.queue_size)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._peek = None
        self._more = True      # may the worker still yield items?
        self._started = False

    def _prepare(self, ds):
        """Worker-thread hook run on each batch before it is queued —
        subclasses stage extra work here (DevicePrefetchIterator dispatches
        the host->device transfer)."""
        return ds

    def _start(self):
        self._queue = queue.Queue(self.queue_size)
        self._error = None
        self._stop = threading.Event()
        # Bind this generation's queue/stop locally: a stale worker that
        # outlives reset()'s join timeout must keep writing to ITS queue, not
        # the new generation's (else previous-epoch batches leak in).
        q, stop = self._queue, self._stop

        def put(item):
            # stop-aware put: an abandoned consumer must not leave this
            # thread blocked on a full queue forever
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                more = self.source.has_next()
                while more and not stop.is_set():
                    ds = self.source.next()
                    more = self.source.has_next()
                    if not put((self._prepare(ds), more)):
                        return
            except BaseException as e:  # propagate to consumer
                self._error = e
            # ALWAYS end with a sentinel: an empty source yields no tagged
            # item at all, so without it the consumer's first _fetch would
            # block forever. After a fully-tagged epoch the consumer never
            # reads it (the last tag ended the epoch) — the queue has space
            # by then and reset()/close() drain it.
            put(self._SENTINEL)

        self._thread = threading.Thread(target=worker, daemon=True,
                                        name="dl4j-async-prefetch")
        self._thread.start()
        self._started = True
        self._peek = None
        self._more = True

    def _ensure_started(self):
        if not self._started:
            self._start()

    def _fetch(self):
        """Block for the next queue entry; resolves end-of-epoch and
        worker errors."""
        item = self._queue.get()
        if item is self._SENTINEL:
            self._more = False   # before raising: a caller that catches the
            self._peek = None    # error and re-polls must not block forever
            if self._error is not None:
                raise RuntimeError(
                    "Async prefetch thread failed") from self._error
        else:
            self._peek, more = item
            if not more:
                self._more = False

    def _shutdown(self):
        """Stop + join the current worker generation (idempotent)."""
        if self._thread is None:
            return
        self._stop.set()
        # drain so a blocked worker unblocks promptly
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)
        self._thread = None

    def close(self):
        """Shut the prefetch thread down. The iterator stays resettable:
        `reset()` (or `__iter__`) restarts a fresh worker."""
        self._shutdown()
        self._peek = None
        self._more = False
        self._started = True   # don't lazily restart; reset() re-arms

    def reset(self):
        self._shutdown()
        self.source.reset()
        self._peek = None
        self._more = True
        self._started = False   # worker restarts on first consumption

    def has_next(self):
        self._ensure_started()
        if self._peek is not None:
            return True
        if not self._more:
            return False
        self._fetch()
        return self._peek is not None

    def next(self):
        if not self.has_next():
            raise StopIteration
        d = self._peek
        self._peek = None
        return d

    def batch(self):
        return self.source.batch()


class AsyncMultiDataSetIterator(AsyncDataSetIterator):
    """Background-thread prefetch over a MULTI-dataset iterator (parity
    with `datasets/iterator/AsyncMultiDataSetIterator.java`) — the prefetch
    machinery is payload-agnostic, so this is the naming/type marker for
    MultiDataSet sources feeding a ComputationGraph."""
