"""LabeledPoint vector-format ingestion bridge.

Parity with the reference's MLlib interop overloads
(`SparkDl4jMultiLayer.java:274-288` — `fit(JavaRDD<LabeledPoint>)` /
`fitLabeledPoint`, conversion in `MLLibUtil`): a `LabeledPoint` is a
(label, feature-vector) pair, dense or sparse; fitting converts them to
DataSets (one-hot labels for classification) and trains normally.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple, Union

import numpy as np

from .iterators import DataSet, DataSetIterator

__all__ = ["LabeledPoint", "labeled_points_to_dataset",
           "LabeledPointDataSetIterator"]


@dataclass
class LabeledPoint:
    """(label, features) — features dense (array) or sparse
    ((indices, values, size) triple, MLlib SparseVector layout)."""

    label: float
    features: Union[np.ndarray, Tuple[Sequence[int], Sequence[float], int]]

    def dense(self) -> np.ndarray:
        f = self.features
        if isinstance(f, tuple) and len(f) == 3:
            idx, vals, size = f
            idx = np.asarray(idx, np.int64)
            if len(idx) and (idx.min() < 0 or idx.max() >= int(size)):
                # MLlib SparseVector contract: indices in [0, size) —
                # numpy wrap-around would silently shuffle features
                raise ValueError(
                    f"sparse indices outside [0, {int(size)}): "
                    f"{idx[(idx < 0) | (idx >= int(size))][:5].tolist()}")
            out = np.zeros(int(size), np.float32)
            out[idx] = np.asarray(vals, np.float32)
            return out
        return np.asarray(f, np.float32)


def labeled_points_to_dataset(points: Sequence[LabeledPoint],
                              n_classes: Optional[int] = None) -> DataSet:
    """Convert LabeledPoints to one DataSet. `n_classes` set: labels are
    class indices -> one-hot (the `fit(RDD<LabeledPoint>, nClasses)`
    overload); None: regression targets, shape [N, 1]."""
    if not points:
        raise ValueError("no points")
    x = np.stack([p.dense() for p in points])
    labels = np.asarray([p.label for p in points])
    if n_classes is not None:
        idx = labels.astype(np.int64)
        if (idx < 0).any() or (idx >= n_classes).any():
            raise ValueError(
                f"labels outside [0, {n_classes}): {sorted(set(idx) - set(range(n_classes)))[:5]}")
        y = np.eye(int(n_classes), dtype=np.float32)[idx]
    else:
        y = labels.astype(np.float32)[:, None]
    return DataSet(x, y)


class LabeledPointDataSetIterator(DataSetIterator):
    """Batched iterator over LabeledPoints — drop-in for fit()/evaluate()
    (the role `MLLibUtil.fromLabeledPoint` + RecordReaderDataSetIterator
    played for the reference's Spark front-end)."""

    def __init__(self, points: Sequence[LabeledPoint], batch_size: int = 32,
                 n_classes: Optional[int] = None):
        self.points = list(points)
        self.batch_size = int(batch_size)
        self.n_classes = n_classes
        self.reset()

    def reset(self):
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self.points)

    def next(self) -> DataSet:
        chunk = self.points[self._pos:self._pos + self.batch_size]
        self._pos += len(chunk)
        return labeled_points_to_dataset(chunk, self.n_classes)

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return len(self.points)
