"""Export-based dataset plane — minibatches saved to files, training fed
from paths.

The reference's DEFAULT cluster training path (`RDDTrainingApproach.Export`,
selected at `ParameterAveragingTrainingMaster.java:101,366`) saves the RDD
as minibatch files (`BatchAndExportDataSetsFunction.java` — re-batches to
the exact minibatch size, writes `dataset_<idx>.bin`) and trains from a
path-based iterator (`PathSparkDataSetIterator.java`,
`util/ExportSupport.java`) so (a) the dataset never has to fit in
driver/worker RAM and (b) failed/interrupted work is recomputable from the
saved files.

TPU-native form: `.npz` minibatch files + `PathDataSetIterator` (composes
with `AsyncDataSetIterator` for prefetch). The multi-host plane writes
PER-PROCESS SHARD files per global batch (`export_sharded`) so each host
reads only its own slice and the SPMD global batch is assembled with
`jax.make_array_from_process_local_data` — in-memory and path-based
training are bit-identical (the equivalence the tests assert).
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Union

import numpy as np

from .iterators import (AsyncDataSetIterator, DataSet, DataSetIterator,
                        IteratorDataSetIterator)

__all__ = ["export_datasets", "export_sharded", "load_dataset",
           "PathDataSetIterator", "ShardedPathDataSetIterator",
           "LocalShardDataSet"]

_FIELDS = ("features", "labels", "features_mask", "labels_mask")


def _save(path: str, ds: DataSet):
    arrays = {k: getattr(ds, k) for k in _FIELDS
              if getattr(ds, k) is not None}
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)   # atomic: a crash never leaves a torn file


def load_dataset(path: Union[str, os.PathLike]) -> DataSet:
    """Load one exported minibatch file."""
    with np.load(path) as z:
        kw = {k: z[k] for k in _FIELDS if k in z.files}
    return DataSet(**kw)


def _as_batches(data, batch_size: Optional[int]):
    """Shared exporter preamble: optionally re-batch to the exact size
    (the reference's `BatchAndExportDataSetsFunction` behavior), reset,
    and return an iterator of DataSets."""
    if batch_size is not None:
        if not isinstance(data, DataSetIterator):
            from .iterators import ListDataSetIterator
            data = ListDataSetIterator(list(data))
        data = IteratorDataSetIterator(data, batch_size=batch_size)
    if isinstance(data, DataSetIterator):
        data.reset()
    return iter(data)


def export_datasets(data, directory: Union[str, os.PathLike],
                    prefix: str = "dataset",
                    batch_size: Optional[int] = None) -> List[str]:
    """Write every minibatch of `data` (a DataSetIterator or iterable of
    DataSet) as `<prefix>_<idx>.npz` under `directory`; returns the paths
    in order. `batch_size` re-batches to the exact size first."""
    directory = str(directory)
    os.makedirs(directory, exist_ok=True)
    paths = []
    for i, ds in enumerate(_as_batches(data, batch_size)):
        p = os.path.join(directory, f"{prefix}_{i:05d}.npz")
        _save(p, ds)
        paths.append(p)
    return paths


def export_sharded(data, directory: Union[str, os.PathLike],
                   n_shards: int, prefix: str = "dataset",
                   batch_size: Optional[int] = None) -> List[List[str]]:
    """Multi-host exporter: each minibatch is split into `n_shards` equal
    row slices saved as `<prefix>_<idx>.shard<k>.npz`; process k later
    reads ONLY its shard files (`ShardedPathDataSetIterator`). Returns
    paths[k] = ordered shard-k paths. Batches must divide by n_shards
    (uniform SPMD shards — ragged tails are an error, as in
    `local_batch_slice`)."""
    directory = str(directory)
    os.makedirs(directory, exist_ok=True)
    paths: List[List[str]] = [[] for _ in range(n_shards)]
    for i, ds in enumerate(_as_batches(data, batch_size)):
        n = ds.num_examples()
        if n % n_shards:
            raise ValueError(
                f"batch {i} has {n} examples, not divisible into "
                f"{n_shards} uniform shards; re-batch upstream")
        per = n // n_shards
        for k in range(n_shards):
            sl = slice(k * per, (k + 1) * per)
            cut = lambda a: None if a is None else a[sl]
            shard = DataSet(cut(ds.features), cut(ds.labels),
                            cut(ds.features_mask), cut(ds.labels_mask))
            p = os.path.join(directory, f"{prefix}_{i:05d}.shard{k}.npz")
            _save(p, shard)
            paths[k].append(p)
    return paths


class PathDataSetIterator(DataSetIterator):
    """Iterate minibatches from saved files (`PathSparkDataSetIterator`
    analog): only one minibatch is resident at a time, so the dataset
    never has to fit in RAM. Wrap in `AsyncDataSetIterator` to overlap
    disk reads with device steps. `start_from` skips already-consumed
    files — resuming an interrupted run from the export directory."""

    def __init__(self, paths: Sequence[Union[str, os.PathLike]],
                 shuffle: bool = False, seed: Optional[int] = None,
                 start_from: int = 0):
        self.paths = [str(p) for p in paths]
        self.shuffle = shuffle
        self.seed = seed
        self.start_from = int(start_from)
        if self.start_from and shuffle and seed is None:
            # resume skips `start_from` positions of a permutation the
            # interrupted run can't have recorded — the resumed run would
            # process a different file subset than the one actually left
            raise ValueError(
                "start_from with shuffle=True needs a seed: an unseeded "
                "permutation cannot reproduce the interrupted run's order")
        self._epoch = 0
        self._started = False   # no batch consumed yet
        self.reset()

    @classmethod
    def from_directory(cls, directory: Union[str, os.PathLike],
                       prefix: str = "dataset", **kw):
        directory = str(directory)
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith(prefix) and n.endswith(".npz"))
        return cls([os.path.join(directory, n) for n in names], **kw)

    def reset(self):
        # the epoch counter advances only once consumption has started:
        # however many resets precede the first batch (__init__ does one,
        # __iter__ may do another), the first traversal's permutation is a
        # function of `seed` ALONE — so a resumed run (start_from > 0)
        # skips exactly the files the interrupted run consumed
        if self._started:
            self._epoch += 1
        order = np.arange(len(self.paths))
        if self.shuffle:
            rng = np.random.default_rng(
                None if self.seed is None else self.seed + self._epoch)
            order = rng.permutation(len(self.paths))
        # only the FIRST traversal resumes mid-way; once a batch has been
        # consumed, reset() means a fresh full epoch
        offset = 0 if self._started else self.start_from
        self._order = order[offset:]
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._order)

    def next(self) -> DataSet:
        self._started = True
        ds = self._load(self.paths[self._order[self._pos]])
        self._pos += 1
        return ds

    def _load(self, path: str) -> DataSet:
        return load_dataset(path)

    def batch(self) -> int:
        if not self.paths:
            return 0
        return load_dataset(self.paths[0]).num_examples()

    def async_prefetch(self, queue_size: int = 2) -> AsyncDataSetIterator:
        return AsyncDataSetIterator(self, queue_size=queue_size)


class LocalShardDataSet(DataSet):
    """A DataSet whose rows are THIS PROCESS's shard of a global batch.
    The SYNC multi-process trainer assembles the sharded global array from
    it directly instead of slicing a replicated global batch."""

    is_local_shard = True


class ShardedPathDataSetIterator(PathDataSetIterator):
    """Multi-host path iterator: given the shard-k paths written by
    `export_sharded` (or any per-process path list), yields
    `LocalShardDataSet`s. Each host touches only its own files — the
    dataset plane never materializes the global batch on any single
    host."""

    def __init__(self, paths, shard_index: Optional[int] = None, **kw):
        if shard_index is not None:
            # select this process's shard files from a full listing
            paths = [p for p in paths if f".shard{shard_index}." in str(p)]
        super().__init__(paths, **kw)

    def _load(self, path: str) -> DataSet:
        ds = load_dataset(path)
        return LocalShardDataSet(ds.features, ds.labels,
                                 ds.features_mask, ds.labels_mask)
