"""Benchmark dataset fetchers: MNIST (IDX binary), Iris (embedded), CIFAR-10
(binary batches), LFW (person-labeled face JPEGs), Curves (synthetic
autoencoder benchmark).

Reference parity:
  * MNIST — `deeplearning4j-core/.../datasets/fetchers/MnistDataFetcher.java:40`
    + the IDX readers under `datasets/mnist/` and the download helper
    `base/MnistFetcher.java` (download + local cache + binary parse).
  * Iris — `datasets/fetchers/IrisDataFetcher.java` (the reference ships the
    150 rows as a resource; here they're embedded).
  * CIFAR-10 — `datasets/iterator/impl/CifarDataSetIterator.java:17` (binary
    "data_batch_N.bin" records: 1 label byte + 3072 channel-major bytes).
  * LFW — `datasets/fetchers/LFWDataFetcher.java` / `LFWDataSetIterator.java`
    (download + person-directory traversal + resize).
  * Curves — `datasets/fetchers/CurvesDataFetcher.java` (the Hinton
    deep-autoencoder curves set; generated deterministically here).

Cache layout: $DL4J_TPU_DATA_DIR (default ~/.deeplearning4j_tpu) /<dataset>/.
Downloads only happen when the cache misses; offline environments can drop
pre-fetched files in the cache dir (tests synthesize IDX/CIFAR files this way).
"""
from __future__ import annotations

import gzip
import os
import struct
import tarfile
import urllib.request
from typing import List, Optional, Tuple

import numpy as np

__all__ = [
    "data_dir", "read_idx", "MnistDataFetcher", "IrisDataFetcher",
    "CifarDataFetcher", "LFWDataFetcher", "CurvesDataFetcher",
    "IRIS_FEATURES", "IRIS_LABELS", "bundled_mnist_subset",
    "bundled_mnist_stratified", "augment_digits",
]


def bundled_mnist_subset(train_count: int = 320, seed: int = 0):
    """384 REAL MNIST digits bundled in-repo so the real-pixel convergence
    gate runs in offline environments (the reference's MnistDataFetcher.java:40
    downloads the full 70k set when online; its keras-interop test resources
    vendor these 3x128 real digits as h5 batches — re-encoded here as a 62KB
    npz of uint8 images + labels).

    Returns (x_train [N,784] f32 in [0,1], y_train one-hot, x_test, y_test)
    with a deterministic shuffled split."""
    imgs, labels = _bundled_mnist_raw()
    x = imgs.astype(np.float32) / 255.0
    y = labels
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(x))
    x, y = x[order].reshape(len(x), -1), y[order]
    oh = np.eye(10, dtype=np.float32)[y]
    return (x[:train_count], oh[:train_count],
            x[train_count:], oh[train_count:])

def _bundled_mnist_raw():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "resources", "mnist_subset.npz")
    with np.load(path) as z:
        return z["images"].astype(np.uint8), z["labels"].astype(np.int64)


def bundled_mnist_stratified(test_per_class: int = 4, seed: int = 0):
    """Stratified split of the bundled 384 real digits: `test_per_class`
    held-out digits per class (balanced eval set), the rest train.
    Returns (train_images [N,28,28] u8, train_labels, test_images,
    test_labels) — raw pixels, for use with `augment_digits`."""
    imgs, labels = _bundled_mnist_raw()
    rng = np.random.default_rng(seed)
    te = []
    for c in range(10):
        idx = np.where(labels == c)[0]
        te.extend(rng.permutation(idx)[:test_per_class])
    te = np.array(sorted(te))
    tr = np.setdiff1d(np.arange(len(imgs)), te)
    return imgs[tr], labels[tr], imgs[te], labels[te]


def augment_digits(images, labels, n_aug: int = 7, seed: int = 0):
    """Label-preserving MNIST augmentation: small rotation, affine
    shear/zoom/shift, and elastic deformation (Simard 2003 — the classic
    MNIST recipe). Stretches the offline real-digit budget (384 bundled
    digits, zero-egress environment) into a training set large enough for
    the >=97% convergence gate; evaluation stays on untouched real
    pixels. Returns ([N*(1+n_aug), 784] f32 in [0,1], one-hot labels)."""
    from scipy import ndimage

    rng = np.random.default_rng(seed)

    def elastic(img, alpha=6.0, sigma=3.5):
        dx = ndimage.gaussian_filter(rng.uniform(-1, 1, (28, 28)), sigma) * alpha
        dy = ndimage.gaussian_filter(rng.uniform(-1, 1, (28, 28)), sigma) * alpha
        yy, xx = np.meshgrid(np.arange(28), np.arange(28), indexing="ij")
        return ndimage.map_coordinates(img, [yy + dy, xx + dx],
                                       order=1).reshape(28, 28)

    def one(img):
        out = img.astype(np.float32)
        out = ndimage.rotate(out, rng.uniform(-12, 12), reshape=False,
                             order=1)
        sh = rng.uniform(-0.08, 0.08, 2)
        zm = rng.uniform(0.9, 1.1)
        mat = np.array([[zm, sh[0]], [sh[1], zm]])
        c = 13.5
        off = c - mat @ np.array([c, c]) + rng.uniform(-2, 2, 2)
        out = ndimage.affine_transform(out, mat, offset=off, order=1)
        if rng.random() < 0.7:
            out = elastic(out)
        return np.clip(out, 0, 255)

    xs, ys = [], []
    for img, lab in zip(images, labels):
        xs.append(img.astype(np.float32))
        ys.append(lab)
        for _ in range(n_aug):
            xs.append(one(img))
            ys.append(lab)
    x = (np.stack(xs) / 255.0).reshape(len(xs), -1).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[np.array(ys)]
    return x, y


_MNIST_URLS = [
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
]
_MNIST_FILES = {
    "train_images": "train-images-idx3-ubyte.gz",
    "train_labels": "train-labels-idx1-ubyte.gz",
    "test_images": "t10k-images-idx3-ubyte.gz",
    "test_labels": "t10k-labels-idx1-ubyte.gz",
}
_CIFAR_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-binary.tar.gz"


def data_dir(dataset: str = "") -> str:
    root = os.environ.get("DL4J_TPU_DATA_DIR",
                          os.path.expanduser("~/.deeplearning4j_tpu"))
    path = os.path.join(root, dataset) if dataset else root
    os.makedirs(path, exist_ok=True)
    return path


def _download(url: str, dest: str, timeout: int = 60) -> bool:
    tmp = dest + ".part"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r, \
                open(tmp, "wb") as f:
            while True:
                chunk = r.read(1 << 20)
                if not chunk:
                    break
                f.write(chunk)
        os.replace(tmp, dest)
        return True
    except Exception:
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def read_idx(path: str) -> np.ndarray:
    """Parse an IDX file (optionally .gz): magic = 0x00 0x00 <dtype> <ndim>.
    MNIST uses dtype 0x08 (ubyte) with ndim 1 (labels) or 3 (images).
    Uncompressed files go through the native C++ decoder when available
    (`native/dl4j_native.cpp`, the reference's `datasets/mnist/` reader
    analog)."""
    if not path.endswith(".gz"):
        from ..native import idx_read_native, native_available
        if native_available():
            return idx_read_native(path)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    zero, dtype_code, ndim = struct.unpack(">HBB", data[:4])
    if zero != 0:
        raise ValueError(f"{path}: bad IDX magic {data[:4]!r}")
    dtypes = {0x08: np.uint8, 0x09: np.int8, 0x0B: ">i2", 0x0C: ">i4",
              0x0D: ">f4", 0x0E: ">f8"}
    if dtype_code not in dtypes:
        raise ValueError(f"{path}: unknown IDX dtype 0x{dtype_code:02x}")
    dims = struct.unpack(">" + "I" * ndim, data[4:4 + 4 * ndim])
    arr = np.frombuffer(data, dtype=dtypes[dtype_code], offset=4 + 4 * ndim)
    if arr.size != int(np.prod(dims)):
        raise ValueError(f"{path}: payload size {arr.size} != shape {dims}")
    return arr.reshape(dims)


class MnistDataFetcher:
    """70k 28x28 grayscale digits. `fetch(train)` -> (images [N,784] float32
    in [0,1] (or binarized), labels one-hot [N,10])."""

    NUM_EXAMPLES = 60000
    NUM_EXAMPLES_TEST = 10000

    def __init__(self, binarize: bool = False, train: bool = True,
                 shuffle: bool = False, seed: Optional[int] = None,
                 cache: Optional[str] = None):
        self.binarize = binarize
        self.train = train
        self.shuffle = shuffle
        self.seed = seed
        self.cache = cache or data_dir("mnist")

    def _file(self, key: str) -> str:
        fname = _MNIST_FILES[key]
        dest = os.path.join(self.cache, fname)
        raw = dest[:-3]  # pre-extracted variant also accepted
        if os.path.exists(dest) or os.path.exists(raw):
            return dest if os.path.exists(dest) else raw
        for base in _MNIST_URLS:
            if _download(base + fname, dest):
                return dest
        raise FileNotFoundError(
            f"MNIST file {fname} not in cache {self.cache} and download "
            "failed (offline?). Place the IDX .gz files there manually.")

    def fetch(self) -> Tuple[np.ndarray, np.ndarray]:
        prefix = "train" if self.train else "test"
        images = read_idx(self._file(f"{prefix}_images"))
        labels = read_idx(self._file(f"{prefix}_labels"))
        from ..native import native_available, u8_to_f32
        flat = images.reshape(images.shape[0], -1)
        if native_available() and flat.dtype == np.uint8:
            # native normalize/binarize; threshold 127 == (x/255 > 0.5)
            x = u8_to_f32(flat, binarize=self.binarize, threshold=127)
        else:
            x = flat.astype(np.float32) / 255.0
            if self.binarize:
                x = (x > 0.5).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[labels.astype(np.int64)]
        if self.shuffle:
            rng = np.random.default_rng(self.seed)
            idx = rng.permutation(x.shape[0])
            x, y = x[idx], y[idx]
        return x, y


# The classic Fisher/Anderson Iris data (150 rows, public domain), embedded
# the way the reference ships it as a bundled resource.
_IRIS_ROWS = """
5.1,3.5,1.4,0.2,0;4.9,3.0,1.4,0.2,0;4.7,3.2,1.3,0.2,0;4.6,3.1,1.5,0.2,0;
5.0,3.6,1.4,0.2,0;5.4,3.9,1.7,0.4,0;4.6,3.4,1.4,0.3,0;5.0,3.4,1.5,0.2,0;
4.4,2.9,1.4,0.2,0;4.9,3.1,1.5,0.1,0;5.4,3.7,1.5,0.2,0;4.8,3.4,1.6,0.2,0;
4.8,3.0,1.4,0.1,0;4.3,3.0,1.1,0.1,0;5.8,4.0,1.2,0.2,0;5.7,4.4,1.5,0.4,0;
5.4,3.9,1.3,0.4,0;5.1,3.5,1.4,0.3,0;5.7,3.8,1.7,0.3,0;5.1,3.8,1.5,0.3,0;
5.4,3.4,1.7,0.2,0;5.1,3.7,1.5,0.4,0;4.6,3.6,1.0,0.2,0;5.1,3.3,1.7,0.5,0;
4.8,3.4,1.9,0.2,0;5.0,3.0,1.6,0.2,0;5.0,3.4,1.6,0.4,0;5.2,3.5,1.5,0.2,0;
5.2,3.4,1.4,0.2,0;4.7,3.2,1.6,0.2,0;4.8,3.1,1.6,0.2,0;5.4,3.4,1.5,0.4,0;
5.2,4.1,1.5,0.1,0;5.5,4.2,1.4,0.2,0;4.9,3.1,1.5,0.2,0;5.0,3.2,1.2,0.2,0;
5.5,3.5,1.3,0.2,0;4.9,3.6,1.4,0.1,0;4.4,3.0,1.3,0.2,0;5.1,3.4,1.5,0.2,0;
5.0,3.5,1.3,0.3,0;4.5,2.3,1.3,0.3,0;4.4,3.2,1.3,0.2,0;5.0,3.5,1.6,0.6,0;
5.1,3.8,1.9,0.4,0;4.8,3.0,1.4,0.3,0;5.1,3.8,1.6,0.2,0;4.6,3.2,1.4,0.2,0;
5.3,3.7,1.5,0.2,0;5.0,3.3,1.4,0.2,0;7.0,3.2,4.7,1.4,1;6.4,3.2,4.5,1.5,1;
6.9,3.1,4.9,1.5,1;5.5,2.3,4.0,1.3,1;6.5,2.8,4.6,1.5,1;5.7,2.8,4.5,1.3,1;
6.3,3.3,4.7,1.6,1;4.9,2.4,3.3,1.0,1;6.6,2.9,4.6,1.3,1;5.2,2.7,3.9,1.4,1;
5.0,2.0,3.5,1.0,1;5.9,3.0,4.2,1.5,1;6.0,2.2,4.0,1.0,1;6.1,2.9,4.7,1.4,1;
5.6,2.9,3.6,1.3,1;6.7,3.1,4.4,1.4,1;5.6,3.0,4.5,1.5,1;5.8,2.7,4.1,1.0,1;
6.2,2.2,4.5,1.5,1;5.6,2.5,3.9,1.1,1;5.9,3.2,4.8,1.8,1;6.1,2.8,4.0,1.3,1;
6.3,2.5,4.9,1.5,1;6.1,2.8,4.7,1.2,1;6.4,2.9,4.3,1.3,1;6.6,3.0,4.4,1.4,1;
6.8,2.8,4.8,1.4,1;6.7,3.0,5.0,1.7,1;6.0,2.9,4.5,1.5,1;5.7,2.6,3.5,1.0,1;
5.5,2.4,3.8,1.1,1;5.5,2.4,3.7,1.0,1;5.8,2.7,3.9,1.2,1;6.0,2.7,5.1,1.6,1;
5.4,3.0,4.5,1.5,1;6.0,3.4,4.5,1.6,1;6.7,3.1,4.7,1.5,1;6.3,2.3,4.4,1.3,1;
5.6,3.0,4.1,1.3,1;5.5,2.5,4.0,1.3,1;5.5,2.6,4.4,1.2,1;6.1,3.0,4.6,1.4,1;
5.8,2.6,4.0,1.2,1;5.0,2.3,3.3,1.0,1;5.6,2.7,4.2,1.3,1;5.7,3.0,4.2,1.2,1;
5.7,2.9,4.2,1.3,1;6.2,2.9,4.3,1.3,1;5.1,2.5,3.0,1.1,1;5.7,2.8,4.1,1.3,1;
6.3,3.3,6.0,2.5,2;5.8,2.7,5.1,1.9,2;7.1,3.0,5.9,2.1,2;6.3,2.9,5.6,1.8,2;
6.5,3.0,5.8,2.2,2;7.6,3.0,6.6,2.1,2;4.9,2.5,4.5,1.7,2;7.3,2.9,6.3,1.8,2;
6.7,2.5,5.8,1.8,2;7.2,3.6,6.1,2.5,2;6.5,3.2,5.1,2.0,2;6.4,2.7,5.3,1.9,2;
6.8,3.0,5.5,2.1,2;5.7,2.5,5.0,2.0,2;5.8,2.8,5.1,2.4,2;6.4,3.2,5.3,2.3,2;
6.5,3.0,5.5,1.8,2;7.7,3.8,6.7,2.2,2;7.7,2.6,6.9,2.3,2;6.0,2.2,5.0,1.5,2;
6.9,3.2,5.7,2.3,2;5.6,2.8,4.9,2.0,2;7.7,2.8,6.7,2.0,2;6.3,2.7,4.9,1.8,2;
6.7,3.3,5.7,2.1,2;7.2,3.2,6.0,1.8,2;6.2,2.8,4.8,1.8,2;6.1,3.0,4.9,1.8,2;
6.4,2.8,5.6,2.1,2;7.2,3.0,5.8,1.6,2;7.4,2.8,6.1,1.9,2;7.9,3.8,6.4,2.0,2;
6.4,2.8,5.6,2.2,2;6.3,2.8,5.1,1.5,2;6.1,2.6,5.6,1.4,2;7.7,3.0,6.1,2.3,2;
6.3,3.4,5.6,2.4,2;6.4,3.1,5.5,1.8,2;6.0,3.0,4.8,1.8,2;6.9,3.1,5.4,2.1,2;
6.7,3.1,5.6,2.4,2;6.9,3.1,5.1,2.3,2;5.8,2.7,5.1,1.9,2;6.8,3.2,5.9,2.3,2;
6.7,3.3,5.7,2.5,2;6.7,3.0,5.2,2.3,2;6.3,2.5,5.0,1.9,2;6.5,3.0,5.2,2.0,2;
6.2,3.4,5.4,2.3,2;5.9,3.0,5.1,1.8,2
""".replace("\n", "")

_iris = np.array([[float(v) for v in row.split(",")]
                  for row in _IRIS_ROWS.strip(";").split(";")],
                 dtype=np.float32)
IRIS_FEATURES: np.ndarray = _iris[:, :4]
IRIS_LABELS: np.ndarray = np.eye(3, dtype=np.float32)[
    _iris[:, 4].astype(np.int64)]


class IrisDataFetcher:
    NUM_EXAMPLES = 150

    def __init__(self, shuffle: bool = False, seed: Optional[int] = None,
                 normalize: bool = True):
        self.shuffle = shuffle
        self.seed = seed
        self.normalize = normalize

    def fetch(self) -> Tuple[np.ndarray, np.ndarray]:
        x, y = IRIS_FEATURES.copy(), IRIS_LABELS.copy()
        if self.normalize:
            x = (x - x.mean(axis=0)) / x.std(axis=0)
        if self.shuffle:
            rng = np.random.default_rng(self.seed)
            idx = rng.permutation(x.shape[0])
            x, y = x[idx], y[idx]
        return x, y


class CifarDataFetcher:
    """CIFAR-10 binary format: records of 1 label byte + 32*32*3 bytes in
    channel-major (R plane, G plane, B plane) order; returned NHWC float32
    in [0,1], labels one-hot [N,10]."""

    NUM_TRAIN = 50000
    NUM_TEST = 10000

    def __init__(self, train: bool = True, cache: Optional[str] = None):
        self.train = train
        self.cache = cache or data_dir("cifar10")

    def _batch_files(self) -> List[str]:
        names = ([f"data_batch_{i}.bin" for i in range(1, 6)]
                 if self.train else ["test_batch.bin"])
        found = []
        for name in names:
            for cand in (os.path.join(self.cache, name),
                         os.path.join(self.cache, "cifar-10-batches-bin",
                                      name)):
                if os.path.exists(cand):
                    found.append(cand)
                    break
        if len(found) == len(names):
            return found
        # cache miss: download + extract the official tarball
        tarball = os.path.join(self.cache, "cifar-10-binary.tar.gz")
        if not os.path.exists(tarball):
            if not _download(_CIFAR_URL, tarball, timeout=300):
                raise FileNotFoundError(
                    f"CIFAR-10 batches not in cache {self.cache} and "
                    "download failed (offline?). Place data_batch_*.bin / "
                    "test_batch.bin there manually.")
        with tarfile.open(tarball, "r:gz") as tf:
            tf.extractall(self.cache, filter="data")
        return self._batch_files()

    def fetch(self) -> Tuple[np.ndarray, np.ndarray]:
        xs, ys = [], []
        for path in self._batch_files():
            raw = np.frombuffer(open(path, "rb").read(), dtype=np.uint8)
            rec = raw.reshape(-1, 3073)
            ys.append(rec[:, 0])
            xs.append(rec[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        x = np.concatenate(xs).astype(np.float32) / 255.0
        y = np.eye(10, dtype=np.float32)[np.concatenate(ys).astype(np.int64)]
        return x, y


_LFW_URL = "https://vis-www.cs.umass.edu/lfw/lfw.tgz"


class LFWDataFetcher:
    """Labeled Faces in the Wild (reference `LFWDataSetIterator.java` /
    `datasets/fetchers/LFWDataFetcher.java`): person-labeled face JPEGs.
    `fetch()` -> (images [N, H, W, 3] float32 in [0,1], labels one-hot over
    the `num_labels` most frequent people). Downloads + caches the official
    tarball; offline hosts must place `lfw.tgz` (or the extracted `lfw/`
    tree) in the cache dir."""

    def __init__(self, image_size: int = 64, num_labels: int = 0,
                 min_images_per_person: int = 1,
                 cache: Optional[str] = None):
        self.image_size = int(image_size)
        self.num_labels = int(num_labels)
        self.min_images = int(min_images_per_person)
        self.cache = cache or data_dir("lfw")

    def _root(self) -> str:
        root = os.path.join(self.cache, "lfw")
        if os.path.isdir(root):
            return root
        tarball = os.path.join(self.cache, "lfw.tgz")
        if not os.path.exists(tarball):
            if not _download(_LFW_URL, tarball, timeout=600):
                raise FileNotFoundError(
                    f"LFW not in cache {self.cache} and download failed "
                    "(offline?). Place lfw.tgz or the extracted lfw/ "
                    "directory there manually.")
        with tarfile.open(tarball, "r:gz") as tf:
            tf.extractall(self.cache, filter="data")
        if not os.path.isdir(root):
            raise FileNotFoundError(
                f"{tarball} did not extract an 'lfw/' directory; expected "
                "the official LFW tarball layout (person-named "
                "subdirectories under lfw/)")
        return root

    def _counted(self, root: str):
        """[(person, image files)] after min-images filtering and
        num_labels selection — the single definition of class ordering."""
        counted = []
        for person in sorted(
                d for d in os.listdir(root)
                if os.path.isdir(os.path.join(root, d))):
            files = sorted(
                f for f in os.listdir(os.path.join(root, person))
                if f.lower().endswith((".jpg", ".jpeg", ".png")))
            if len(files) >= self.min_images:
                counted.append((person, files))
        if self.num_labels > 0:
            counted.sort(key=lambda pf: (-len(pf[1]), pf[0]))
            counted = counted[: self.num_labels]
            counted.sort(key=lambda pf: pf[0])
        return counted

    def fetch(self) -> Tuple[np.ndarray, np.ndarray]:
        # decode through the image tier: native C++ decoders (PNG/BMP/PPM)
        # with PIL fallback for JPEG — same path ImageRecordReader uses
        from .images import ImageLoader

        root = self._root()
        counted = self._counted(root)
        xs, ys = [], []
        s = self.image_size
        loader = ImageLoader(s, s, 3)
        for label, (person, files) in enumerate(counted):
            for f in files:
                xs.append(loader.load(os.path.join(root, person, f))
                          .astype(np.float32))
                ys.append(label)
        n_cls = len(counted)
        x = np.stack(xs) if xs else np.zeros((0, s, s, 3), np.float32)
        y = (np.eye(n_cls, dtype=np.float32)[np.asarray(ys, np.int64)]
             if xs else np.zeros((0, n_cls), np.float32))
        return x, y

    def labels(self) -> List[str]:
        """Person names in class-index order — labels()[k] names one-hot
        column k of fetch()'s labels."""
        return [p for p, _ in self._counted(self._root())]


class CurvesDataFetcher:
    """Synthetic "curves" dataset (reference `CurvesDataFetcher.java` — the
    Hinton deep-autoencoder benchmark: 28x28 images of smooth random
    curves). The reference downloads a serialized copy; here the dataset is
    generated deterministically from a seed (quadratic Bezier strokes
    rasterized with anti-aliasing), which keeps it available offline and
    infinitely extensible."""

    def __init__(self, n_examples: int = 10000, image_size: int = 28,
                 seed: int = 123):
        self.n = int(n_examples)
        self.size = int(image_size)
        self.seed = int(seed)

    def fetch(self) -> Tuple[np.ndarray, np.ndarray]:
        r = np.random.default_rng(self.seed)
        s = self.size
        t = np.linspace(0.0, 1.0, 64)[:, None]          # curve parameter
        # control points for quadratic Bezier curves, [N, 3, 2] in [0, s)
        ctrl = r.uniform(2, s - 2, size=(self.n, 3, 2))
        pts = ((1 - t) ** 2 * ctrl[:, None, 0]
               + 2 * (1 - t) * t * ctrl[:, None, 1]
               + t ** 2 * ctrl[:, None, 2])             # [N, T, 2]
        imgs = np.zeros((self.n, s, s), np.float32)
        ij = np.floor(pts).astype(np.int64)
        frac = pts - ij
        n_idx = np.repeat(np.arange(self.n), t.shape[0])
        for dy in (0, 1):
            for dx in (0, 1):
                yy = np.clip(ij[..., 1] + dy, 0, s - 1).ravel()
                xx = np.clip(ij[..., 0] + dx, 0, s - 1).ravel()
                w = (np.abs(1 - dy - frac[..., 1])
                     * np.abs(1 - dx - frac[..., 0])).ravel()
                np.add.at(imgs, (n_idx, yy, xx), w)
        x = np.clip(imgs, 0.0, 1.0).reshape(self.n, -1)
        return x, x.copy()   # autoencoder dataset: target == input
