"""DeepWalk graph embeddings.

Parity with `graph/models/deepwalk/DeepWalk.java:31` + `GraphHuffman.java` +
`embeddings/GraphVectorsImpl.java`: random walks over the graph fed to a
skip-gram trainer with hierarchical softmax (the reference scores via a
Huffman binary tree over vertex degrees — here the shared SequenceVectors
HS path serves, with walk-visit counts as frequencies).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .graph import Graph
from .walkers import RandomWalkIterator, WeightedRandomWalkIterator
from ..nlp.word2vec import SequenceVectors

__all__ = ["DeepWalk", "GraphVectors"]


class GraphVectors(SequenceVectors):
    """Vertex-embedding query API (reference GraphVectorsImpl)."""

    def vertex_vector(self, idx: int) -> Optional[np.ndarray]:
        return self.word_vector(str(idx))

    def similarity_vertices(self, a: int, b: int) -> float:
        return self.similarity(str(a), str(b))

    def vertices_nearest(self, idx: int, top_n: int = 10) -> List[int]:
        return [int(w) for w in self.words_nearest(str(idx), top_n)]

    def num_vertices(self) -> int:
        return self.vocab.num_words() if self.vocab else 0


class DeepWalk(GraphVectors):
    """Builder parity: DeepWalk.Builder().vectorSize(..).windowSize(..)
    .walkLength(..).build(); then fit(graph) / fit(walk_iterator)."""

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 walk_length: int = 40, walks_per_vertex: int = 10,
                 learning_rate: float = 0.025, epochs: int = 1,
                 seed: int = 12345, weighted: bool = False,
                 use_hierarchic_softmax: bool = True, negative: int = 0,
                 batch_size: int = 512):
        super().__init__(layer_size=vector_size, window_size=window_size,
                         learning_rate=learning_rate, min_word_frequency=1,
                         epochs=epochs, seed=seed,
                         use_hierarchic_softmax=use_hierarchic_softmax,
                         negative=negative, batch_size=batch_size,
                         train_elements=True, train_sequences=False)
        self.walk_length = int(walk_length)
        self.walks_per_vertex = int(walks_per_vertex)
        self.weighted = weighted
        self._walks: List[List[str]] = []

    def _sequences(self):
        for w in self._walks:
            yield w, []

    def fit(self, graph_or_walks=None):
        if isinstance(graph_or_walks, Graph):
            g = graph_or_walks
            self._walks = []
            cls = (WeightedRandomWalkIterator if self.weighted
                   else RandomWalkIterator)
            for rep in range(self.walks_per_vertex):
                it = cls(g, self.walk_length, seed=self.seed + rep)
                for walk in it:
                    self._walks.append([str(v) for v in walk])
        elif graph_or_walks is not None:
            self._walks = [[str(v) for v in walk]
                           for walk in graph_or_walks]
        return super().fit()
