"""Graph API.

Parity with `deeplearning4j-graph`: `graph/api/IGraph.java` contracts +
`graph/graph/Graph.java` adjacency-list implementation (directed/undirected,
weighted edges, vertex values).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["Vertex", "Edge", "Graph"]


@dataclass
class Vertex:
    idx: int
    value: Any = None


@dataclass
class Edge:
    from_idx: int
    to_idx: int
    weight: float = 1.0
    directed: bool = False


class Graph:
    def __init__(self, num_vertices: int, directed: bool = False,
                 allow_multiple_edges: bool = True):
        self.directed = directed
        self.allow_multiple_edges = allow_multiple_edges
        self._vertices = [Vertex(i) for i in range(num_vertices)]
        self._adj: List[List[Edge]] = [[] for _ in range(num_vertices)]

    # ------------------------------------------------------------------
    def num_vertices(self) -> int:
        return len(self._vertices)

    def get_vertex(self, idx: int) -> Vertex:
        return self._vertices[idx]

    def set_vertex_value(self, idx: int, value):
        self._vertices[idx].value = value

    def add_edge(self, from_idx: int, to_idx: int, weight: float = 1.0,
                 directed: Optional[bool] = None):
        directed = self.directed if directed is None else directed
        e = Edge(from_idx, to_idx, weight, directed)
        if not self.allow_multiple_edges:
            for ex in self._adj[from_idx]:
                if ex.to_idx == to_idx:
                    return
        self._adj[from_idx].append(e)
        if not directed:
            self._adj[to_idx].append(Edge(to_idx, from_idx, weight, directed))

    def edges_out(self, idx: int) -> List[Edge]:
        return list(self._adj[idx])

    def neighbors(self, idx: int) -> List[int]:
        return [e.to_idx for e in self._adj[idx]]

    def degree(self, idx: int) -> int:
        return len(self._adj[idx])

    def num_edges(self) -> int:
        total = sum(len(a) for a in self._adj)
        return total if self.directed else total // 2
