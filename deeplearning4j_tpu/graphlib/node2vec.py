"""Node2Vec graph embeddings.

Parity with `deeplearning4j-nlp/.../models/node2vec/Node2Vec.java` — vertex
embeddings from second-order biased random walks (Grover & Leskovec's
return parameter p and in-out parameter q) fed to the shared SequenceVectors
skip-gram trainer (negative sampling by default, as the paper).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .deepwalk import GraphVectors
from .graph import Graph

__all__ = ["Node2Vec", "Node2VecWalker"]


class Node2VecWalker:
    """Second-order biased walks: from (prev, cur), the unnormalized
    transition weight to neighbor x is  1/p if x == prev,  1 if x is a
    neighbor of prev, 1/q otherwise — times the edge weight."""

    def __init__(self, graph: Graph, walk_length: int, p: float = 1.0,
                 q: float = 1.0, seed: int = 0):
        self.graph = graph
        self.walk_length = int(walk_length)
        self.p = float(p)
        self.q = float(q)
        self.rng = np.random.default_rng(seed)
        self._nbr_sets = [set(graph.neighbors(v))
                          for v in range(graph.num_vertices())]

    def walk_from(self, start: int) -> List[int]:
        walk = [start]
        prev: Optional[int] = None
        cur = start
        for _ in range(self.walk_length - 1):
            edges = self.graph.edges_out(cur)
            if not edges:
                break
            nxt_ids = np.array([e.to_idx for e in edges])
            w = np.array([e.weight for e in edges], dtype=np.float64)
            if prev is not None:
                prev_nbrs = self._nbr_sets[prev]
                bias = np.array([
                    1.0 / self.p if x == prev
                    else (1.0 if x in prev_nbrs else 1.0 / self.q)
                    for x in nxt_ids])
                w = w * bias
            w = w / w.sum()
            nxt = int(self.rng.choice(nxt_ids, p=w))
            walk.append(nxt)
            prev, cur = cur, nxt
        return walk

    def walks(self, walks_per_vertex: int = 1):
        n = self.graph.num_vertices()
        for _ in range(walks_per_vertex):
            for start in self.rng.permutation(n):
                yield self.walk_from(int(start))


class Node2Vec(GraphVectors):
    """Builder parity with the reference's Node2Vec model class; p/q are the
    walk bias hyperparameters from the paper."""

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 walk_length: int = 40, walks_per_vertex: int = 10,
                 p: float = 1.0, q: float = 1.0,
                 learning_rate: float = 0.025, epochs: int = 1,
                 seed: int = 12345, negative: int = 5,
                 use_hierarchic_softmax: bool = False,
                 batch_size: int = 512):
        super().__init__(layer_size=vector_size, window_size=window_size,
                         learning_rate=learning_rate, min_word_frequency=1,
                         epochs=epochs, seed=seed,
                         use_hierarchic_softmax=use_hierarchic_softmax,
                         negative=negative, batch_size=batch_size,
                         train_elements=True, train_sequences=False)
        self.walk_length = int(walk_length)
        self.walks_per_vertex = int(walks_per_vertex)
        self.p = float(p)
        self.q = float(q)
        self._walks: List[List[str]] = []

    def _sequences(self):
        for w in self._walks:
            yield w, []

    def fit(self, graph_or_walks=None):
        if isinstance(graph_or_walks, Graph):
            walker = Node2VecWalker(graph_or_walks, self.walk_length,
                                    p=self.p, q=self.q, seed=self.seed)
            self._walks = [[str(v) for v in walk]
                           for walk in walker.walks(self.walks_per_vertex)]
        elif graph_or_walks is not None:
            self._walks = [[str(v) for v in walk]
                           for walk in graph_or_walks]
        return super().fit()
