"""Graph loaders.

Parity with `graph/data/GraphLoader.java`: edge-list files ("from to" or
"from to weight" per line, configurable delimiter), adjacency-list files
("v n1 n2 n3 ...").
"""
from __future__ import annotations

from typing import Optional

from .graph import Graph

__all__ = ["GraphLoader"]


class GraphLoader:
    @staticmethod
    def load_edge_list(path: str, num_vertices: Optional[int] = None,
                       directed: bool = False, delimiter: str = None) -> Graph:
        edges = []
        max_v = -1
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(delimiter)
                a, b = int(parts[0]), int(parts[1])
                w = float(parts[2]) if len(parts) > 2 else 1.0
                edges.append((a, b, w))
                max_v = max(max_v, a, b)
        g = Graph(num_vertices or max_v + 1, directed=directed)
        for a, b, w in edges:
            g.add_edge(a, b, w)
        return g

    @staticmethod
    def load_adjacency_list(path: str, num_vertices: Optional[int] = None,
                            directed: bool = False,
                            delimiter: str = None) -> Graph:
        rows = []
        max_v = -1
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = [int(p) for p in line.split(delimiter)]
                rows.append(parts)
                max_v = max(max_v, *parts)
        g = Graph(num_vertices or max_v + 1, directed=True)
        for parts in rows:
            for b in parts[1:]:
                g.add_edge(parts[0], b, directed=True)
        return g
