"""Random walk iterators.

Parity with `graph/iterator/RandomWalkIterator.java` and
`WeightedRandomWalkIterator.java` (+ the parallel variants' semantics —
vectorized batch generation replaces thread pools).
"""
from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from .graph import Graph

__all__ = ["NoEdgeHandling", "RandomWalkIterator",
           "WeightedRandomWalkIterator"]


class NoEdgeHandling:
    SELF_LOOP_ON_DISCONNECTED = "self_loop"
    EXCEPTION_ON_DISCONNECTED = "exception"


class RandomWalkIterator:
    """Uniform random walks of fixed length from each vertex."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 0,
                 no_edge_handling: str = NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED):
        self.graph = graph
        self.walk_length = int(walk_length)
        self.seed = seed
        self.no_edge_handling = no_edge_handling
        self.reset()

    def reset(self):
        self._rng = np.random.default_rng(self.seed)
        self._next_vertex = 0

    def has_next(self) -> bool:
        return self._next_vertex < self.graph.num_vertices()

    def _step(self, v: int) -> int:
        nbrs = self.graph.neighbors(v)
        if not nbrs:
            if self.no_edge_handling == NoEdgeHandling.EXCEPTION_ON_DISCONNECTED:
                raise ValueError(f"Vertex {v} has no edges")
            return v
        return int(nbrs[self._rng.integers(0, len(nbrs))])

    def next(self) -> List[int]:
        v = self._next_vertex
        self._next_vertex += 1
        walk = [v]
        for _ in range(self.walk_length):
            v = self._step(v)
            walk.append(v)
        return walk

    def __iter__(self) -> Iterator[List[int]]:
        self.reset()
        while self.has_next():
            yield self.next()


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Transition probability proportional to edge weight."""

    def _step(self, v: int) -> int:
        edges = self.graph.edges_out(v)
        if not edges:
            if self.no_edge_handling == NoEdgeHandling.EXCEPTION_ON_DISCONNECTED:
                raise ValueError(f"Vertex {v} has no edges")
            return v
        w = np.array([e.weight for e in edges], np.float64)
        p = w / w.sum()
        return int(edges[self._rng.choice(len(edges), p=p)].to_idx)
