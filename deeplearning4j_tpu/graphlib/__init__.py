from .graph import Graph, Vertex, Edge
from .loader import GraphLoader
from .walkers import RandomWalkIterator, WeightedRandomWalkIterator, NoEdgeHandling
from .deepwalk import DeepWalk, GraphVectors
from .node2vec import Node2Vec, Node2VecWalker

__all__ = ["Graph", "Vertex", "Edge", "GraphLoader", "RandomWalkIterator",
           "WeightedRandomWalkIterator", "NoEdgeHandling", "DeepWalk",
           "GraphVectors", "Node2Vec", "Node2VecWalker"]
