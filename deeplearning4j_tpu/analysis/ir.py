"""graftlint IR tier: jaxpr/HLO verification of jit entry points.

The AST tier (rules_jit/rules_concurrency) sees what the Python source
shows; SPMD correctness lives below it — in shard specs, collective
schedules and buffer aliasing that only exist once a program is traced,
partitioned and compiled. This tier abstract-evals registered jit entry
points under a virtual 8-device mesh (the same
`--xla_force_host_platform_device_count=8` mesh the test suite trains
on), then inspects three artifacts per entry:

  * the **closed jaxpr** (`fn.trace(...)`) — axis names, the
    `sharding_constraint` schedule, `optimization_barrier` ordering
    chains, redundant reshard pairs at the primitive level;
  * the **lowered StableHLO** (`.lower().as_text()`) — donation intent
    (`tf.aliasing_output` on donated parameters) plus the lowering-time
    "donated buffers were not usable" warning;
  * the **compiled, scheduled HLO** (`.compile().as_text()`) — the
    collectives GSPMD actually inserted (op, shape, replica groups, in
    schedule order), the executable's input→output alias map, and
    text-level reshard pairs.

Rule families (ids registered with the shared engine; findings flow
through the same pragma/baseline/ratchet machinery, under the
`ir_findings` baseline section):

  ir-collective-order        two lowerings of one entry disagree on the
                             collective issue sequence — the invariant
                             elastic resize (ROADMAP item 4) must
                             preserve across processes
  ir-invalid-axis            a collective names an axis the entry's mesh
                             does not carry
  ir-redundant-reshard       reduce-scatter immediately all-gathered
                             back (or psum_scatter -> all_gather in the
                             jaxpr): a full collective round-trip that a
                             plain psum/allreduce does in one
  ir-implicit-reshard        GSPMD-inserted collective bytes exceed the
                             step's declared static accounting
                             (parallel/zero.py `info["bytes"]`), or the
                             traced `sharding_constraint` count fell
                             below the plan's declared schedule — either
                             way a "sharded" tensor is being silently
                             materialized replicated
  ir-ineffective-donation    a donate_argnums buffer the lowering or XLA
                             quietly refused to alias — the donation is
                             a no-op and peak memory is 2x the tensor
  ir-nondeterministic-reduction
                             an entry asserting bit-exact resume issues
                             multiple float gradient reductions with no
                             optimization_barrier ordering chain — XLA's
                             collective combiner may merge/reorder them,
                             so the summed gradients are not stable
                             across schedules or elastic resizes
  ir-missing-custom-call     an entry declaring the shard_map'd Pallas
                             kernel path (expects_custom_call) whose
                             traced program carries no pallas_call
                             primitive — the kernel silently fell back
                             to the XLA path

The order check has a runtime counterpart
(`analysis.sanitizer.CollectiveSequenceHasher`): the static pass digests
a compiled program's collective sequence (op/shape/replica-groups from
the HLO text), the runtime hook digests the schedule each process
actually issues per step (op/bytes/multiplicity from the trainer's
accounting). The two hash different views and are each compared ACROSS
PROCESSES within their own domain — program digest vs program digest,
runtime stream vs runtime stream — which is how item 4's kill/rejoin
drills use them.
"""
from __future__ import annotations

import hashlib
import os
import re
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .engine import (Finding, LintResult, baseline_diff, load_baseline,
                     register_rule_id)

__all__ = ["IrEntry", "analyze_entry", "run_ir_lint", "collective_sequence",
           "sequence_digest", "check_cross_program_order",
           "measured_collective_bytes", "measured_collective_bytes_by_axis",
           "IR_RULES", "IR_BASELINE_SECTION"]

IR_BASELINE_SECTION = "ir_findings"

_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "collective-permute", "all-to-all")
# HLO shape element bytes (shapes the package's programs produce)
_ITEMSIZE = {"pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2,
             "u16": 2, "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8,
             "u64": 8, "c64": 8, "c128": 16}

IR_RULES = {
    "ir-collective-order": ("ir-collective", "collective issue order "
                            "diverges between lowerings of one entry"),
    "ir-invalid-axis": ("ir-collective", "collective references an axis "
                        "name the entry's mesh does not define"),
    "ir-redundant-reshard": ("ir-collective", "reduce-scatter immediately "
                             "all-gathered back (redundant reshard pair)"),
    "ir-implicit-reshard": ("ir-reshard", "GSPMD-inserted collective "
                            "traffic exceeds the declared static "
                            "accounting, or a declared shard constraint "
                            "is missing from the traced program"),
    "ir-ineffective-donation": ("ir-donation", "donated buffer the "
                                "lowering or XLA did not alias"),
    "ir-nondeterministic-reduction": ("ir-determinism", "bit-exact entry "
                                      "issues unordered float reductions "
                                      "XLA may reassociate"),
    "ir-missing-custom-call": ("ir-kernel", "entry declares a Pallas "
                               "kernel path but the traced program "
                               "carries no pallas_call — the kernel was "
                               "silently replaced by the XLA fallback"),
}
for _rid, (_fam, _desc) in IR_RULES.items():
    register_rule_id(_rid, _fam, _desc)


@dataclass
class IrEntry:
    """One jit entry point to abstract-eval. Probes (analysis/ir_probes)
    build these from real models/trainers on the virtual mesh; tests
    build them directly around seeded mutations.

    `fn` is the JITTED callable (donation/shardings baked in) and `args`
    a concrete or abstract argument tuple it can be `.trace()`d with.
    Alternatively `compiled` carries a pre-built executable (serving's
    AOT runners) — then only the text-level checks run.
    """
    name: str                       # roster/scope name, e.g. "parallel/zero2_step"
    path: str                       # package-relative source attribution
    fn: Any = None
    args: Tuple = ()
    kwargs: Dict[str, Any] = field(default_factory=dict)
    compiled: Any = None
    mesh_axes: Tuple[str, ...] = ()
    declared_bytes: Optional[int] = None   # static per-program collective payload
    check_bytes: bool = False              # byte-diff only for scan-free steps
    # 2-D mesh entries (ISSUE 14): per-axis byte budgets, diffed against
    # the measured collectives CLASSIFIED BY AXIS via replica-group size
    # (axis_sizes = {"data": d, "model": m}; sizes must be distinct or
    # the classification falls back to "other" and the check skips)
    declared_bytes_by_axis: Optional[Dict[str, int]] = None
    axis_sizes: Optional[Dict[str, int]] = None
    expected_constraints: Optional[int] = None
    requires_ordered_reductions: bool = False
    asserts_bitexact: bool = False
    # flash-under-SPMD entries (ISSUE 18): the step is built around the
    # shard_map'd Pallas kernel, so the traced jaxpr must carry a
    # pallas_call primitive (inside the shard_map body — _walk_eqns
    # descends it). Checked at the jaxpr level: it is backend-portable
    # (interpret-mode tracing emits the same primitive the TPU lowering
    # turns into the custom call), where compiled-HLO custom-call text
    # only exists on a real TPU.
    expects_custom_call: bool = False
    byte_slack: float = 1.5                # CPU emulates reduce-scatter as
                                           # full all-reduce; 1.5x + 1KiB
                                           # absorbs that plus scalar sums

    def finding(self, rule: str, message: str, detail_key: str) -> Finding:
        """IR findings have no source line; the baseline key is
        (rule, path, entry name, stable detail token) so it survives
        unrelated edits exactly like the AST tier's line-free keys."""
        return Finding(rule, self.path, 0, 0, message, scope=self.name,
                       snippet=f"ir:{self.name}:{detail_key}")


# ---------------------------------------------------------------------------
# HLO text parsing
# ---------------------------------------------------------------------------
_INSTR = re.compile(
    r"%?([\w.\-]+)\s*=\s*(\([^)]*\)|\S+)\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(-start|-done)?\(([^)]*)\)(.*)")
_SHAPE = re.compile(r"(\w+)\[([\d,]*)\]")
_GROUPS = re.compile(r"replica_groups=(\[[^\]]*\](?:<=\[\d+\])?|\{[^}]*\})")


def _shape_bytes(shape_text: str) -> int:
    """Total bytes of every 'dtype[dims]' shape in `shape_text`."""
    total = 0
    for dt, dims in _SHAPE.findall(shape_text):
        if dt not in _ITEMSIZE:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _ITEMSIZE[dt]
    return total


def collective_sequence(hlo_text: str) -> List[Tuple[str, str, str]]:
    """(op, result shape, replica groups) per collective instruction, in
    program-text order. Compiled modules are scheduled
    (`is_scheduled=true`) so text order IS the issue order each device
    executes — the sequence elastic resize must keep identical across
    per-process programs."""
    seq = []
    for ln in hlo_text.splitlines():
        m = _INSTR.search(ln)
        if not m:
            continue
        _, shape, op, suffix, _, tail = m.groups()
        if suffix == "-done":
            continue    # the async completion half: same collective,
            # already sequenced (and sized) at its -start
        g = _GROUPS.search(ln)
        seq.append((op, shape, g.group(1) if g else ""))
    return seq


def sequence_digest(seq: Sequence[Tuple]) -> str:
    """Stable digest of a STATIC collective sequence (as parsed from
    compiled HLO text). Compare program digests against program digests
    across processes; the runtime CollectiveSequenceHasher digests a
    different view (issued ops/bytes) and is compared within its own
    domain."""
    h = hashlib.sha256()
    for item in seq:
        h.update(repr(tuple(item)).encode())
        h.update(b"\0")
    return h.hexdigest()[:16]


def check_cross_program_order(sequences: Sequence[Sequence[Tuple]]
                              ) -> Optional[str]:
    """None when every program issues the identical collective sequence;
    else a message naming the first divergence. Used three ways: the
    static pass compares independent lowerings of one entry, and the
    multi-host drills compare per-process program texts and per-process
    runtime hashes."""
    if len(sequences) < 2:
        return None
    ref = list(sequences[0])
    for pi, seq in enumerate(sequences[1:], 1):
        seq = list(seq)
        if seq == ref:
            continue
        n = min(len(ref), len(seq))
        for i in range(n):
            if ref[i] != seq[i]:
                return (f"program {pi} diverges at collective {i}: "
                        f"{ref[i]} vs {seq[i]}")
        return (f"program {pi} issues {len(seq)} collectives, "
                f"program 0 issues {len(ref)}")
    return None


def measured_collective_bytes(hlo_text: str) -> Dict[str, int]:
    """Logical payload bytes by op from the compiled text, matching the
    convention of parallel/zero.py's static accounting (full tensor
    bytes once, not x(N-1)/N wire segments): all-reduce/all-gather count
    the (full) RESULT shape, reduce-scatter counts the full OPERAND.
    Collectives inside a scan/while body appear once in the text, so for
    looped programs this is a per-iteration lower bound."""
    out: Dict[str, int] = {}
    for ln in hlo_text.splitlines():
        m = _INSTR.search(ln)
        if not m:
            continue
        _, shape, op, suffix, operands, _ = m.groups()
        if suffix == "-done":
            continue    # async pair: payload counted once at -start
        b = _shape_bytes(operands if op == "reduce-scatter" else shape)
        out[op] = out.get(op, 0) + b
    return out


_FIRST_GROUP = re.compile(r"\{(\d+(?:\s*,\s*\d+)*)\}")
_IOTA_GROUPS = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=")
_PERMUTE_PAIRS = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)+)\}")


def _permute_axis(line: str, axis_items) -> Optional[str]:
    """Mesh-axis attribution for a collective-permute: its
    `source_target_pairs` connect LINEAR device ids, so unraveling each
    (src, dst) against the mesh shape (axis_items = ordered
    (name, size) pairs, mesh-major order — the order `make_mesh` builds)
    names the axis every pair moves along. Pipeline stage handoffs shift
    exactly one coordinate (the `pipe` axis); a permute whose pairs move
    along a different single axis is a LEAK the per-axis budgets catch,
    and multi-axis pairs (GSPMD reshard shuffles) land under "other".
    Returns None when the line carries no pairs."""
    m = _PERMUTE_PAIRS.search(line)
    if not m:
        return None
    shape = [int(s) for _, s in axis_items]
    names = [n for n, _ in axis_items]
    total = 1
    for s in shape:
        total *= s

    def unravel(idx):
        coords = []
        for s in reversed(shape):
            idx, c = divmod(idx, s)
            coords.append(c)
        return coords[::-1]

    axes = set()
    for pm in re.finditer(r"\{(\d+),(\d+)\}", m.group(1)):
        a, b = int(pm.group(1)), int(pm.group(2))
        if a == b:
            continue    # identity legs of a reshard shuffle
        if not shape or a >= total or b >= total:
            return "other"
        ca, cb = unravel(a), unravel(b)
        diff = [i for i in range(len(shape)) if ca[i] != cb[i]]
        if len(diff) != 1:
            return "other"
        axes.add(diff[0])
    if len(axes) == 1:
        return names[axes.pop()]
    return "other"


def _replica_group_size(line: str) -> Optional[int]:
    """Participant count per replica group of a collective instruction
    line — the key that maps it onto a mesh axis. Handles both HLO
    forms: explicit `replica_groups={{0,4},{1,5},...}` (count the first
    group's members) and iota `replica_groups=[G,S]<=[...]` (S). None
    when the line carries no groups (the collective spans everything)."""
    m = _IOTA_GROUPS.search(line)
    if m:
        return int(m.group(2))
    if "replica_groups=" not in line:
        return None
    m = _FIRST_GROUP.search(line.split("replica_groups=", 1)[1])
    if m:
        return len(m.group(1).split(","))
    return None


def measured_collective_bytes_by_axis(hlo_text: str,
                                      axis_sizes: Dict[str, int]
                                      ) -> Dict[str, Dict[str, int]]:
    """`measured_collective_bytes` split by MESH AXIS: each collective is
    attributed to the axis whose size equals its replica-group size
    (on a (2, 4) mesh, groups of 2 ride "data", groups of 4 ride
    "model"). Collectives whose group size matches no axis — or matches
    more than one (d == m; use distinct sizes for checkable meshes) —
    land under "other". Collective-PERMUTEs carry no replica groups;
    their `source_target_pairs` are unraveled against the mesh shape
    instead (`_permute_axis` — `axis_sizes` must list the axes in MESH
    order, as `make_mesh` builds them), so a pipeline stage handoff
    attributes to `pipe` and a permute leaking onto `data`/`model`
    attributes there even when axis sizes collide. This is how the IR
    tier verifies the 2-D/3-D contract: ZeRO's optimizer collectives
    must ride the data axis at the plan's declared payload, the model
    axis must carry only the Megatron activation psums, and only the
    pipe axis may carry stage handoffs."""
    inverse: Dict[int, List[str]] = {}
    for ax, n in axis_sizes.items():
        inverse.setdefault(int(n), []).append(ax)
    items = list(axis_sizes.items())
    out: Dict[str, Dict[str, int]] = {}
    for ln in hlo_text.splitlines():
        m = _INSTR.search(ln)
        if not m:
            continue
        _, shape, op, suffix, operands, _ = m.groups()
        if suffix == "-done":
            continue
        b = _shape_bytes(operands if op == "reduce-scatter" else shape)
        if op == "collective-permute":
            ax = _permute_axis(ln, items) or "other"
        else:
            gsize = _replica_group_size(ln)
            axes = inverse.get(gsize, []) if gsize is not None else []
            ax = axes[0] if len(axes) == 1 else "other"
        bucket = out.setdefault(ax, {})
        bucket[op] = bucket.get(op, 0) + b
    return out


def compiled_aliased_params(hlo_text: str) -> set:
    """Parameter indices the compiled executable aliases to an output
    (the `input_output_alias={ {0}: (3, {}, may-alias), ... }` header)."""
    head = hlo_text.split("\n", 1)[0]
    i = head.find("input_output_alias=")
    if i < 0:
        return set()
    # the map ends at the matching close of its outer brace pair
    body = head[i + len("input_output_alias="):]
    depth = 0
    for j, ch in enumerate(body):
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                body = body[: j + 1]
                break
    return {int(m.group(1)) for m in re.finditer(r"\(\s*(\d+)\s*,", body)}


def donated_params(stablehlo_text: str) -> set:
    """Parameter indices the lowering marked as consumed donations
    (`tf.aliasing_output` / `jax.buffer_donor` attributes). Parsed
    per-argument within the @main signature only — a span-based match
    would attribute a later arg's donation attribute to an earlier
    non-donated arg (and the body's bare `%argN` uses must not count)."""
    i = stablehlo_text.find("@main(")
    if i < 0:
        return set()
    # the signature ends at the paren matching "@main(" (types may nest
    # their own parens/brackets)
    j = i + len("@main(")
    depth, k = 1, j
    while k < len(stablehlo_text) and depth:
        c = stablehlo_text[k]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        k += 1
    sig = stablehlo_text[j:k - 1]
    out = set()
    decls = list(re.finditer(r"%arg(\d+):", sig))
    for idx, m in enumerate(decls):
        end = decls[idx + 1].start() if idx + 1 < len(decls) else len(sig)
        seg = sig[m.end():end]
        if "tf.aliasing_output" in seg or "jax.buffer_donor" in seg:
            out.add(int(m.group(1)))
    return out


def _redundant_reshard_pairs(hlo_text: str) -> List[str]:
    """all-gather instructions whose operand is (directly) a
    reduce-scatter result: the pair moves the full tensor twice where
    one all-reduce would."""
    producers = {}
    for ln in hlo_text.splitlines():
        m = _INSTR.search(ln)
        if m:
            producers[m.group(1)] = m.group(3)
    pairs = []
    for ln in hlo_text.splitlines():
        m = _INSTR.search(ln)
        if not m or m.group(3) != "all-gather" or m.group(4) == "-done":
            continue    # a -done consumes its own -start handle, not data
        for op_name in re.findall(r"%([\w.\-]+)", m.group(5)):
            if producers.get(op_name) == "reduce-scatter":
                pairs.append(f"{op_name} -> {m.group(1)}")
    return pairs


# ---------------------------------------------------------------------------
# jaxpr inspection
# ---------------------------------------------------------------------------
def _walk_eqns(jaxpr):
    """Every eqn in `jaxpr` and its nested sub-jaxprs (scan/while/cond
    bodies, shard_map bodies, custom-derivative branches). Params carry
    sub-programs as either ClosedJaxpr (`.jaxpr`) or raw Jaxpr
    (`.eqns`) — shard_map uses the raw form."""
    stack = [jaxpr]
    seen = set()
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        for eq in j.eqns:
            yield eq
            for v in eq.params.values():
                vs = v if isinstance(v, (list, tuple)) else (v,)
                for vv in vs:
                    inner = getattr(vv, "jaxpr", None)
                    if inner is None and hasattr(vv, "eqns"):
                        inner = vv
                    if inner is not None:
                        stack.append(inner)


def count_primitives(jaxpr, name: str) -> int:
    return sum(1 for eq in _walk_eqns(jaxpr) if str(eq.primitive) == name)


def collect_axis_names(jaxpr) -> set:
    """Axis names referenced by collective primitives (psum, all_gather,
    psum_scatter, ppermute, axis_index, ...)."""
    out = set()
    for eq in _walk_eqns(jaxpr):
        for key in ("axis_name", "axes", "axis_index_groups_axis"):
            v = eq.params.get(key)
            if v is None:
                continue
            vs = v if isinstance(v, (tuple, list)) else (v,)
            for a in vs:
                if isinstance(a, str):
                    out.add(a)
    return out


def _jaxpr_reshard_pairs(jaxpr) -> List[str]:
    """psum_scatter results consumed directly by all_gather over the same
    axis — the primitive-level form of the redundant pair."""
    scatter_vars = {}
    pairs = []
    for eq in _walk_eqns(jaxpr):
        prim = str(eq.primitive)
        if prim == "psum_scatter":
            ax = eq.params.get("axis_name")
            for ov in eq.outvars:
                scatter_vars[id(ov)] = ax
        elif prim == "all_gather":
            ax = eq.params.get("axis_name")
            for iv in eq.invars:
                if id(iv) in scatter_vars and scatter_vars[id(iv)] == ax:
                    pairs.append(f"psum_scatter->all_gather over {ax}")
    return pairs


# ---------------------------------------------------------------------------
# Per-entry analysis
# ---------------------------------------------------------------------------
def analyze_entry(entry: IrEntry) -> List[Finding]:
    """Trace, lower and compile `entry` twice; run every IR rule. Raises
    nothing on rule hits (findings are data); raises if the entry itself
    cannot be traced (a broken probe is a bug, not a finding)."""
    findings: List[Finding] = []
    if entry.compiled is not None and entry.fn is None:
        texts = [entry.compiled.as_text()]
        jaxpr = None
        stablehlo = ""
    else:
        with warnings.catch_warnings(record=True) as wlist:
            warnings.simplefilter("always")
            traced = entry.fn.trace(*entry.args, **entry.kwargs)
            lowered = traced.lower()
            stablehlo = lowered.as_text()
            compiled = lowered.compile()
        # an independent second trace+lower+compile: the issue-order
        # determinism check (set/dict iteration anywhere in the step
        # builder shows up as a reordered schedule)
        compiled2 = entry.fn.trace(*entry.args,
                                   **entry.kwargs).lower().compile()
        texts = [compiled.as_text(), compiled2.as_text()]
        jaxpr = traced.jaxpr.jaxpr
        for w in wlist:
            msg = str(w.message)
            if "donated" in msg and "not usable" in msg:
                findings.append(entry.finding(
                    "ir-ineffective-donation",
                    "lowering dropped donation(s): " + msg.split("\n")[0],
                    "lowering-dropped"))

    text = texts[0]
    seqs = [collective_sequence(t) for t in texts]

    # -- collective-audit --------------------------------------------------
    div = check_cross_program_order(seqs)
    if div is not None:
        findings.append(entry.finding(
            "ir-collective-order",
            f"collective issue order is not stable across lowerings: {div}",
            "order"))
    if jaxpr is not None and entry.mesh_axes:
        unknown = collect_axis_names(jaxpr) - set(entry.mesh_axes)
        if unknown:
            findings.append(entry.finding(
                "ir-invalid-axis",
                f"collectives reference axis name(s) {sorted(unknown)} "
                f"not defined by the entry's mesh {entry.mesh_axes}",
                "axis:" + ",".join(sorted(unknown))))
    pairs = _redundant_reshard_pairs(text)
    if jaxpr is not None:
        pairs += _jaxpr_reshard_pairs(jaxpr)
    for p in pairs:
        findings.append(entry.finding(
            "ir-redundant-reshard",
            f"reduce-scatter result is immediately all-gathered back "
            f"({p}) — the pair moves the full tensor twice where one "
            "all-reduce would; keep the scattered shard or reduce "
            "replicated", "pair"))

    # -- implicit-reshard --------------------------------------------------
    if entry.check_bytes and entry.declared_bytes is not None:
        measured = measured_collective_bytes(text)
        total = sum(measured.values())
        budget = int(entry.declared_bytes * entry.byte_slack) + 1024
        if total > budget:
            findings.append(entry.finding(
                "ir-implicit-reshard",
                f"GSPMD inserted {total} collective bytes "
                f"({measured}) against {entry.declared_bytes} declared "
                f"by the step's static accounting (slack-adjusted budget "
                f"{budget}) — a sharded tensor is being materialized "
                "replicated", "bytes"))
    if entry.declared_bytes_by_axis and entry.axis_sizes:
        by_axis = measured_collective_bytes_by_axis(text, entry.axis_sizes)
        for ax in sorted(entry.declared_bytes_by_axis):
            declared = entry.declared_bytes_by_axis[ax]
            got = sum(by_axis.get(ax, {}).values())
            budget = int(declared * entry.byte_slack) + 1024
            if got > budget:
                findings.append(entry.finding(
                    "ir-implicit-reshard",
                    f"GSPMD inserted {got} collective bytes on the "
                    f"'{ax}' mesh axis ({by_axis.get(ax, {})}) against "
                    f"{declared} declared for that axis (slack-adjusted "
                    f"budget {budget}) — a tensor sharded over the other "
                    "axis is being materialized/resharded here",
                    f"bytes:{ax}"))
    if entry.expected_constraints is not None and jaxpr is not None:
        got = count_primitives(jaxpr, "sharding_constraint")
        if got < entry.expected_constraints:
            findings.append(entry.finding(
                "ir-implicit-reshard",
                f"traced program carries {got} sharding_constraint(s) "
                f"but the plan's declared layout schedule has "
                f"{entry.expected_constraints} — a with_sharding_"
                "constraint was dropped; XLA propagation is now free to "
                "replicate the shard", "constraints"))

    # -- missing-custom-call ----------------------------------------------
    if entry.expects_custom_call and jaxpr is not None:
        calls = count_primitives(jaxpr, "pallas_call")
        if calls == 0:
            findings.append(entry.finding(
                "ir-missing-custom-call",
                "entry declares the shard_map'd Pallas kernel path but "
                "the traced program carries no pallas_call primitive — "
                "the kernel was dropped and the step silently runs the "
                "XLA fallback (the einsum path should be selected "
                "EXPLICITLY via configure_flash_attention, not by "
                "losing the kernel)", "custom-call"))

    # -- ineffective-donation ---------------------------------------------
    if stablehlo:
        intended = donated_params(stablehlo)
        aliased = compiled_aliased_params(text)
        dropped = intended - aliased
        if dropped:
            findings.append(entry.finding(
                "ir-ineffective-donation",
                f"XLA did not alias donated input(s) {sorted(dropped)} "
                f"in the executable (aliased: {sorted(aliased)}) — the "
                "donation is a no-op and the buffer is live twice",
                "xla-dropped"))

    # -- nondeterministic-reduction ---------------------------------------
    # requires_ordered_reductions = the program SHAPE half (stage-2,
    # multi-bucket float reductions); asserts_bitexact = the CONTRACT
    # half (the equivalence suite promises bit-exact resume). Only the
    # conjunction is a bug: unordered reductions on an entry nobody
    # asserts bit-exactness for are a performance choice, not a lint.
    if entry.requires_ordered_reductions and entry.asserts_bitexact \
            and jaxpr is not None:
        barriers = count_primitives(jaxpr, "optimization_barrier")
        if barriers == 0:
            findings.append(entry.finding(
                "ir-nondeterministic-reduction",
                "entry asserts bit-exact resume and issues bucketed "
                "float gradient reductions, but the traced program has "
                "NO optimization_barrier ordering chain — XLA's "
                "collective combiner may merge/reorder the reductions, "
                "so the summed gradients are not stable across "
                "schedules or elastic resizes (set "
                "ZeroConfig.ordered_flush=True)", "unordered"))
    return findings


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------
def run_ir_lint(entries: Optional[Sequence[IrEntry]] = None,
                baseline_path: Optional[str] = None,
                rules: Optional[Sequence[str]] = None) -> LintResult:
    """Analyze `entries` (default: the probe-built roster covering the
    package's jit entry points) and diff against the `ir_findings`
    baseline section. Mirrors engine.run_lint's contract so the CLI,
    metrics and tests treat both tiers uniformly.

    Raises RuntimeError on a single-device backend: with one device the
    virtual mesh degenerates, GSPMD inserts no collectives, and a
    "clean" run would have verified nothing — a silently green gate is
    worse than a loud environment error (set
    XLA_FLAGS=--xla_force_host_platform_device_count=8 before jax
    initializes, as tests/conftest.py and tools/graftlint --ir do)."""
    import jax

    if jax.device_count() < 2:
        raise RuntimeError(
            f"graftlint IR pass needs a multi-device mesh, got "
            f"{jax.device_count()} device(s) — the sharding/collective "
            "rules cannot fire on one device and a clean run would "
            "verify nothing. Set XLA_FLAGS=--xla_force_host_platform_"
            "device_count=8 (CPU) before jax initializes.")
    if entries is None:
        from .ir_probes import build_entries
        entries = build_entries()
    findings: List[Finding] = []
    for entry in entries:
        findings.extend(analyze_entry(entry))
    wanted = set(rules) if rules else None
    if wanted is not None:
        findings = [f for f in findings if f.rule in wanted]
    findings.sort(key=lambda f: (f.path, f.scope, f.rule, f.snippet))
    result = LintResult(findings=findings, files=len(list(entries)))
    baseline = load_baseline(baseline_path, section=IR_BASELINE_SECTION) \
        if baseline_path else {}
    if wanted is not None:
        baseline = {k: v for k, v in baseline.items()
                    if k.split("|", 1)[0] in wanted}
    result.new, result.stale_baseline = baseline_diff(findings, baseline)
    return result
