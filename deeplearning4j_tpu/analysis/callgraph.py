"""Package call graph + JAX trace-entry discovery.

The jit/tracer-hygiene rules need to know which functions execute under a
JAX trace. That set is discovered, not annotated: every `jax.jit` /
`pjit` / `shard_map` / `lax.scan|while_loop|cond|fori_loop` / `vmap` /
`grad` call site (and decorator) in the package marks its callee as a
**trace root**, and everything reachable from a root through the
package-local call graph is considered traced.

Resolution is deliberately best-effort AST-level: plain names resolve
lexically (nested defs, then module top level, then project-local
imports), `self.m()` resolves within the enclosing class (then named
base classes), `module.f()` through import aliases. Unresolvable calls
(data-driven dispatch, third-party callables) are dropped — the rules
prefer false negatives over noisy false positives.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = ["CallGraph", "FuncInfo", "JitSite"]

# attribute-chain tails that make their callee argument(s) traced
_TRACE_WRAPPERS = {
    "jit", "pjit", "vmap", "pmap", "grad", "value_and_grad", "shard_map",
    "checkpoint", "remat", "custom_vjp", "custom_jvp", "named_call",
}
# lax control flow: positions of traced callee args
_TRACE_CONTROL = {
    "scan": (0,), "while_loop": (0, 1), "fori_loop": (2,), "cond": (1, 2, 3),
    "switch": None,   # every positional arg after the index may be a branch
    "associative_scan": (0,), "map": (0,),
}
# jit-like constructors (the recompile/donation rules key off these
# specifically, not off control-flow primitives)
_JIT_MAKERS = {"jit", "pjit"}


def walk_shallow(body) -> "list[ast.AST]":
    """Walk statements/expressions WITHOUT descending into nested
    function/lambda/class bodies — each def owns its own nodes (calls in
    a closure belong to the closure's call-graph entry, not its parent's).
    The nested def node itself IS yielded (so `jax.jit(inner)` sites and
    decorators stay visible to the enclosing scope's rules)."""
    out: List[ast.AST] = []
    stack = list(body) if isinstance(body, (list, tuple)) else [body]
    while stack:
        node = stack.pop()
        if not isinstance(node, ast.AST):
            continue
        out.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # decorators/defaults evaluate in the enclosing scope
            stack.extend(getattr(node, "decorator_list", []))
            if getattr(node, "args", None) is not None:
                stack.extend(d for d in node.args.defaults if d is not None)
                stack.extend(d for d in node.args.kw_defaults
                             if d is not None)
            continue
        if isinstance(node, ast.Lambda):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def attr_chain(node: ast.AST) -> Optional[str]:
    """'jax.lax.scan' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class FuncInfo:
    qualname: str                  # "pkg.mod:Class.method" / "pkg.mod:fn.inner"
    module: str
    node: ast.AST                  # FunctionDef | AsyncFunctionDef | Lambda
    sf: object                     # SourceFile
    class_name: Optional[str] = None
    params: Tuple[str, ...] = ()
    static_params: Set[str] = field(default_factory=set)
    calls: Set[str] = field(default_factory=set)      # resolved callee qualnames
    traced_root: bool = False

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1].rsplit(":", 1)[-1]


@dataclass
class JitSite:
    """One jit/pjit construction site (`jax.jit(f, ...)`)."""
    sf: object
    node: ast.Call
    scope: str                     # enclosing qualname
    callee: Optional[str]          # resolved qualname of the jitted fn
    donate: Tuple[int, ...] = ()
    donate_names: Tuple[str, ...] = ()
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    watched: bool = False          # wrapped in telemetry watch_compiles(...)
    binding: Optional[str] = None  # name/attr the jitted callable is bound to


def _params_of(node) -> Tuple[str, ...]:
    a = node.args
    names = [x.arg for x in a.posonlyargs + a.args]
    if a.vararg:
        names.append(a.vararg.arg)
    names += [x.arg for x in a.kwonlyargs]
    if a.kwarg:
        names.append(a.kwarg.arg)
    return tuple(names)


def _int_tuple(node) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _str_tuple(node) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


class _ModuleIndexer(ast.NodeVisitor):
    """One pass per module: function defs with lexical scopes, class
    layout, import aliases."""

    def __init__(self, sf, graph: "CallGraph"):
        self.sf = sf
        self.graph = graph
        self.stack: List[str] = []         # qualname components
        self.class_stack: List[Optional[str]] = []
        self.scope_defs: List[Dict[str, str]] = [{}]  # name -> qualname
        self.module = sf.module

    # -- imports --------------------------------------------------------
    def visit_Import(self, node: ast.Import):
        for a in node.names:
            alias = a.asname or a.name.split(".")[0]
            self.graph.imports.setdefault(self.module, {})[alias] = \
                (a.name, None)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        src = node.module or ""
        if node.level:
            base = self.module.split(".")
            # conftest-style: module 'a.b.c' with level 1 -> package 'a.b'
            base = base[: len(base) - node.level]
            src = ".".join(base + ([src] if src else []))
        for a in node.names:
            alias = a.asname or a.name
            self.graph.imports.setdefault(self.module, {})[alias] = \
                (src, a.name)

    # -- defs -----------------------------------------------------------
    def _qual(self, name: str) -> str:
        return f"{self.module}:{'.'.join(self.stack + [name])}" \
            if self.stack else f"{self.module}:{name}"

    def _handle_def(self, node):
        qual = self._qual(node.name)
        info = FuncInfo(qual, self.module, node, self.sf,
                        class_name=self.class_stack[-1]
                        if self.class_stack else None,
                        params=_params_of(node))
        self.graph.funcs[qual] = info
        self.scope_defs[-1][node.name] = qual
        if info.class_name:
            self.graph.methods.setdefault(
                (self.module, info.class_name), {})[node.name] = qual
            self.graph.method_names.setdefault(node.name, []).append(qual)
        elif not self.stack:
            self.graph.toplevel.setdefault(self.module, {})[node.name] = qual
        self.stack.append(node.name)
        self.class_stack.append(self.class_stack[-1]
                                if self.class_stack else None)
        self.scope_defs.append({})
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.scope_defs.pop()
        self.class_stack.pop()
        self.stack.pop()

    def visit_FunctionDef(self, node):
        self._handle_def(node)

    def visit_AsyncFunctionDef(self, node):
        self._handle_def(node)

    def visit_ClassDef(self, node: ast.ClassDef):
        bases = [attr_chain(b) for b in node.bases]
        self.graph.class_bases[(self.module, node.name)] = \
            [b for b in bases if b]
        self.stack.append(node.name)
        self.class_stack.append(node.name)
        self.scope_defs.append({})
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.scope_defs.pop()
        self.class_stack.pop()
        self.stack.pop()

    def visit_Lambda(self, node: ast.Lambda):
        qual = self._qual(f"<lambda@{node.lineno}>")
        self.graph.funcs[qual] = FuncInfo(
            qual, self.module, node, self.sf,
            class_name=self.class_stack[-1] if self.class_stack else None,
            params=_params_of(node))
        self.graph.lambda_quals[id(node)] = qual
        self.generic_visit(node)


class CallGraph:
    def __init__(self, project):
        self.project = project
        self.funcs: Dict[str, FuncInfo] = {}
        self.toplevel: Dict[str, Dict[str, str]] = {}
        self.methods: Dict[Tuple[str, str], Dict[str, str]] = {}
        self.method_names: Dict[str, List[str]] = {}
        self.class_bases: Dict[Tuple[str, str], List[str]] = {}
        self.imports: Dict[str, Dict[str, Tuple[str, Optional[str]]]] = {}
        self.lambda_quals: Dict[int, str] = {}
        self.jit_sites: List[JitSite] = []
        self.watch_names: Set[str] = set()   # CompileWatcher-covered names
        self.thread_targets: Set[str] = set()
        for sf in project.files:
            _ModuleIndexer(sf, self).visit(sf.tree)
        # module-level statements form a pseudo-function per module so
        # top-level jit sites / thread spawns are discovered too
        for sf in project.files:
            qual = f"{sf.module}:<module>"
            self.funcs[qual] = FuncInfo(qual, sf.module, sf.tree, sf)
        self._link()
        self.traced: Set[str] = self._reach(
            {q for q, f in self.funcs.items() if f.traced_root})
        self.thread_reachable: Set[str] = self._reach(self.thread_targets)

    # -- name resolution -------------------------------------------------
    def resolve_name(self, module: str, scopes: List[ast.AST], name: str
                     ) -> Optional[str]:
        """Lexical lookup: enclosing defs' nested functions, module top
        level, then project-local imports."""
        for scope in reversed(scopes):
            qual = self._scoped.get((id(scope), name))
            if qual:
                return qual
        qual = self.toplevel.get(module, {}).get(name)
        if qual:
            return qual
        imp = self.imports.get(module, {}).get(name)
        if imp:
            src, item = imp
            if item is None:
                return None                      # bare module import
            tl = self.toplevel.get(src)
            if tl and item in tl:
                return tl[item]
        return None

    def resolve_method(self, module: str, class_name: Optional[str],
                       name: str) -> Optional[str]:
        """self.<name>() within class_name (searching named bases, then a
        globally-unique method name as last resort)."""
        seen = set()
        stack = [(module, class_name)] if class_name else []
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            qual = self.methods.get(key, {}).get(name)
            if qual:
                return qual
            for base in self.class_bases.get(key, []):
                base_name = base.rsplit(".", 1)[-1]
                for (m, c) in self.methods:
                    if c == base_name:
                        stack.append((m, c))
        quals = self.method_names.get(name, [])
        return quals[0] if len(quals) == 1 else None

    def resolve_call_target(self, sf, scopes: List[ast.AST],
                            class_name: Optional[str], func: ast.AST
                            ) -> Optional[str]:
        if isinstance(func, ast.Lambda):
            return self.lambda_quals.get(id(func))
        if isinstance(func, ast.Name):
            return self.resolve_name(sf.module, scopes, func.id)
        if isinstance(func, ast.Attribute):
            chain = attr_chain(func)
            if chain is None:
                return None
            head, _, rest = chain.partition(".")
            if head in ("self", "cls") and rest and "." not in rest:
                return self.resolve_method(sf.module, class_name, rest)
            imp = self.imports.get(sf.module, {}).get(head)
            if imp and rest and "." not in rest:
                src, item = imp
                mod = src if item is None else (
                    f"{src}.{item}" if f"{src}.{item}" in self.toplevel
                    else None)
                if mod:
                    return self.toplevel.get(mod, {}).get(rest)
        return None

    # -- linking pass -----------------------------------------------------
    def _link(self):
        # map (scope-node id, fname) -> qual for lexical lookup
        self._scoped: Dict[Tuple[int, str], str] = {}
        for qual, info in self.funcs.items():
            mod_prefix, _, dotted = qual.partition(":")
            parent = dotted.rsplit(".", 1)[0] if "." in dotted else None
            if parent is not None:
                pq = f"{mod_prefix}:{parent}"
                pinfo = self.funcs.get(pq)
                if pinfo is not None:
                    self._scoped[(id(pinfo.node), info.name)] = qual
        for qual, info in list(self.funcs.items()):
            self._link_one(info)

    def _enclosing_scopes(self, info: FuncInfo) -> List[ast.AST]:
        scopes = []
        mod_prefix, _, dotted = info.qualname.partition(":")
        parts = dotted.split(".")
        for i in range(1, len(parts) + 1):
            q = f"{mod_prefix}:{'.'.join(parts[:i])}"
            f = self.funcs.get(q)
            if f is not None:
                scopes.append(f.node)
        return scopes

    def _link_one(self, info: FuncInfo):
        sf = info.sf
        scopes = self._enclosing_scopes(info)
        body = info.node.body if not isinstance(info.node, ast.Lambda) \
            else [info.node.body]
        for node in walk_shallow(body):
            if isinstance(node, ast.Call):
                self._record_call(info, sf, scopes, node)
        # decorators are trace roots too (@jax.jit / @partial(jax.jit,...))
        for deco in getattr(info.node, "decorator_list", []):
            jit = self._jit_like(deco if isinstance(deco, ast.Call) else deco)
            if jit:
                info.traced_root = True
                tail = jit.rsplit(".", 1)[-1]
                if tail in _JIT_MAKERS and isinstance(deco, ast.Call):
                    self.jit_sites.append(self._mk_site(
                        info.sf, deco, info.qualname, info.qualname,
                        binding=info.name))

    def _jit_like(self, node) -> Optional[str]:
        """The trace-wrapper chain named by `node`, unwrapping
        functools.partial(jax.jit, ...) forms."""
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain.rsplit(".", 1)[-1] == "partial" and node.args:
                return self._jit_like(node.args[0])
            return chain if chain and chain.rsplit(".", 1)[-1] in (
                _TRACE_WRAPPERS | set(_TRACE_CONTROL)) else None
        chain = attr_chain(node)
        if chain is None:
            return None
        tail = chain.rsplit(".", 1)[-1]
        return chain if tail in (_TRACE_WRAPPERS | set(_TRACE_CONTROL)) \
            else None

    def _mk_site(self, sf, call: ast.Call, scope: str,
                 callee: Optional[str], binding=None) -> JitSite:
        site = JitSite(sf, call, scope, callee, binding=binding)
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                site.donate = _int_tuple(kw.value)
            elif kw.arg == "donate_argnames":
                site.donate_names = _str_tuple(kw.value)
            elif kw.arg == "static_argnums":
                site.static_argnums = _int_tuple(kw.value)
            elif kw.arg == "static_argnames":
                site.static_argnames = _str_tuple(kw.value)
        return site

    def _record_call(self, info: FuncInfo, sf, scopes, node: ast.Call):
        chain = attr_chain(node.func)
        tail = chain.rsplit(".", 1)[-1] if chain else None
        # telemetry coverage: watch_compiles(fn, "name")
        if tail == "watch_compiles":
            for arg in node.args[1:]:
                if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                                str):
                    self.watch_names.add(arg.value)
        # trace roots
        if tail in _TRACE_WRAPPERS and (
                chain == tail or chain.startswith(("jax.", "lax."))
                or tail == "shard_map"):
            positions: Sequence[int] = (0,)
            self._mark_traced(info, sf, scopes, node, positions,
                              jit=tail in _JIT_MAKERS)
        elif tail in _TRACE_CONTROL:
            # require a jax-ish prefix for control-flow names (plain
            # `map`/`scan` calls on host objects must not count)
            if "lax" in chain or chain.startswith("jax."):
                positions = _TRACE_CONTROL[tail]
                if positions is None:
                    positions = tuple(range(len(node.args)))
                self._mark_traced(info, sf, scopes, node, positions,
                                  jit=False)
        # thread targets
        if tail == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    q = self.resolve_call_target(sf, scopes,
                                                 info.class_name, kw.value)
                    if q:
                        self.thread_targets.add(q)
        elif tail == "submit" and node.args:
            q = self.resolve_call_target(sf, scopes, info.class_name,
                                         node.args[0])
            if q:
                self.thread_targets.add(q)
        # plain call edge
        q = self.resolve_call_target(sf, scopes, info.class_name, node.func)
        if q:
            info.calls.add(q)

    def _mark_traced(self, info: FuncInfo, sf, scopes, node: ast.Call,
                     positions: Sequence[int], jit: bool):
        site: Optional[JitSite] = None
        for pos in positions:
            if pos >= len(node.args):
                continue
            callee = self.resolve_call_target(sf, scopes, info.class_name,
                                              node.args[pos])
            if jit and site is None:
                site = self._mk_site(sf, node, info.qualname, callee)
                self.jit_sites.append(site)
            if callee is None:
                continue
            cinfo = self.funcs.get(callee)
            if cinfo is None:
                continue
            cinfo.traced_root = True
            if jit and site is not None:
                # un-taint declared static params on the DIRECT callee
                statics = set()
                for i in site.static_argnums:
                    if i < len(cinfo.params):
                        statics.add(cinfo.params[i])
                statics.update(n for n in site.static_argnames
                               if n in cinfo.params)
                cinfo.static_params |= statics

    # -- reachability -----------------------------------------------------
    def _reach(self, roots: Set[str]) -> Set[str]:
        seen: Set[str] = set()
        stack = [q for q in roots if q in self.funcs]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            for callee in self.funcs[q].calls:
                if callee not in seen and callee in self.funcs:
                    stack.append(callee)
        return seen

    def is_traced(self, qualname: str) -> bool:
        return qualname in self.traced
