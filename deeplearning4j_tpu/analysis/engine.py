"""graftlint core: source model, pragma suppression, baseline, runner.

Design constraints, in order:

  * **Self-hosting must stay cheap.** The whole ~25k-line package parses
    and lints in a couple of seconds (pure `ast`, one pass per file), so
    the lint can run inside the tier-1 test suite as a hard CI gate.
  * **Findings must be stable across unrelated edits.** A baseline keyed
    on line numbers churns on every PR; findings are keyed on
    `(rule, file, enclosing-scope, normalized source line)` instead, so
    only touching the flagged line itself invalidates its baseline entry.
  * **Suppression is always visible in the diff.** Inline
    `# graftlint: disable=<rule>` pragmas mark reviewed false positives
    where they live; the baseline file holds the pre-existing accepted
    findings so NEW findings fail CI while old ones are burned down
    incrementally (the classic ratchet workflow).
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["Finding", "SourceFile", "Project", "LintResult", "RULES",
           "rule", "run_lint", "load_baseline", "write_baseline",
           "baseline_diff"]

_PRAGMA = re.compile(
    r"#\s*graftlint:\s*disable(?P<scope>-file)?\s*=\s*"
    r"(?P<rules>[\w\-*]+(?:\s*,\s*[\w\-*]+)*)")


# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class RuleInfo:
    id: str
    family: str          # "jit-hygiene" | "recompile" | "donation" | "concurrency"
    description: str


RULES: Dict[str, RuleInfo] = {}
_CHECKERS: List[Tuple[RuleInfo, Callable]] = []


def rule(id: str, family: str, description: str):
    """Register a checker: `fn(project) -> Iterable[Finding]`. A checker
    may emit several rule ids (cross-rule passes register under the id
    they primarily own); every emitted id must be registered."""
    info = RuleInfo(id, family, description)

    def deco(fn):
        RULES[id] = info
        _CHECKERS.append((info, fn))
        return fn
    return deco


def register_rule_id(id: str, family: str, description: str):
    """Register an id emitted by a shared checker (no new pass)."""
    RULES[id] = RuleInfo(id, family, description)


# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------
@dataclass
class Finding:
    rule: str
    path: str            # package-relative, '/'-separated
    line: int            # 1-based
    col: int
    message: str
    scope: str = ""      # enclosing qualname ("" = module level)
    snippet: str = ""    # stripped source line (baseline key material)

    def key(self) -> str:
        """Line-number-free identity used by the baseline (stable across
        unrelated edits elsewhere in the file)."""
        return f"{self.rule}|{self.path}|{self.scope}|{self.snippet}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.scope or '<module>'}] {self.message}")


# ---------------------------------------------------------------------------
# Source model
# ---------------------------------------------------------------------------
class SourceFile:
    """One parsed module: AST + per-line pragma suppression sets."""

    def __init__(self, path: str, relpath: str, module: str, text: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.module = module          # dotted module name
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        # line (1-based) -> set of disabled rule ids ('*' = all)
        self.disabled: Dict[int, set] = {}
        self.file_disabled: set = set()
        for i, ln in enumerate(self.lines, 1):
            if "graftlint" not in ln:
                continue
            m = _PRAGMA.search(ln)
            if not m:
                continue
            ids = {r.strip() for r in m.group("rules").split(",") if r.strip()}
            if m.group("scope"):
                self.file_disabled |= ids
            else:
                self.disabled.setdefault(i, set()).update(ids)

    def suppressed(self, rule_id: str, line: int) -> bool:
        if "*" in self.file_disabled or rule_id in self.file_disabled:
            return True
        ids = self.disabled.get(line)
        return bool(ids) and ("*" in ids or rule_id in ids)

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


class Project:
    """All lintable files under one or more roots, plus the lazily-built
    call graph (shared by every rule pass)."""

    def __init__(self, files: Sequence[SourceFile]):
        self.files = list(files)
        self.by_module: Dict[str, SourceFile] = {f.module: f for f in files}
        self._callgraph = None

    @property
    def callgraph(self):
        if self._callgraph is None:
            from .callgraph import CallGraph
            self._callgraph = CallGraph(self)
        return self._callgraph

    def finding(self, sf: SourceFile, rule_id: str, node: ast.AST,
                message: str, scope: str = "") -> Optional[Finding]:
        """Build a Finding unless a pragma suppresses it. Checkers emit
        via this helper so suppression stays in one place."""
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        if sf.suppressed(rule_id, line):
            return None
        return Finding(rule_id, sf.relpath, line, col, message,
                       scope=scope, snippet=sf.line_text(line))


def _module_name(root: str, path: str) -> str:
    rel = os.path.relpath(path, os.path.dirname(os.path.abspath(root)) or ".")
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def load_project(paths: Sequence[str],
                 exclude: Sequence[str] = ("__pycache__",)) -> Project:
    """Parse every .py file under `paths` (files or directories)."""
    files: List[SourceFile] = []
    for root in paths:
        root = os.path.normpath(root)
        if os.path.isfile(root):
            candidates = [(root, root)]
        else:
            candidates = []
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [d for d in dirnames if d not in exclude]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        candidates.append((root, os.path.join(dirpath, fn)))
        for r, path in candidates:
            relpath = os.path.relpath(path,
                                      os.path.dirname(os.path.abspath(r)))
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
            try:
                files.append(SourceFile(path, relpath, _module_name(r, path),
                                        text))
            except SyntaxError as e:
                raise SyntaxError(f"graftlint cannot parse {path}: {e}")
    return Project(files)


# ---------------------------------------------------------------------------
# Baseline workflow
# ---------------------------------------------------------------------------
# One baseline file holds one section per analysis tier: "findings" for
# the AST pass, "ir_findings" for the jaxpr/HLO tier (ISSUE 13). Writing
# one section must never clobber the other — each tier ratchets
# independently.
def load_baseline(path: str, section: str = "findings") -> Dict[str, int]:
    """{finding key: accepted count}. Missing file = empty baseline."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return {str(k): int(v) for k, v in data.get(section, {}).items()}


def write_baseline(path: str, findings: Sequence[Finding],
                   section: str = "findings"):
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    payload = {}
    if os.path.exists(path):
        try:
            with open(path, "r", encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError):
            payload = {}
    payload["comment"] = (
        "graftlint accepted-findings baseline. Keys are "
        "rule|file|scope|source-line (line-number free); 'findings' is "
        "the AST pass, 'ir_findings' the jaxpr/HLO tier. Regenerate "
        "with: python -m tools.graftlint deeplearning4j_tpu/ "
        "--write-baseline [--ir]")
    payload[section] = {k: counts[k] for k in sorted(counts)}
    with open(path, "w", encoding="utf-8", newline="\n") as f:
        json.dump(payload, f, indent=1, sort_keys=False)
        f.write("\n")


def baseline_diff(findings: Sequence[Finding], baseline: Dict[str, int]
                  ) -> Tuple[List[Finding], List[str]]:
    """(new findings not covered by the baseline, stale baseline keys).
    A key covers at most its accepted count — the ratchet: fixing one of
    two identical findings then re-introducing it elsewhere still fails."""
    seen: Dict[str, int] = {}
    new: List[Finding] = []
    for f in findings:
        k = f.key()
        seen[k] = seen.get(k, 0) + 1
        if seen[k] > baseline.get(k, 0):
            new.append(f)
    stale = [k for k, n in sorted(baseline.items())
             if seen.get(k, 0) < n]
    return new, stale


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------
@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    new: List[Finding] = field(default_factory=list)
    stale_baseline: List[str] = field(default_factory=list)
    files: int = 0

    def by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def new_by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.new:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def run_lint(paths: Sequence[str], baseline_path: Optional[str] = None,
             rules: Optional[Sequence[str]] = None) -> LintResult:
    """Lint `paths`; compare against the baseline when given. `rules`
    restricts to a subset of rule ids (default: all registered)."""
    from . import rules_concurrency  # noqa: F401  (registration side effect)
    from . import rules_jit  # noqa: F401

    project = load_project(paths)
    wanted = set(rules) if rules else None
    findings: List[Finding] = []
    ran = set()
    for info, checker in _CHECKERS:
        if checker in ran:          # one checker may own several ids
            continue
        ran.add(checker)
        for f in checker(project):
            if f is None:
                continue
            if wanted is not None and f.rule not in wanted:
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    result = LintResult(findings=findings, files=len(project.files))
    baseline = load_baseline(baseline_path) if baseline_path else {}
    if wanted is not None:
        # a rule-restricted run must only be judged against (and must
        # not report as stale) baseline entries for the selected rules
        baseline = {k: v for k, v in baseline.items()
                    if k.split("|", 1)[0] in wanted}
    result.new, result.stale_baseline = baseline_diff(findings, baseline)
    return result
