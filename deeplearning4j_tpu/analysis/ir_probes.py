"""IR-tier probes: build real jit entry points for analysis/ir.py.

The AST tier's `unwatched-jit-entry` rule drove the telemetry
`watch_compiles` roster to 100% coverage of the package's jit entry
points; these probes construct representatives of every entry-point
FAMILY on the virtual 8-device mesh — tiny models (d=8 MLP, one-edge
graph) so each trace+lower+compile is tens of milliseconds — and hand
them to the IR rules with the metadata the rules diff against:

  * the ZeRO step/superstep entries carry `parallel/zero.py`'s static
    accounting (declared collective payload bytes, the declared
    `with_sharding_constraint` schedule) and the bit-exactness the
    equivalence suite asserts;
  * the serving entries are the registry's AOT-compiled executables,
    audited as compiled text (no re-lowering — what serves is what is
    checked);
  * everything else (single-device nn entries) is audited for donation
    aliasing and schedule determinism.

Tests reuse the builders here to seed mutations (drop a shard
constraint, unorder the bucket flushes, donate an unaliasable buffer)
and prove each rule fires.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from .ir import IrEntry

__all__ = ["build_entries", "tiny_mlp", "nn_entries", "graph_entries",
           "parallel_entries", "zero_accum_entry", "mesh2d_entries",
           "mesh2d_zero1_tp_entry", "flash_spmd_entry", "flash_entries",
           "pp_entry", "pp_entries", "serving_entries", "decode_entry",
           "decode_entries", "elastic_restore_entry", "elastic_entries",
           "virtual_mesh"]


def virtual_mesh():
    """The lint mesh: every local device on one `data` axis (8 under the
    CI/CLI `--xla_force_host_platform_device_count=8` setup)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from ..parallel.mesh import MeshAxes

    devs = np.array(jax.devices())
    return Mesh(devs.reshape(devs.size), (MeshAxes.DATA,))


def tiny_mlp(seed: int = 0):
    """8->16->4 MLP with Adam — four param leaves, one of each shape
    class (two matrices, two biases), enough for the ZeRO plan to have
    sharded AND replicated leaves and >=2 gradient buckets at a small
    bucket bound."""
    from .. import (Adam, DenseLayer, InputType, MultiLayerNetwork,
                    NeuralNetConfiguration, OutputLayer)

    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def _batch(b: int = 16):
    import jax.numpy as jnp
    import numpy as np

    r = np.random.default_rng(0)
    x = jnp.asarray(r.normal(size=(b, 8)).astype(np.float32))
    y = jnp.asarray(np.eye(4, dtype=np.float32)[np.arange(b) % 4])
    return x, y


def nn_entries() -> List[IrEntry]:
    """MultiLayerNetwork family: the per-batch train step (donates
    params/state/opt), score, predict, and the accumulated superstep
    (nested scan) — the single-device half of the roster."""
    import jax
    import jax.numpy as jnp

    model = tiny_mlp()
    x, y = _batch()
    step = jnp.asarray(0, jnp.int32)
    rng = jax.random.PRNGKey(0)
    p, s, o = model.params, model.state, model.updater_state
    entries = [
        IrEntry("nn/train_step", "nn/multilayer.py",
                fn=model._train_step.__wrapped__,
                args=(p, s, o, step, x, y, rng, None, None)),
        IrEntry("nn/score", "nn/multilayer.py",
                fn=model._score_fn.__wrapped__,
                args=(p, s, x, y, None, None)),
        IrEntry("nn/predict", "nn/multilayer.py",
                fn=model._predict_fn.__wrapped__,
                args=(p, s, x, None)),
    ]
    K, M, B = 2, 2, 8
    xs = jnp.zeros((K, M, B, 8), jnp.float32)
    ys = jnp.asarray(jnp.broadcast_to(
        jnp.eye(4, dtype=jnp.float32)[jnp.arange(B) % 4], (K, M, B, 4)))
    ones = jnp.ones((K, M, B), jnp.float32)
    entries.append(IrEntry(
        "nn/accum_superstep", "nn/superstep.py",
        fn=model._accum_superstep_fn(False).__wrapped__,
        args=(p, s, o, step, rng, xs, ys, ones, ones)))
    entries.append(IrEntry(
        "nn/superstep", "nn/superstep.py",
        fn=model._superstep_fn.__wrapped__,
        args=(p, s, o, step, rng, xs[:, 0], ys[:, 0], ones[:, 0],
              ones[:, 0])))
    return entries


def graph_entries() -> List[IrEntry]:
    """ComputationGraph family representative (the graph train step has
    its own step builder and donation wiring)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .. import (Adam, DenseLayer, InputType, NeuralNetConfiguration,
                    OutputLayer)
    from ..nn.graph import ComputationGraph

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
            .graph_builder()
            .add_inputs("in")
            .add_layer("dense", DenseLayer(n_out=16, activation="relu"),
                       "in")
            .add_layer("out", OutputLayer(n_out=4, activation="softmax",
                                          loss="mcxent"), "dense")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(8))
            .build())
    model = ComputationGraph(conf).init()
    r = np.random.default_rng(0)
    x = {"in": jnp.asarray(r.normal(size=(16, 8)).astype(np.float32))}
    y = {"out": jnp.asarray(np.eye(4, dtype=np.float32)[np.arange(16) % 4])}
    return [IrEntry(
        "graph/train_step", "nn/graph.py",
        fn=model._train_step.__wrapped__,
        args=(model.params, model.state, model.updater_state,
              jnp.asarray(0, jnp.int32), x, y, jax.random.PRNGKey(0),
              None, None))]


def _trainer_entry(strategy, name: str, bucket_mb: Optional[float] = None
                   ) -> IrEntry:
    import jax
    import jax.numpy as jnp

    from ..parallel.trainer import ParallelTrainer

    model = tiny_mlp()
    kw = {} if bucket_mb is None else {"zero_bucket_mb": bucket_mb}
    tr = ParallelTrainer(model, strategy=strategy, **kw)
    x, y = _batch()
    info = tr.collective_accounting()
    entry = IrEntry(
        name, "parallel/zero.py" if info else "parallel/trainer.py",
        fn=tr._step_fn.__wrapped__,
        args=(tr._params, tr._state, tr._opt, jnp.asarray(0, jnp.int32),
              x, y, jax.random.PRNGKey(0), None, None),
        mesh_axes=tuple(tr.mesh.axis_names),
        asserts_bitexact=True)   # tests/test_zero.py asserts replicated==zero
    if info:
        entry.declared_bytes = sum(info["bytes"].values())
        entry.check_bytes = True           # scan-free: text == per-step
        entry.expected_constraints = info.get("expected_constraints")
    return entry


def parallel_entries() -> List[IrEntry]:
    """ParallelTrainer family on the virtual mesh: the SYNC replicated,
    ZeRO-1 and ZeRO-2 per-batch steps (each carrying its declared static
    accounting where the strategy publishes one) plus the AVERAGING
    shard_map local step."""
    import jax
    import jax.numpy as jnp

    from ..parallel.trainer import (ParallelTrainer, ShardingStrategy,
                                    TrainingMode)

    entries = [
        _trainer_entry(ShardingStrategy.REPLICATED, "parallel/train_step"),
        _trainer_entry(ShardingStrategy.ZERO1, "parallel/zero1_step"),
        _trainer_entry(ShardingStrategy.ZERO2, "parallel/zero2_step",
                       bucket_mb=0.0005),
    ]
    tr = ParallelTrainer(tiny_mlp(), mode=TrainingMode.AVERAGING)
    n = tr.n_data
    x, y = _batch(16)
    resh = lambda a: jnp.reshape(a, (n, -1) + a.shape[1:])
    entries.append(IrEntry(
        "parallel/local_step", "parallel/trainer.py",
        fn=tr._local_step.__wrapped__,
        args=(tr._params, tr._state, tr._opt, jnp.asarray(0, jnp.int32),
              resh(x), resh(y), None, None, jax.random.PRNGKey(0)),
        mesh_axes=tuple(tr.mesh.axis_names)))
    return entries


def zero_accum_entry(stage: int = 2, bucket_mb: float = 0.0005,
                     ordered_flush: bool = True, model=None,
                     K: int = 2, M: int = 2, B: int = 16) -> IrEntry:
    """The ZeRO accumulated superstep (nested scan, barrier-token-ordered
    bucket flushes, sharded fp32 accumulators) jitted exactly as
    ParallelTrainer jits it. Public so tests can seed mutations through
    the same builder (ordered_flush=False, monkeypatched constraints)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import MeshAxes
    from ..parallel.zero import (ZeroConfig, make_zero_accum_superstep,
                                 zero_opt_shardings)

    from ..telemetry.compile_watch import watch_compiles

    model = model if model is not None else tiny_mlp()
    mesh = virtual_mesh()
    cfg = ZeroConfig(stage=stage, bucket_mb=bucket_mb,
                     ordered_flush=ordered_flush)
    fn, info = make_zero_accum_superstep(model, mesh, config=cfg)
    repl = NamedSharding(mesh, P())
    win = NamedSharding(mesh, P(None, None, MeshAxes.DATA))
    o_sh = zero_opt_shardings(model.updater_state, model.params, mesh,
                              MeshAxes.DATA)
    jitted = watch_compiles(jax.jit(
        fn,
        in_shardings=(repl, repl, o_sh, repl, repl, win, win, win, win),
        out_shardings=(repl, repl, o_sh, repl, repl, repl),
        donate_argnums=(0, 1, 2)),
        f"analysis/ir_probe:zero{stage}_accum_superstep").__wrapped__
    xs = jnp.zeros((K, M, B, 8), jnp.float32)
    ys = jnp.asarray(jnp.broadcast_to(
        jnp.eye(4, dtype=jnp.float32)[jnp.arange(B) % 4], (K, M, B, 4)))
    ones = jnp.ones((K, M, B), jnp.float32)
    return IrEntry(
        f"parallel/zero{stage}_accum_superstep", "parallel/zero.py",
        fn=jitted,
        args=(model.params, model.state, model.updater_state,
              jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
              xs, ys, ones, ones),
        mesh_axes=tuple(mesh.axis_names),
        expected_constraints=info.get("expected_constraints"),
        requires_ordered_reductions=(stage >= 2
                                     and info.get("n_buckets", 0) >= 2),
        asserts_bitexact=True)


def _mesh2d_tp_entry(shape: Tuple[int, int]
                     ) -> Tuple[IrEntry, int, int]:
    """The DP×TP train step on a (data, model) mesh, plus its measured
    MODEL-axis collective bytes (the Megatron activation-psum traffic)
    and its "other"-bucket bytes (collectives spanning neither single
    axis: whole-mesh groups, permutes). Both measurements become byte
    BUDGETS for the matching ZERO1×TP entry: ZeRO-1 only adds data-axis
    optimizer collectives, so extra model-axis traffic means a model
    shard is being silently resharded — and the "other" budget closes
    the remaining hole, a rematerialization compiled as ONE gather over
    BOTH axes (replica group size d·m) that axis-bucketed budgets alone
    would never see."""
    import jax
    import jax.numpy as jnp

    from ..analysis.ir import measured_collective_bytes_by_axis
    from ..parallel.trainer import ParallelTrainer, ShardingStrategy

    d, m = shape
    tr = ParallelTrainer(tiny_mlp(), mesh_shape=shape,
                         strategy=ShardingStrategy.TENSOR_PARALLEL)
    x, y = _batch()
    args = (tr._params, tr._state, tr._opt, jnp.asarray(0, jnp.int32),
            x, y, jax.random.PRNGKey(0), None, None)
    fn = tr._step_fn.__wrapped__
    text = fn.trace(*args).lower().compile().as_text()
    by_axis = measured_collective_bytes_by_axis(
        text, {"data": d, "model": m})
    model_bytes = sum(by_axis.get("model", {}).values())
    other_bytes = sum(by_axis.get("other", {}).values())
    entry = IrEntry(
        f"parallel/tp_step_{d}x{m}", "parallel/trainer.py",
        fn=fn, args=args, mesh_axes=tuple(tr.mesh.axis_names))
    return entry, model_bytes, other_bytes


def mesh2d_zero1_tp_entry(shape: Tuple[int, int] = (2, 4),
                          model_budget: Optional[int] = None,
                          other_budget: int = 0,
                          mutate: Optional[str] = None) -> IrEntry:
    """The ZERO1×TP train step on a (data, model) mesh, carrying the
    extended 2-D contract: per-AXIS byte budgets (data = the plan's
    declared optimizer payload, model = the paired TP step's measured
    activation traffic) and the plan's `with_sharding_constraint`
    schedule. Public so tests can seed mutations through the same
    builder:

      mutate="drop_constraints"  the step skips constrain_params/opt
                                 entirely — the traced constraint count
                                 falls below the declared schedule
      mutate="drop_model_axis"   constraints keep their COUNT but lose
                                 the model axis (data-only specs): the
                                 update materializes params replicated
                                 over `model` and the model-axis bytes
                                 blow the TP-derived budget
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import MeshAxes, make_mesh
    from ..parallel.sharding import (ShardingStrategy, model_layer_hints,
                                     param_specs)
    from ..parallel.zero import (ZeroConfig, _ZeroPlan, make_zero_step,
                                 zero_opt_shardings)
    from ..telemetry.compile_watch import watch_compiles

    d, m = shape
    model = tiny_mlp()
    mesh = make_mesh({MeshAxes.DATA: d, MeshAxes.MODEL: m})
    base = param_specs(model.params, ShardingStrategy.ZERO1_TP, mesh,
                       layers=model_layer_hints(model))
    cfg = ZeroConfig(stage=1)
    if mutate is None:
        step, info = make_zero_step(model, mesh, config=cfg,
                                    base_specs=base,
                                    model_axis=MeshAxes.MODEL)
    else:
        # seeded mutations re-assemble the step body so the contract
        # (expected constraints / per-axis budgets) stays the TRUE plan's
        true_plan = _ZeroPlan(model, mesh, MeshAxes.DATA, cfg,
                              base_specs=base, model_axis=MeshAxes.MODEL)
        info = dict(true_plan.info)
        info["expected_constraints"] = true_plan.expected_constraints()
        if mutate == "drop_model_axis":
            plan = _ZeroPlan(model, mesh, MeshAxes.DATA, cfg)  # data-only
        elif mutate == "drop_constraints":
            plan = None
        else:
            raise ValueError(f"unknown mutation {mutate!r}")
        grad_fn = model.grad_step_fn

        def step(params, state, opt_state, step_i, x, y, rng, fm, lm):
            score, new_state, grads = grad_fn(params, state, x, y, rng,
                                              fm, lm)
            new_params, new_opt = model.apply_updates(params, grads,
                                                      opt_state, step_i)
            if plan is not None:
                new_params = plan.constrain_params(new_params)
                new_opt = plan.constrain_opt(new_opt)
            return new_params, new_state, new_opt, score

    repl = NamedSharding(mesh, P())
    batch = NamedSharding(mesh, P(MeshAxes.DATA))
    p_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), base,
        is_leaf=lambda s: isinstance(s, P))
    o_sh = zero_opt_shardings(model.updater_state, model.params, mesh,
                              base=base)
    jitted = watch_compiles(jax.jit(
        step,
        in_shardings=(p_sh, repl, o_sh, repl, batch, batch, repl, batch,
                      batch),
        out_shardings=(p_sh, repl, o_sh, repl),
        donate_argnums=(0, 1, 2)),
        f"analysis/ir_probe:zero1_tp_step_{d}x{m}").__wrapped__
    x, y = _batch()
    params = jax.device_put(model.params, p_sh)
    opt = jax.device_put(model.updater_state, o_sh)
    entry = IrEntry(
        f"parallel/zero1_tp_step_{d}x{m}", "parallel/zero.py",
        fn=jitted,
        args=(params, model.state, opt, jnp.asarray(0, jnp.int32),
              x, y, jax.random.PRNGKey(0), None, None),
        mesh_axes=tuple(mesh.axis_names),
        expected_constraints=info.get("expected_constraints"))
    if model_budget is not None:
        entry.axis_sizes = {"data": d, "model": m}
        # "other" is budgeted too (TP-measured + slack floor): a sharded
        # tensor rematerialized via ONE whole-mesh gather (group size
        # d·m) lands in that bucket, not under either axis
        entry.declared_bytes_by_axis = {
            "data": sum(info["bytes"].values()),
            "model": model_budget,
            "other": int(other_budget)}
    return entry


def mesh2d_entries() -> List[IrEntry]:
    """The 2-D train-step family (ISSUE 14) on BOTH reshapes of the
    8-device mesh — (2, 4) and (4, 2), distinct axis sizes so the
    per-axis byte classification is unambiguous. Each reshape registers
    the DP×TP step and the ZERO1×TP step; the TP step's measured
    model-axis traffic becomes the ZeRO entry's model-axis budget."""
    entries: List[IrEntry] = []
    for shape in ((2, 4), (4, 2)):
        tp_entry, model_bytes, other_bytes = _mesh2d_tp_entry(shape)
        entries.append(tp_entry)
        entries.append(mesh2d_zero1_tp_entry(shape,
                                             model_budget=model_bytes,
                                             other_budget=other_bytes))
    return entries


def _flash_arm(shape: Tuple[int, int], flash):
    """Build the ZERO1×TP transformer-LM trainer with the attention mode
    FORCED (``flash="spmd"`` -> shard_map'd Pallas kernel, interpret mode
    on the CPU mesh; ``flash=False`` -> the einsum reference) and return
    the jitted step fn plus its args. Both arms share the model, mesh and
    batch so their compiled texts differ only in the attention body."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..parallel.scaling_bench import _build_transformer_lm
    from ..parallel.trainer import ParallelTrainer, ShardingStrategy

    vocab, seq, b = 32, 8, 8
    tr = ParallelTrainer(_build_transformer_lm(vocab, 16, 4, 1, seq),
                         mesh_shape=shape,
                         strategy=ShardingStrategy.ZERO1_TP, flash=flash)
    r = np.random.default_rng(0)
    x = r.integers(0, vocab, (b, seq, 1)).astype(np.float32)
    y = np.eye(vocab, dtype=np.float32)[r.integers(0, vocab, (b, seq))]
    args = (tr._params, tr._state, tr._opt, jnp.asarray(0, jnp.int32),
            x, y, jax.random.PRNGKey(0), None, None)
    return tr._step_fn.__wrapped__, args, tuple(tr.mesh.axis_names)


def flash_spmd_entry(shape: Tuple[int, int] = (2, 4),
                     budgets: Optional[dict] = None,
                     mutate: Optional[str] = None) -> IrEntry:
    """The flash-attention ZERO1×TP train step: the shard_map'd Pallas
    kernel must SURVIVE into the traced program (`expects_custom_call` —
    a silent einsum fallback is a perf regression, not an error) and its
    per-axis collective bytes must stay inside the paired einsum arm's
    measured budgets (the kernel is per-shard local, so it may remove
    attention collectives but never add reshard traffic). Public so tests
    can seed the mutation through the same builder:

      mutate="drop_flash"  the step body is the einsum fallback while the
                           entry still declares the kernel contract — the
                           jaxpr carries no pallas_call and
                           `ir-missing-custom-call` fires
    """
    if mutate not in (None, "drop_flash"):
        raise ValueError(f"unknown mutation {mutate!r}")
    d, m = shape
    fn, args, axes = _flash_arm(
        shape, False if mutate == "drop_flash" else "spmd")
    entry = IrEntry(
        f"parallel/flash_spmd_step_{d}x{m}", "kernels/attention.py",
        fn=fn, args=args, mesh_axes=axes, expects_custom_call=True)
    if budgets is not None:
        entry.axis_sizes = {"data": d, "model": m}
        entry.declared_bytes_by_axis = dict(budgets)
    return entry


def flash_entries() -> List[IrEntry]:
    """The flash-under-SPMD pair (ISSUE 18): compile the EINSUM arm of
    the same ZERO1×TP transformer-LM step first and measure its per-axis
    collective payloads; those measurements become the flash entry's
    budgets on every bucket (data, model, other), so any reshard byte the
    shard_map'd kernel adds over the fallback is a finding."""
    from ..analysis.ir import measured_collective_bytes_by_axis

    shape = (2, 4)
    fn, args, _ = _flash_arm(shape, False)
    text = fn.trace(*args).lower().compile().as_text()
    by_axis = measured_collective_bytes_by_axis(
        text, {"data": shape[0], "model": shape[1]})
    budgets = {ax: sum(by_axis.get(ax, {}).values())
               for ax in ("data", "model", "other")}
    return [flash_spmd_entry(shape, budgets=budgets)]


def _pp_stack_model(depth: int, hidden: int = 8, seed: int = 0):
    """Uniform Dense(hidden->hidden) stack + softmax head: the minimal
    homogeneous-run model the PipelinePlan stages (input width == hidden
    so every Dense layer is stackable)."""
    from .. import (Adam, DenseLayer, InputType, MultiLayerNetwork,
                    NeuralNetConfiguration, OutputLayer)

    b = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
         .list())
    for _ in range(depth):
        b = b.layer(DenseLayer(n_out=hidden, activation="tanh"))
    conf = (b.layer(OutputLayer(n_out=4, activation="softmax",
                                loss="mcxent"))
            .set_input_type(InputType.feed_forward(hidden)).build())
    return MultiLayerNetwork(conf).init()


def _pp_build(shape: Tuple[int, int, int], zero: bool, M: int, B: int,
              mutate: Optional[str] = None, hidden: int = 8,
              tp: Optional[bool] = None):
    """Assemble the 1F1B accumulated-superstep jit + args on a 3-D
    (data, model, pipe) mesh, exactly as ParallelTrainer jits it.
    Returns (jitted_unwrapped, args, info, mesh)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import MeshAxes, make_mesh
    from ..parallel.pipeline import PipelinePlan, make_pp_accum_superstep
    from ..parallel.sharding import _opt_sharding_like
    from ..telemetry.compile_watch import watch_compiles

    d, m, p = shape
    tp = zero if tp is None else tp
    mesh = make_mesh({MeshAxes.DATA: d, MeshAxes.MODEL: m,
                      MeshAxes.PIPE: p})
    model = _pp_stack_model(depth=p, hidden=hidden)
    plan = PipelinePlan(model, mesh, tp=tp)
    params_pp = plan.stack(model.params)
    state_pp = plan.stack(model.state)
    opt_pp = plan.stack(model.updater_state)
    p_specs = plan.param_specs()
    p_sh = plan.shardings(p_specs)
    s_sh = plan.shardings(plan.state_specs())
    zero_plan = None
    if zero:
        from ..parallel.zero import ZeroConfig, _ZeroPlan
        zero_plan = _ZeroPlan(model, mesh, MeshAxes.DATA,
                              ZeroConfig(stage=1), base_specs=p_specs,
                              model_axis=MeshAxes.MODEL,
                              params=params_pp, opt_state=opt_pp)
        o_sh = zero_plan.opt_shardings_tree
    else:
        o_sh = _opt_sharding_like(opt_pp, params_pp, p_sh)
    fn, info = make_pp_accum_superstep(model, plan, zero_plan=zero_plan,
                                       mutate=mutate)
    repl = NamedSharding(mesh, P())
    win = NamedSharding(mesh, P(None, None, MeshAxes.DATA))
    name = ("zero1_tp_pp" if zero else "pp") + f"_step_{d}x{m}x{p}"
    jitted = watch_compiles(jax.jit(
        fn,
        in_shardings=(p_sh, s_sh, o_sh, repl, repl, win, win, win, win),
        out_shardings=(p_sh, s_sh, o_sh, repl, repl, repl),
        donate_argnums=(0, 1, 2)),
        f"analysis/ir_probe:{name}").__wrapped__
    xs = jnp.zeros((1, M, B, hidden), jnp.float32)
    ys = jnp.asarray(jnp.broadcast_to(
        jnp.eye(4, dtype=jnp.float32)[jnp.arange(B) % 4], (1, M, B, 4)))
    args = (jax.device_put(params_pp, p_sh),
            jax.device_put(state_pp, s_sh),
            jax.device_put(opt_pp, o_sh),
            jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
            xs, ys, None, None)
    return jitted, args, info, mesh


def pp_entry(shape: Tuple[int, int, int] = (1, 1, 8), *, zero: bool = False,
             M: int = 8, B: int = 32, mutate: Optional[str] = None,
             budgets: Optional[dict] = None,
             budget_from_plan: bool = False) -> IrEntry:
    """The 1F1B step family on a (data, model, pipe) mesh, carrying the
    pipeline contract: the declared `with_sharding_constraint` schedule
    (the 1F1B builder's buffer constraints + the ZeRO plan's shard
    constraints) and optional per-AXIS byte budgets — the `data` budget
    is the ZeRO plan's declared optimizer payload, `model`/`other` come
    from the PAIRED no-ZeRO build (`pp_entries`), and the `pipe` axis is
    deliberately unbudgeted (stage handoffs ride it by design). Public
    so tests can seed mutations through the same builder:

      mutate="drop_stage_constraint"  the step emits NO buffer sharding
                                      constraints — the traced count
                                      falls below the declared schedule
      mutate="permute_data_axis"      the injection buffer is
                                      additionally rolled along its
                                      data-sharded row axis before the
                                      ring scan (a halo exchange) — a
                                      collective-permute leaking onto
                                      `data` that blows that axis's
                                      byte budget
    """
    d, m, p = shape
    jitted, args, info, mesh = _pp_build(shape, zero, M, B, mutate=mutate)
    kind = "zero1_tp_pp" if zero else "pp"
    entry = IrEntry(
        f"parallel/{kind}_step_{d}x{m}x{p}", "parallel/pipeline.py",
        fn=jitted, args=args, mesh_axes=tuple(mesh.axis_names),
        expected_constraints=info["expected_constraints"])
    if budget_from_plan and zero:
        budgets = dict(budgets or {})
        budgets["data"] = sum(info["zero"]["bytes"].values())
    if budgets is not None:
        entry.axis_sizes = {"data": d, "model": m, "pipe": p}
        entry.declared_bytes_by_axis = dict(budgets)
        # the data bucket carries GSPMD's activation-buffer staging
        # gathers on top of the plan's declared optimizer payload — a
        # wider slack than the scan-free 2-D steps, still far below the
        # ~Nx a replicated stage-param materialization would cost
        entry.byte_slack = 2.0
    return entry


def pp_entries() -> List[IrEntry]:
    """The 1F1B roster (ISSUE 15): the pure pipeline on (1, 1, 8) with
    hard zero budgets on `data`/`model` (no traffic may ride them at
    all — d = m = 1), and the ZERO1×TP×PP composition on both
    distinct-size reshapes (2, 1, 4) and (1, 2, 4) — the data budget
    from the ZeRO plan's declared accounting, the model/other budgets
    from the PAIRED no-ZeRO build of the identical step (ZeRO-1 adds
    only data-axis optimizer traffic, so anything extra on `model` is a
    resharded stage/TP param). The `pipe` axis stays unbudgeted: stage
    handoffs ride it by design."""
    from .ir import measured_collective_bytes_by_axis

    entries: List[IrEntry] = []
    entries.append(pp_entry((1, 1, 8),
                            budgets={"data": 0, "model": 0}))
    for shape in ((2, 1, 4), (1, 2, 4)):
        d, m, p = shape
        # the paired arm: the IDENTICAL TP×PP step without the ZeRO
        # plan — its model/other traffic is the legitimate Megatron
        # boundary payload the ZeRO entry may not exceed
        jitted, args, _info, _mesh = _pp_build(shape, False, 8, 32,
                                               tp=True)
        text = jitted.trace(*args).lower().compile().as_text()
        by_axis = measured_collective_bytes_by_axis(
            text, {"data": d, "model": m, "pipe": p})
        paired = {ax: sum(ops.values()) for ax, ops in by_axis.items()}
        entries.append(pp_entry(
            shape, zero=True, budget_from_plan=True,
            budgets={"model": paired.get("model", 0),
                     "other": paired.get("other", 0)}))
    return entries


def serving_entries() -> List[IrEntry]:
    """The serving plane's AOT executables: register a tiny model, then
    audit exactly the compiled runners request threads will invoke."""
    from ..serving.registry import ModelRegistry

    reg = ModelRegistry()
    reg.register("ir-probe", tiny_mlp(), buckets=(8,))
    return [IrEntry(f"serving/aot:{name}:b{bucket}", "serving/registry.py",
                    compiled=co)
            for name, bucket, co in reg.aot_executables()]


def _decode_build(seed: int = 0):
    """Tiny generate-capable LM (vocab=16, width=8, 1 block) registered
    into a fresh registry, plus the paged decode engine over it — small
    enough that tracing both decode-plane steps is milliseconds."""
    from .. import (Adam, EmbeddingSequenceLayer, InputType,
                    MultiLayerNetwork, NeuralNetConfiguration,
                    RnnOutputLayer, TransformerBlock)
    from ..serving.decode.engine import DecodeEngine
    from ..serving.registry import ModelRegistry

    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
            .list()
            .layer(EmbeddingSequenceLayer(n_in=16, n_out=8))
            .layer(TransformerBlock(n_heads=2))
            .layer(RnnOutputLayer(n_out=16, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(1, 16)).build())
    reg = ModelRegistry()
    reg.register("ir-gen", MultiLayerNetwork(conf).init(), buckets=(1,))
    eng = DecodeEngine(reg, "ir-gen", block_len=4, decode_buckets=(1, 2))
    return eng, reg.get("ir-gen")


def decode_entry(phase: str = "tick",
                 mutate: Optional[str] = None) -> IrEntry:
    """One decode-plane jit entry (`phase` in prefill|tick), donation and
    byte budget declared: the cache pytree (arg 1) is donated and must
    alias the output arena bit-for-bit; a single-device step declares 0
    collective payload bytes.

    Mutations (each must trip exactly one IR rule):

      mutate="donate_tokens"  the int32 token ids are donated TOO — they
        can alias nothing in the (f32/int8 cache, f32 logits) outputs,
        so the lowering/XLA must drop that donation
        -> ir-ineffective-donation.
    """
    import jax
    import jax.numpy as jnp

    from ..serving.decode.cache import make_cache
    from ..serving.decode.engine import build_decode_fn, build_prefill_fn

    eng, v = _decode_build()
    spec = eng.spec
    if mutate is None:
        donate = (1,)
    elif mutate == "donate_tokens":
        donate = (1, 2)
    else:
        raise ValueError(f"unknown mutation {mutate!r}")
    w = spec.table_width
    if phase == "prefill":
        fn = build_prefill_fn(v.model, v.snapshot, spec)
        args = (v.snapshot.data, make_cache(spec),
                jnp.zeros((1, 8), jnp.int32), jnp.ones((1,), jnp.int32),
                jnp.zeros((1, w), jnp.int32))
    elif phase == "tick":
        fn = build_decode_fn(v.model, v.snapshot, spec)
        args = (v.snapshot.data, make_cache(spec),
                jnp.zeros((2,), jnp.int32), jnp.zeros((2,), jnp.int32),
                jnp.zeros((2, w), jnp.int32))
    else:
        raise ValueError(f"unknown decode phase {phase!r}")
    from ..telemetry.compile_watch import watch_compiles
    jitted = watch_compiles(
        jax.jit(fn, donate_argnums=donate),
        f"analysis/ir_probe:decode_{phase}").__wrapped__
    return IrEntry(f"serving/decode_{phase}", "serving/decode/engine.py",
                   fn=jitted, args=args,
                   declared_bytes=0, check_bytes=True)


def decode_entries() -> List[IrEntry]:
    """The generation plane's two compiled signatures (ISSUE 16): the
    batch-1 prompt prefill and the batched decode tick, audited for
    donation aliasing (the arena must update in place, never copy) and
    the zero-collective byte budget of a single-device step."""
    return [decode_entry("prefill"), decode_entry("tick")]


def elastic_restore_entry(shape: Tuple[int, int] = (2, 4),
                          hidden: int = 64,
                          mutate: Optional[str] = None) -> IrEntry:
    """The elastic-restore re-placement step (ISSUE 19): after a mesh
    reshape, `load_elastic_state` -> `_prepare` re-lands the restored
    host trees through identity jits with sharded out_shardings (the
    `parallel/{param,opt}_placement` entries). Landing replicated host
    bytes onto shards is pure slicing — the compiled program must move
    ZERO collective bytes on EVERY axis (floor-budgeted at the linter's
    1KiB slack floor). A hidden width of 64 makes each dense kernel
    (8x64, 2KiB f32) bigger than that floor, so a single wrong-direction
    gather is an unambiguous finding. Public so tests can seed the
    mutation through the same builder:

      mutate="gather_replicated"  the inputs arrive SHARDED and the
                                  out_shardings are replicated — the
                                  restore path compiles to all-gathers
                                  (a resize that re-materializes every
                                  shard on every device) and the
                                  per-axis byte budgets blow
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .. import (Adam, DenseLayer, InputType, MultiLayerNetwork,
                    NeuralNetConfiguration, OutputLayer)
    from ..parallel.mesh import MeshAxes, make_mesh
    from ..parallel.sharding import (ShardingStrategy, model_layer_hints,
                                     param_specs)
    from ..parallel.zero import zero_opt_shardings
    from ..telemetry.compile_watch import watch_compiles

    if mutate not in (None, "gather_replicated"):
        raise ValueError(f"unknown mutation {mutate!r}")
    d, m = shape
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    model = MultiLayerNetwork(conf).init()
    mesh = make_mesh({MeshAxes.DATA: d, MeshAxes.MODEL: m})
    base = param_specs(model.params, ShardingStrategy.ZERO1_TP, mesh,
                      layers=model_layer_hints(model))
    p_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), base,
        is_leaf=lambda s: isinstance(s, P))
    o_sh = zero_opt_shardings(model.updater_state, model.params, mesh,
                              base=base)
    repl_p = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, P()), p_sh,
        is_leaf=lambda s: isinstance(s, NamedSharding))
    repl_o = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, P()), o_sh,
        is_leaf=lambda s: isinstance(s, NamedSharding))
    if mutate == "gather_replicated":
        params = jax.device_put(model.params, p_sh)
        opt = jax.device_put(model.updater_state, o_sh)
        out_sh = (repl_p, repl_o)
    else:
        # restore lands host (replicated) trees onto the shards
        params = jax.device_put(model.params, repl_p)
        opt = jax.device_put(model.updater_state, repl_o)
        out_sh = (p_sh, o_sh)
    jitted = watch_compiles(
        jax.jit(lambda p, o: (p, o), out_shardings=out_sh),
        f"analysis/ir_probe:elastic_restore_{d}x{m}").__wrapped__
    entry = IrEntry(
        f"parallel/elastic_restore_{d}x{m}", "parallel/elastic.py",
        fn=jitted, args=(params, opt),
        mesh_axes=tuple(mesh.axis_names))
    entry.axis_sizes = {"data": d, "model": m}
    entry.declared_bytes_by_axis = {"data": 0, "model": 0, "other": 0}
    return entry


def elastic_entries() -> List[IrEntry]:
    """The elastic-training plane's compiled surface (ISSUE 19): the
    restore re-placement identity step on the (2, 4) mesh, hard-floored
    at zero collective bytes on every axis — a restore that compiles to
    gathers would silently turn every resize into a full-state
    re-broadcast."""
    return [elastic_restore_entry((2, 4))]


def build_entries() -> List[IrEntry]:
    """The full IR roster, in deterministic order. Every entry family the
    package registers through watch_compiles/record_aot is represented;
    the self-host gate (tests/test_analysis.py) runs these against the
    `ir_findings` baseline section."""
    entries: List[IrEntry] = []
    entries += nn_entries()
    entries += graph_entries()
    entries += parallel_entries()
    entries.append(zero_accum_entry())
    entries += pp_entries()
    entries += mesh2d_entries()
    entries += flash_entries()
    entries += serving_entries()
    entries += decode_entries()
    entries += elastic_entries()
    return entries
