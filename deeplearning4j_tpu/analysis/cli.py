"""graftlint CLI.

    python -m tools.graftlint deeplearning4j_tpu/            # AST lint vs baseline
    python -m tools.graftlint deeplearning4j_tpu/ --ir       # IR tier (jaxpr/HLO)
    python -m tools.graftlint pkg/ --write-baseline          # accept current
    python -m tools.graftlint pkg/ --metrics                 # Prometheus text
    python -m tools.graftlint --list-rules

Exit codes: 0 = clean against the baseline, 1 = new findings (or stale
baseline entries with --strict-stale), 2 = usage/parse error. The AST
pass is pure stdlib; `--ir` imports jax and abstract-evals the
package's jit entry points on the virtual 8-device mesh (baseline
section `ir_findings` in the same baseline file).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, Optional, Sequence

from .engine import RULES, LintResult, run_lint, write_baseline

DEFAULT_BASELINE = "graftlint_baseline.json"


def _find_baseline(paths: Sequence[str], explicit: Optional[str]
                   ) -> Optional[str]:
    """Explicit path wins; else look for graftlint_baseline.json next to
    the first target, then upward to the filesystem root, then cwd."""
    if explicit:
        return explicit
    start = os.path.abspath(paths[0]) if paths else os.getcwd()
    if os.path.isfile(start):
        start = os.path.dirname(start)
    cur = start
    while True:
        cand = os.path.join(cur, DEFAULT_BASELINE)
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(cur)
        if parent == cur:
            break
        cur = parent
    cand = os.path.join(os.getcwd(), DEFAULT_BASELINE)
    return cand if os.path.exists(cand) else None


def lint_metrics(paths: Sequence[str],
                 baseline: Optional[str] = None) -> Dict:
    """Programmatic entry for bench.py: {'total', 'new', 'by_rule',
    'new_by_rule', 'files', 'wall_s'} for the given targets."""
    t0 = time.perf_counter()
    res = run_lint(paths, baseline_path=_find_baseline(paths, baseline))
    return {
        "total": len(res.findings),
        "new": len(res.new),
        "by_rule": res.by_rule(),
        "new_by_rule": res.new_by_rule(),
        "files": res.files,
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def ir_lint_metrics(paths: Sequence[str] = (),
                    baseline: Optional[str] = None) -> Dict:
    """IR-tier counterpart of `lint_metrics` for bench.py: runs the
    jaxpr/HLO pass over the probe roster (requires jax + the virtual
    mesh) and reports totals plus the measured whole-package IR wall
    time and the watch_compiles roster size."""
    from ..telemetry.compile_watch import roster_names
    from .ir import run_ir_lint
    from .ir_probes import build_entries

    t0 = time.perf_counter()
    entries = build_entries()
    res = run_ir_lint(entries,
                      baseline_path=_find_baseline(list(paths), baseline))
    # count the roster while `entries` still pins the jitted fns alive
    # (the ledger holds weakrefs)
    n_roster = len(roster_names())
    del entries
    return {
        "total": len(res.findings),
        "new": len(res.new),
        "by_rule": res.by_rule(),
        "new_by_rule": res.new_by_rule(),
        "entries": res.files,
        "roster": n_roster,
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def _prometheus(res: LintResult, ir: bool = False) -> str:
    lines = [
        "# HELP dl4j_lint_findings_total graftlint findings by rule "
        "(baselined + new)",
        "# TYPE dl4j_lint_findings_total counter",
    ]
    for rule_id, n in sorted(res.by_rule().items()):
        lines.append(f'dl4j_lint_findings_total{{rule="{rule_id}"}} {n}')
    lines += [
        "# HELP dl4j_lint_new_findings_total graftlint findings not "
        "covered by the baseline",
        "# TYPE dl4j_lint_new_findings_total counter",
    ]
    for rule_id, n in sorted(res.new_by_rule().items()):
        lines.append(
            f'dl4j_lint_new_findings_total{{rule="{rule_id}"}} {n}')
    if ir:
        lines.append("# HELP dl4j_lint_ir_entries_total jit entry points "
                     "abstract-evaled by the IR tier")
        lines.append("# TYPE dl4j_lint_ir_entries_total gauge")
        lines.append(f"dl4j_lint_ir_entries_total {res.files}")
    else:
        lines.append("# HELP dl4j_lint_files_total files linted")
        lines.append("# TYPE dl4j_lint_files_total gauge")
        lines.append(f"dl4j_lint_files_total {res.files}")
    return "\n".join(lines) + "\n"


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="JAX-aware static analysis for deeplearning4j_tpu "
                    "(jit/tracer hygiene, recompilation hazards, donation "
                    "safety, concurrency lint)")
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: nearest "
                         f"{DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding as new")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the baseline")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--ir", action="store_true",
                    help="run the IR tier instead of the AST pass: "
                         "trace/lower/compile the package's jit entry "
                         "points on the virtual 8-device mesh and verify "
                         "shardings, collectives and donation aliasing "
                         "(requires jax; baseline section 'ir_findings')")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--metrics", action="store_true",
                    help="emit Prometheus text "
                         "(dl4j_lint_findings_total{rule=...}) and exit 0")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--strict-stale", action="store_true",
                    help="also fail when baseline entries no longer match "
                         "any finding (keeps the ratchet tight)")
    ap.add_argument("--show-baselined", action="store_true",
                    help="print baselined findings too, not just new ones")
    args = ap.parse_args(argv)

    if args.list_rules:
        # force registration (ir registers rule ids only — no jax import)
        from . import ir  # noqa: F401
        from . import rules_concurrency  # noqa: F401
        from . import rules_jit  # noqa: F401
        for rid, info in sorted(RULES.items()):
            print(f"{rid:26s} [{info.family}] {info.description}")
        return 0
    if not args.paths:
        ap.error("no paths given (try: deeplearning4j_tpu/)")

    if args.write_baseline and args.rules:
        # a filtered run sees only a subset of findings — writing it out
        # would silently erase every other rule's accepted entries
        ap.error("--write-baseline cannot be combined with --rules "
                 "(the baseline must cover ALL rules)")
    baseline_path = None if args.no_baseline else \
        _find_baseline(args.paths, args.baseline)
    rules = [r.strip() for r in args.rules.split(",")] if args.rules \
        else None
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"graftlint: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    t0 = time.perf_counter()
    if args.ir:
        # the IR tier: probe-built jit entry points on the virtual mesh;
        # `paths` only locate the baseline file. Imported lazily so the
        # plain AST CLI keeps working in jax-free environments.
        from .ir import IR_BASELINE_SECTION, run_ir_lint
        try:
            res = run_ir_lint(baseline_path=baseline_path, rules=rules)
        except RuntimeError as e:      # 1-device backend: environment
            print(f"graftlint: {e}", file=sys.stderr)
            return 2
        section = IR_BASELINE_SECTION
        unit = "entries"
    else:
        try:
            res = run_lint(args.paths, baseline_path=baseline_path,
                           rules=rules)
        except SyntaxError as e:
            print(f"graftlint: {e}", file=sys.stderr)
            return 2
        section = "findings"
        unit = "files"
        if res.files == 0:
            print("graftlint: no .py files found under "
                  f"{', '.join(args.paths)}", file=sys.stderr)
            return 2
    wall = time.perf_counter() - t0

    if args.write_baseline:
        path = args.baseline or os.path.join(
            os.getcwd(), DEFAULT_BASELINE) if baseline_path is None \
            else baseline_path
        write_baseline(path, res.findings, section=section)
        print(f"graftlint: wrote {len(res.findings)} finding(s) to {path} "
              f"[{section}]")
        return 0

    if args.metrics:
        sys.stdout.write(_prometheus(res, ir=args.ir))
        return 0

    if args.format == "json":
        print(json.dumps({
            "files": res.files,
            "findings": [vars(f) for f in res.findings],
            "new": [vars(f) for f in res.new],
            "stale_baseline": res.stale_baseline,
            "wall_s": round(wall, 3),
        }, indent=1))
    else:
        shown = res.findings if args.show_baselined else res.new
        for f in shown:
            marker = "" if f in res.new else " (baselined)"
            print(f.render() + marker)
        for k in res.stale_baseline:
            print(f"stale baseline entry (no longer found): {k}")
        summary = (f"graftlint: {res.files} {unit}, "
                   f"{len(res.findings)} finding(s) "
                   f"({len(res.findings) - len(res.new)} baselined, "
                   f"{len(res.new)} new), "
                   f"{len(res.stale_baseline)} stale baseline entr"
                   f"{'y' if len(res.stale_baseline) == 1 else 'ies'} "
                   f"in {wall:.2f}s")
        print(summary)
    if res.new:
        return 1
    if args.strict_stale and res.stale_baseline:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
