"""Runtime sanitizer: the dynamic half of graftlint.

Static rules catch what the AST shows; these shims catch what only shows
at runtime — leaked tracers, NaN-producing steps, threads that outlive
their owner, and lock acquisitions that contradict the declared order.

    with sanitize(thread_watchdog=True, lock_order=True) as report:
        ... run the concurrency-heavy code ...
    # exiting raises ThreadLeakError / LockOrderError on violations

Pieces (each individually optional):

  * `tracer_leaks=True`  — flips `jax_check_tracer_leaks` for the block.
  * `debug_nans=True`    — flips `jax_debug_nans` for the block (leave
    off for suites that INJECT NaNs deliberately, e.g. fault/).
  * `thread_watchdog`    — snapshots live threads on entry; on exit,
    threads started inside the block get `grace_s` to finish, then any
    survivor (name not matching `allow_threads`) raises ThreadLeakError.
    This is the check that keeps "every subsystem joins its workers"
    true as the threaded surface grows.
  * `lock_order`         — wraps the lock attributes of serving's
    known lock-bearing classes (ModelRegistry, InferenceServer entries)
    in order-asserting shims for instances constructed INSIDE the block:
    each thread's held-lock stack is tracked and the global pairwise
    acquisition order must stay consistent; a contradiction is recorded
    and raised at block exit (raising inside a worker thread would just
    kill the worker silently).

Pytest integration (tests/conftest.py): mark a module or test with
`@pytest.mark.sanitize` (kwargs forwarded) and the autouse fixture wraps
the test body in this context manager.
"""
from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["sanitize", "SanitizerReport", "ThreadLeakError",
           "LockOrderError", "OrderCheckedLock", "LockOrderWatch",
           "wrap_lock_attrs", "CollectiveSequenceHasher",
           "current_collective_hasher", "collective_hashes_agree"]


class ThreadLeakError(AssertionError):
    """Threads started inside a sanitized block outlived it."""


class LockOrderError(AssertionError):
    """Two lock acquisitions contradict the established global order."""


@dataclass
class SanitizerReport:
    leaked_threads: List[str] = field(default_factory=list)
    lock_violations: List[str] = field(default_factory=list)
    checked_locks: int = 0
    started_threads: int = 0
    # filled by sanitize(collective_hash=True): one digest per training
    # step observed inside the block, plus the whole-block digest
    collective_step_digests: List[str] = field(default_factory=list)
    collective_digest: str = ""


# ---------------------------------------------------------------------------
# Per-step collective-sequence hash (the runtime half of the IR tier's
# collective-order check — analysis/ir.py owns the static half and the
# shared digest format)
# ---------------------------------------------------------------------------
class CollectiveSequenceHasher:
    """Hashes the sequence of collectives a process ISSUES per training
    step. ParallelTrainer feeds it from the step's static accounting
    (op, logical payload bytes, multiplicity) in issue order; `end_step`
    closes one step's digest. The invariant under test: every process in
    a multi-host mesh must produce the IDENTICAL digest stream — a
    divergence (stale ZeRO plan after an elastic resize, mismatched
    bucket layout, a worker running a different step ordinal) is visible
    in a log line instead of a silent deadlock inside the mismatched
    collective. Item 4's kill/rejoin drills run under this hook via
    `sanitize(collective_hash=True)`."""

    def __init__(self):
        import hashlib
        self._hashlib = hashlib
        self._lock = threading.Lock()
        self._step = hashlib.sha256()
        self._step_len = 0
        self.step_digests: List[str] = []

    def record(self, op: str, nbytes: int, n: int = 1):
        """One collective issue: `op` moving `nbytes` logical payload
        (`n` = multiplicity, e.g. bucket flushes per reduce-scatter)."""
        with self._lock:
            self._step.update(f"{op}:{int(nbytes)}:{int(n)}\0".encode())
            self._step_len += 1

    def end_step(self):
        with self._lock:
            if self._step_len == 0:
                return
            self.step_digests.append(self._step.hexdigest()[:16])
            self._step = self._hashlib.sha256()
            self._step_len = 0

    def digest(self) -> str:
        """Digest of the whole per-step digest stream — the one value
        processes exchange to compare runs."""
        h = self._hashlib.sha256()
        with self._lock:
            for d in self.step_digests:
                h.update(d.encode())
        return h.hexdigest()[:16]


_collective_hasher: Optional[CollectiveSequenceHasher] = None


def current_collective_hasher() -> Optional[CollectiveSequenceHasher]:
    return _collective_hasher


def collective_hashes_agree(hasher: CollectiveSequenceHasher) -> bool:
    """Multi-process agreement check: allgather every process's stream
    digest and compare. True on a single process. Safe to call from all
    processes simultaneously (it IS a collective)."""
    import jax

    if jax.process_count() <= 1:
        return True
    from jax.experimental import multihost_utils as mhu
    import numpy as np

    mine = int(hasher.digest(), 16) % (2 ** 63)
    got = np.asarray(mhu.process_allgather(np.asarray([mine])))
    return bool((got == got.flat[0]).all())


# ---------------------------------------------------------------------------
# Order-asserting lock shim
# ---------------------------------------------------------------------------
class LockOrderWatch:
    """Shared order registry for a family of OrderCheckedLocks: records
    (held -> acquired) pairs and flags the first contradiction. Lock
    identity is the NAME given at wrap time (class-level, matching the
    static analyzer's granularity)."""

    def __init__(self):
        self._meta = threading.Lock()
        self._order: Dict[Tuple[str, str], str] = {}   # (a, b) -> where
        self._held = threading.local()
        self.violations: List[str] = []
        self.wrapped = 0

    def _stack(self) -> List[str]:
        st = getattr(self._held, "stack", None)
        if st is None:
            st = self._held.stack = []
        return st

    def on_acquire(self, name: str):
        stack = self._stack()
        if name in stack:          # reentrant (RLock) — no new edge
            stack.append(name)
            return
        where = threading.current_thread().name
        with self._meta:
            for held in stack:
                if held == name:
                    continue
                if (name, held) in self._order:
                    self.violations.append(
                        f"lock order violation: acquiring '{name}' while "
                        f"holding '{held}' (thread {where}), but the "
                        f"opposite order was established at "
                        f"{self._order[(name, held)]}")
                self._order.setdefault((held, name), where)
        stack.append(name)

    def on_release(self, name: str):
        stack = self._stack()
        if name in stack:
            # remove the most recent acquisition of this name
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == name:
                    del stack[i]
                    break


class OrderCheckedLock:
    """Duck-typed Lock/RLock proxy feeding a LockOrderWatch. Supports
    the subset of the lock API the codebase uses (context manager,
    acquire/release, locked)."""

    def __init__(self, inner, name: str, watch: LockOrderWatch):
        self._inner = inner
        self._name = name
        self._watch = watch
        watch.wrapped += 1

    def acquire(self, *a, **kw):
        got = self._inner.acquire(*a, **kw)
        if got:
            self._watch.on_acquire(self._name)
        return got

    def release(self):
        self._inner.release()
        self._watch.on_release(self._name)

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def wrap_lock_attrs(obj, watch: LockOrderWatch,
                    attrs: Optional[Sequence[str]] = None,
                    prefix: Optional[str] = None) -> int:
    """Replace `obj`'s lock-valued attributes with order-checked proxies
    (auto-discovered when `attrs` is None). Returns the wrap count."""
    lock_types = tuple({type(threading.Lock()), type(threading.RLock())})
    if attrs is not None:
        names = attrs
    else:
        try:
            candidates = list(vars(obj))
        except TypeError:       # __slots__ class (serving._Entry)
            candidates = [s for klass in type(obj).__mro__
                          for s in getattr(klass, "__slots__", ())]
        names = [k for k in candidates
                 if isinstance(getattr(obj, k, None), lock_types)]
    prefix = prefix or type(obj).__name__
    n = 0
    for k in names:
        v = getattr(obj, k, None)
        if v is None or isinstance(v, OrderCheckedLock):
            continue
        setattr(obj, k, OrderCheckedLock(v, f"{prefix}.{k}", watch))
        n += 1
    return n


# classes whose instances get their locks auto-wrapped when constructed
# inside a sanitize(lock_order=True) block: the serving plane's
# lock-bearing objects (the lint's lock-order pass covers the same set)
def _lock_order_patch_points():
    from ..serving.batcher import DynamicBatcher
    from ..serving.registry import ModelRegistry, _Entry
    from ..serving.server import InferenceServer
    return [(ModelRegistry, None), (_Entry, None),
            (InferenceServer, None), (DynamicBatcher, None)]


@contextlib.contextmanager
def _patched_lock_order(watch: LockOrderWatch):
    patched = []
    try:
        points = _lock_order_patch_points()
    except Exception:       # serving unavailable (minimal env) — no-op
        points = []
    for cls, attrs in points:
        orig = cls.__init__

        def make(orig, cls, attrs):
            def __init__(self, *a, **kw):
                orig(self, *a, **kw)
                wrap_lock_attrs(self, watch, attrs)
            return __init__

        cls.__init__ = make(orig, cls, attrs)
        patched.append((cls, orig))
    try:
        yield
    finally:
        for cls, orig in patched:
            cls.__init__ = orig


# ---------------------------------------------------------------------------
# Thread-leak watchdog
# ---------------------------------------------------------------------------
_DEFAULT_ALLOW = (
    "pydevd", "IPython", "pytest-",        # tooling
    "ThreadPoolExecutor",                  # jax internal pools
    "jax_",
)


def _thread_leaks(before: set, grace_s: float,
                  allow: Sequence[str]) -> List[str]:
    deadline = time.monotonic() + grace_s
    while True:
        new = [t for t in threading.enumerate()
               if t not in before and t.is_alive()
               and not any(p in (t.name or "") for p in allow)]
        if not new or time.monotonic() >= deadline:
            return [f"{t.name} (daemon={t.daemon})" for t in new]
        for t in new:
            t.join(timeout=max(0.01, deadline - time.monotonic()))


# ---------------------------------------------------------------------------
# The context manager
# ---------------------------------------------------------------------------
@contextlib.contextmanager
def sanitize(tracer_leaks: bool = False, debug_nans: bool = False,
             thread_watchdog: bool = True, lock_order: bool = True,
             collective_hash: bool = False,
             grace_s: float = 5.0,
             allow_threads: Sequence[str] = (),
             raise_on_violation: bool = True):
    """Run a block under the runtime sanitizers; yields a
    SanitizerReport filled in at exit. `allow_threads` name-substrings
    are ADDED to the built-in allowlist (tooling/jax pools) — use it for
    threads owned by longer-lived fixtures that legitimately outlive one
    sanitized block. `collective_hash=True` installs the per-step
    collective-sequence hasher (see CollectiveSequenceHasher); the
    report carries the per-step digests at exit. See module docstring."""
    global _collective_hasher
    report = SanitizerReport()
    allow_threads = tuple(_DEFAULT_ALLOW) + tuple(allow_threads)
    hasher = prev_hasher = None
    if collective_hash:
        hasher = CollectiveSequenceHasher()
        prev_hasher = _collective_hasher
        _collective_hasher = hasher
    jax_restore = []
    if tracer_leaks or debug_nans:
        import jax
        for flag, on in (("jax_check_tracer_leaks", tracer_leaks),
                         ("jax_debug_nans", debug_nans)):
            if on:
                jax_restore.append((flag, bool(getattr(jax.config, flag))))
                jax.config.update(flag, True)
    before = set(threading.enumerate()) if thread_watchdog else set()
    watch = LockOrderWatch() if lock_order else None
    ctx = _patched_lock_order(watch) if lock_order \
        else contextlib.nullcontext()
    try:
        with ctx:
            yield report
    finally:
        if hasher is not None:
            _collective_hasher = prev_hasher
            report.collective_step_digests = list(hasher.step_digests)
            report.collective_digest = hasher.digest()
        if jax_restore:
            import jax
            for flag, old in jax_restore:
                jax.config.update(flag, old)
        if thread_watchdog:
            report.started_threads = sum(
                1 for t in threading.enumerate() if t not in before)
            report.leaked_threads = _thread_leaks(before, grace_s,
                                                  allow_threads)
        if watch is not None:
            report.checked_locks = watch.wrapped
            report.lock_violations = list(watch.violations)
    if raise_on_violation:
        if report.lock_violations:
            raise LockOrderError("; ".join(report.lock_violations))
        if report.leaked_threads:
            raise ThreadLeakError(
                "threads leaked past the sanitized block (grace "
                f"{grace_s:.1f}s): {', '.join(report.leaked_threads)} — "
                "every subsystem must join/close its workers "
                "(close()/stop()/shutdown())")
