"""graftlint: JAX-aware static analysis + runtime sanitizers.

The codebase's recurring review-fix classes — host syncs and retrace
hazards inside jit-reachable code, donated-buffer reuse, and
lock/lifecycle races in the threaded subsystems — are all statically
visible in the AST. This package turns them into enforced rules:

  * `engine`      — findings, pragma suppression, baseline workflow, the
                    lint runner (`run_lint`).
  * `callgraph`   — package-wide call graph; discovers `jit`/`pjit`/
                    `shard_map`/`scan` entry points and the set of
                    functions reachable from a trace.
  * `rules_jit`   — jit/tracer hygiene, recompilation hazards, donation
                    safety (families JH/RC/DN).
  * `rules_concurrency` — threaded-state and lock discipline (family CC).
  * `ir` / `ir_probes` — the IR tier (ISSUE 13): abstract-eval the jit
                    entry points on the virtual 8-device mesh and verify
                    shard layouts, collective schedules and donation
                    aliasing in the jaxpr/lowered/compiled artifacts.
  * `sanitizer`   — the runtime side: tracer-leak/debug-nans config,
                    thread-leak watchdog, order-asserting lock shims,
                    per-step collective-sequence hashing, exposed to
                    tests via the `sanitize` pytest marker.

CLI: `python -m tools.graftlint deeplearning4j_tpu/` (AST pass, pure
stdlib) and `... --ir` (IR tier; see `analysis.cli`). Suppression:
`# graftlint: disable=<rule>[,<rule>...]` on the offending line,
`# graftlint: disable-file=<rule>` anywhere in a file; accepted findings
live in `graftlint_baseline.json` (sections `findings` / `ir_findings`).
"""
from .engine import (Finding, LintResult, Project, RULES, load_baseline,
                     run_lint, write_baseline)
from .sanitizer import (CollectiveSequenceHasher, LockOrderError,
                        SanitizerReport, ThreadLeakError, sanitize)

__all__ = ["Finding", "LintResult", "Project", "RULES", "run_lint",
           "load_baseline", "write_baseline", "sanitize", "SanitizerReport",
           "ThreadLeakError", "LockOrderError", "CollectiveSequenceHasher"]
