"""Rule families JH (jit/tracer hygiene), RC (recompilation hazards),
DN (donation safety).

Everything here keys off the call graph's traced set: the functions that
execute under a JAX trace (jit/pjit/shard_map/lax control flow callees
and everything they reach). Host-side code is free to call `float()` or
`np.asarray`; traced code is not — there it either crashes
(ConcretizationTypeError), silently constant-folds trace-time state
(wall clocks, Python RNG), or forces a device sync per step.

Taint model: inside a traced function every parameter is a potential
tracer EXCEPT parameters declared static at the jit site
(`static_argnums`/`static_argnames` are propagated onto the direct
callee). Attribute reads of `.shape`/`.ndim`/`.dtype` and `is None` /
`isinstance` tests are shields — those are static under trace and
branching on them is fine (rank/None specialization), while branching
on the VALUES is not.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .callgraph import attr_chain, walk_shallow
from .engine import Finding, Project, register_rule_id, rule

_SHIELD_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                 "weak_type", "itemsize", "nbytes"}
_WALLCLOCK = {"time.time", "time.perf_counter", "time.monotonic",
              "time.time_ns", "time.perf_counter_ns", "time.process_time"}
_PY_RNG_PREFIX = ("random.", "np.random.", "numpy.random.")
_NP_PREFIX = ("np.", "numpy.", "onp.")
_HOT_HOOKS = {"iteration_done"}


# ---------------------------------------------------------------------------
# Taint helpers
# ---------------------------------------------------------------------------
# calls whose results are (pytrees of) traced arrays inside traced code
_ARRAY_CALL_PREFIXES = ("jnp.", "jax.numpy.", "jax.lax.", "lax.",
                        "jax.nn.", "jax.random.", "jax.scipy.",
                        "jax.vjp", "jax.jvp", "jax.grad",
                        "jax.value_and_grad")


def _collect_taint(info, cg=None) -> Set[str]:
    """Names bound to ARRAY-DERIVED values: locals assigned from
    jnp./jax.lax./jax.random. calls (or from calls into other traced
    package functions), propagated through assignments in document
    order.

    Parameters are deliberately NOT seeded: in this codebase traced
    functions routinely thread static config (train flags, activation
    names, enum modes, partial-bound scalars) through their signatures,
    and branching on those at trace time is idiomatic JAX — seeding
    params flagged ~30 such branches and zero real ones. Branching on a
    traced param also fails loudly on the very first trace, while
    branching on a derived value can hide in a rarely-taken path; the
    derived set is where a linter earns its keep."""
    taint: Set[str] = set()
    body = info.node.body if not isinstance(info.node, ast.Lambda) \
        else [info.node.body]

    def arrayish(expr) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                chain = attr_chain(n.func)
                if chain and (chain.startswith(_ARRAY_CALL_PREFIXES)
                              or chain in ("jnp", "lax")):
                    return True
                if cg is not None:
                    q = cg.resolve_call_target(info.sf, [info.node],
                                               info.class_name, n.func)
                    if q is not None and q in cg.traced:
                        return True
        return _expr_tainted(expr, taint)

    # document order matters (walk_shallow yields a stack order): sort
    # binding sites by position, then run TWO forward passes so values
    # flowing backward through a loop body still land
    sites = sorted(
        (n for n in walk_shallow(body)
         if isinstance(n, (ast.Assign, ast.AugAssign, ast.For))),
        key=lambda n: (n.lineno, n.col_offset))
    for _ in range(2):
        before = len(taint)
        for node in sites:
            if isinstance(node, ast.Assign):
                if arrayish(node.value):
                    for t in node.targets:
                        taint.update(_target_names(t))
            elif isinstance(node, ast.AugAssign):
                if arrayish(node.value) and isinstance(node.target,
                                                       ast.Name):
                    taint.add(node.target.id)
            elif isinstance(node, ast.For):
                if _expr_tainted(node.iter, taint):
                    taint.update(_target_names(node.target))
        if len(taint) == before:
            break
    return taint


def _target_names(t: ast.AST):
    """Names BOUND by an assignment target. A subscript store taints the
    container, never the index expression (`values[name] = ...` must not
    taint `name`); attribute stores bind no local name."""
    if isinstance(t, ast.Name):
        yield t.id
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _target_names(e)
    elif isinstance(t, ast.Starred):
        yield from _target_names(t.value)
    elif isinstance(t, ast.Subscript):
        yield from _target_names(t.value)


def _expr_tainted(expr: Optional[ast.AST], taint: Set[str]) -> bool:
    """Does `expr` read a tainted name OUTSIDE a shield context?"""
    if expr is None or not taint:
        return False
    return _scan_taint(expr, taint)


def _scan_taint(node: ast.AST, taint: Set[str]) -> bool:
    if isinstance(node, ast.Attribute) and node.attr in _SHIELD_ATTRS:
        return False                      # x.shape / x.ndim / x.dtype
    if isinstance(node, ast.Call):
        chain = attr_chain(node.func)
        if chain in ("len", "isinstance", "getattr", "hasattr", "type"):
            return False                  # len(x) is shape-derived/static
    if isinstance(node, ast.Compare):
        # `x is None` / `x is not None` — None-ness is static under trace
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False
    if isinstance(node, ast.Name):
        return node.id in taint
    return any(_scan_taint(c, taint) for c in ast.iter_child_nodes(node))


def _receiver_chain(node: ast.AST) -> Optional[str]:
    return attr_chain(node)


# ---------------------------------------------------------------------------
# Parent-tracked walker (rules need ancestor context: loops, guards)
# ---------------------------------------------------------------------------
class _Ancestry:
    """node id -> parent map, per function body (shallow)."""

    def __init__(self, body):
        self.parent: Dict[int, ast.AST] = {}
        stack = list(body) if isinstance(body, (list, tuple)) else [body]
        while stack:
            node = stack.pop()
            if not isinstance(node, ast.AST):
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue
            for child in ast.iter_child_nodes(node):
                self.parent[id(child)] = node
                stack.append(child)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent.get(id(node))
        while cur is not None:
            yield cur
            cur = self.parent.get(id(cur))

    def in_loop(self, node: ast.AST) -> bool:
        return any(isinstance(a, (ast.For, ast.While))
                   for a in self.ancestors(node))

    def under_if(self, node: ast.AST) -> bool:
        return any(isinstance(a, (ast.If, ast.IfExp))
                   for a in self.ancestors(node))


# ---------------------------------------------------------------------------
# JH: jit/tracer hygiene
# ---------------------------------------------------------------------------
register_rule_id("print-in-trace", "jit-hygiene",
                 "print() inside trace-reachable code runs at trace time "
                 "only (or forces a host sync via io callbacks)")
register_rule_id("wallclock-in-trace", "jit-hygiene",
                 "wall-clock read inside trace-reachable code is "
                 "constant-folded at trace time")
register_rule_id("python-rng-in-trace", "jit-hygiene",
                 "Python/numpy RNG inside trace-reachable code freezes "
                 "one sample into the compiled program")
register_rule_id("traced-value-branch", "jit-hygiene",
                 "Python branch on a traced value raises "
                 "TracerBoolConversionError (or silently specializes)")


@rule("host-sync-in-trace", "jit-hygiene",
      "float()/int()/.item()/np.asarray on a traced value forces a "
      "device->host sync (or ConcretizationTypeError) inside jitted code")
def check_trace_hygiene(project: Project):
    cg = project.callgraph
    out: List[Finding] = []
    for qual in sorted(cg.traced):
        info = cg.funcs[qual]
        sf = info.sf
        taint = _collect_taint(info, cg)
        body = info.node.body if not isinstance(info.node, ast.Lambda) \
            else [info.node.body]
        for node in walk_shallow(body):
            if isinstance(node, ast.Call):
                out.extend(_check_traced_call(project, sf, qual, node, taint))
            elif isinstance(node, (ast.If, ast.While, ast.IfExp)):
                if _expr_tainted(node.test, taint):
                    out.append(project.finding(
                        sf, "traced-value-branch", node,
                        "branch condition reads a traced value — under "
                        "jit this raises TracerBoolConversionError; hoist "
                        "the branch or use lax.cond/jnp.where", scope=qual))
            elif isinstance(node, ast.Assert):
                if _expr_tainted(node.test, taint):
                    out.append(project.finding(
                        sf, "traced-value-branch", node,
                        "assert on a traced value — use "
                        "checkify/debug_nans instead", scope=qual))
    return [f for f in out if f is not None]


def _check_traced_call(project, sf, qual, node: ast.Call, taint
                       ) -> List[Finding]:
    out: List[Finding] = []
    chain = attr_chain(node.func)

    def emit(rule_id, msg):
        f = project.finding(sf, rule_id, node, msg, scope=qual)
        if f is not None:
            out.append(f)

    if chain == "print":
        emit("print-in-trace",
             "print() under trace runs once at trace time — use "
             "jax.debug.print for per-step output")
    elif chain in _WALLCLOCK:
        emit("wallclock-in-trace",
             f"{chain}() under trace is evaluated once at trace time and "
             "baked into the compiled program")
    elif chain and chain.startswith(_PY_RNG_PREFIX):
        emit("python-rng-in-trace",
             f"{chain}() under trace freezes one host RNG draw into the "
             "compiled program — thread a jax.random key instead")
    elif chain in ("float", "int", "bool", "complex"):
        if node.args and _expr_tainted(node.args[0], taint):
            emit("host-sync-in-trace",
                 f"{chain}() on a traced value — raises "
                 "ConcretizationTypeError under jit; keep it an array")
    elif isinstance(node.func, ast.Attribute) and \
            node.func.attr in ("item", "tolist"):
        if _expr_tainted(node.func.value, taint):
            emit("host-sync-in-trace",
                 f".{node.func.attr}() on a traced value — host "
                 "materialization inside jitted code")
    elif chain and chain.startswith(_NP_PREFIX) and \
            not chain.startswith(_PY_RNG_PREFIX):
        if any(_expr_tainted(a, taint) for a in node.args):
            emit("host-sync-in-trace",
                 f"{chain}() on a traced value inside jitted code — "
                 "numpy materializes on host; use jnp")
    elif chain in ("jax.device_get",) or (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "block_until_ready"):
        emit("host-sync-in-trace",
             "explicit device sync inside trace-reachable code")
    return out


@rule("hot-loop-sync", "jit-hygiene",
      "unconditional host materialization (float(score)/.item()/"
      "np.asarray) in a per-iteration listener hook serializes the "
      "async dispatch pipeline every training step")
def check_hot_loop_sync(project: Project):
    """Codebase-tuned: `iteration_done(model, iteration)` runs after
    EVERY training step. The step's score is an unmaterialized device
    value precisely so dispatch stays async; a listener that converts it
    per call re-introduces a per-step device->host sync. Guarded reads
    (inside any `if`, or after an early-return frequency gate) are the
    sanctioned pattern and stay quiet."""
    out: List[Finding] = []
    cg = project.callgraph
    for qual, info in sorted(cg.funcs.items()):
        if info.name not in _HOT_HOOKS or isinstance(info.node, ast.Lambda):
            continue
        anc = _Ancestry(info.node.body)
        has_gate = any(
            isinstance(stmt, ast.If)
            and any(isinstance(s, (ast.Return, ast.Continue))
                    for s in stmt.body)
            for stmt in info.node.body)
        if has_gate:
            continue
        for node in walk_shallow(info.node.body):
            if not isinstance(node, ast.Call) or anc.under_if(node):
                continue
            chain = attr_chain(node.func)
            sync = None
            if chain in ("float", "int") and node.args and any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    for n in ast.walk(node.args[0])):
                # float(model.score()) materializes a device value;
                # float(getattr(model, ...)) and friends do not
                sync = f"{chain}(...)"
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("item", "tolist",
                                       "block_until_ready"):
                sync = f".{node.func.attr}()"
            elif chain and chain.startswith(_NP_PREFIX) and \
                    chain.rsplit(".", 1)[-1] in ("asarray", "array"):
                sync = chain
            if sync:
                f = project.finding(
                    info.sf, "hot-loop-sync", node,
                    f"{sync} runs unguarded on every iteration_done — "
                    "gate it on a reporting interval (iteration % N) so "
                    "the hot loop stays sync-free", scope=qual)
                if f is not None:
                    out.append(f)
    return out


# ---------------------------------------------------------------------------
# RC: recompilation hazards
# ---------------------------------------------------------------------------
register_rule_id("unhashable-static-arg", "recompile",
                 "unhashable literal passed in a static jit position "
                 "raises at call time")
register_rule_id("shape-branch-in-trace", "recompile",
                 "shape compared against a runtime variable in traced "
                 "code specializes the compile per shape")
register_rule_id("unwatched-jit-entry", "recompile",
                 "jit entry point not covered by telemetry "
                 "watch_compiles — recompilation storms here are "
                 "invisible to CompileWatcher")


@rule("jit-in-loop", "recompile",
      "jax.jit constructed inside a loop builds a fresh cache per "
      "iteration — every call recompiles")
def check_recompile(project: Project):
    out: List[Finding] = []
    cg = project.callgraph
    # RC001: jit construction inside loops + RC-unwatched cross-check
    watched_calls = _watch_wrapped_calls(project)
    for site in cg.jit_sites:
        info = cg.funcs.get(site.scope)
        if info is None:
            continue
        anc = _Ancestry(info.node.body
                        if not isinstance(info.node, ast.Lambda)
                        else [info.node.body])
        if anc.in_loop(site.node):
            f = project.finding(
                site.sf, "jit-in-loop", site.node,
                "jit constructed inside a loop: each iteration builds a "
                "fresh jitted callable with an empty cache — hoist the "
                "jit out of the loop", scope=site.scope)
            if f is not None:
                out.append(f)
        if id(site.node) not in watched_calls:
            f = project.finding(
                site.sf, "unwatched-jit-entry", site.node,
                "jit entry point is not wrapped in telemetry "
                "watch_compiles(...) — CompileWatcher cannot attribute "
                "recompilation storms to it", scope=site.scope)
            if f is not None:
                out.append(f)
    # RC002: unhashable literals at static positions of known jit bindings
    out.extend(_check_static_args(project))
    # RC003: shape-vs-variable comparisons in traced code
    for qual in sorted(cg.traced):
        info = cg.funcs[qual]
        body = info.node.body if not isinstance(info.node, ast.Lambda) \
            else [info.node.body]
        for node in walk_shallow(body):
            if isinstance(node, (ast.If, ast.While)) and \
                    _shape_vs_variable(node.test):
                f = project.finding(
                    info.sf, "shape-branch-in-trace", node,
                    "shape compared against a runtime variable inside "
                    "traced code — every distinct value compiles its own "
                    "program (unbounded specialization)", scope=qual)
                if f is not None:
                    out.append(f)
    return out


def _watch_wrapped_calls(project) -> Set[int]:
    """ids of jit Call nodes that appear as an argument (at any depth
    inside the argument expression) of a watch_compiles(...) call, or in
    a module that wires compiles into the watcher another way
    (serving/registry records AOT compiles via record_aot)."""
    wrapped: Set[int] = set()
    for sf in project.files:
        # a module only counts as AOT-covered if it actually CALLS
        # record_aot (a comment/docstring mention must not bypass the
        # gate)
        records_aot = any(
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "record_aot"
            for n in ast.walk(sf.tree))
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            tail = chain.rsplit(".", 1)[-1] if chain else None
            if tail == "watch_compiles" and node.args:
                for sub in ast.walk(node.args[0]):
                    if isinstance(sub, ast.Call):
                        wrapped.add(id(sub))
            elif records_aot:
                # module-local AOT accounting covers its own jit sites
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        sub_chain = attr_chain(sub.func)
                        if sub_chain and sub_chain.rsplit(".", 1)[-1] in (
                                "jit", "pjit"):
                            wrapped.add(id(sub))
    return wrapped


def _shape_vs_variable(test: ast.AST) -> bool:
    """`x.shape[0] < n` / `len(x) != budget` — shape against a
    non-constant. Shape-vs-literal (`x.ndim == 3`) is bounded rank/shape
    specialization and stays quiet."""
    for node in ast.walk(test):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left] + list(node.comparators)
        shapeish = [s for s in sides if _is_shape_expr(s)]
        if not shapeish:
            continue
        others = [s for s in sides if not _is_shape_expr(s)]
        if others and not all(_is_const_like(o) for o in others):
            return True
    return False


def _is_shape_expr(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in ("shape", "ndim"):
            return True
        if isinstance(n, ast.Call) and attr_chain(n.func) == "len":
            return True
    return False


def _is_const_like(node: ast.AST) -> bool:
    return all(isinstance(n, (ast.Constant, ast.UnaryOp, ast.Tuple,
                              ast.List, ast.expr_context, ast.unaryop))
               for n in ast.walk(node))


def _check_static_args(project) -> List[Finding]:
    out: List[Finding] = []
    bindings = _jit_bindings(project)
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_binding_name(node.func)
            if name is None or name not in bindings:
                continue
            site = bindings[name]
            for i in site.static_argnums:
                if i < len(node.args) and isinstance(
                        node.args[i], (ast.List, ast.Dict, ast.Set)):
                    f = project.finding(
                        sf, "unhashable-static-arg", node.args[i],
                        f"static arg {i} of '{name}' receives an "
                        "unhashable literal — jit static args must be "
                        "hashable (pass a tuple)", scope="")
                    if f is not None:
                        out.append(f)
            for kw in node.keywords:
                if kw.arg in site.static_argnames and isinstance(
                        kw.value, (ast.List, ast.Dict, ast.Set)):
                    f = project.finding(
                        sf, "unhashable-static-arg", kw.value,
                        f"static arg '{kw.arg}' of '{name}' receives an "
                        "unhashable literal — pass a hashable value",
                        scope="")
                    if f is not None:
                        out.append(f)
    return out


# ---------------------------------------------------------------------------
# DN: donation safety
# ---------------------------------------------------------------------------
def _jit_bindings(project) -> Dict[str, "object"]:
    """binding name -> JitSite for jit results bound to a name: plain
    assignment (`f = jax.jit(...)`), attribute assignment
    (`self._step = jax.jit(...)`), or returned from a method/
    cached_property (binding = the method name)."""
    cg = project.callgraph
    by_call: Dict[int, object] = {id(s.node): s for s in cg.jit_sites}
    bindings: Dict[str, object] = {}
    for sf in project.files:
        for node in ast.walk(sf.tree):
            target: Optional[str] = None
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    target = t.id
                elif isinstance(t, ast.Attribute):
                    target = t.attr
                value = node.value
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                rets = [n for n in walk_shallow(node.body)
                        if isinstance(n, ast.Return) and n.value is not None]
                if len(rets) == 1:
                    target, value = node.name, rets[0].value
            if target is None or value is None:
                continue
            for sub in ast.walk(value):
                site = by_call.get(id(sub))
                if site is not None:
                    site.binding = target
                    bindings[target] = site
    return bindings


@rule("donated-buffer-reuse", "donation",
      "a binding passed in a donate_argnums position is read after the "
      "call — its buffer may already be aliased/invalidated")
def check_donation(project: Project):
    out: List[Finding] = []
    bindings = {n: s for n, s in _jit_bindings(project).items()
                if s.donate or s.donate_names}
    if not bindings:
        return out
    cg = project.callgraph
    for qual, info in sorted(cg.funcs.items()):
        if isinstance(info.node, ast.Lambda):
            continue
        body = info.node.body
        stmts = _linear_stmts(body)
        anc = _Ancestry(body)
        for si, stmt in enumerate(stmts):
            # only this statement's OWN expressions: a call nested in a
            # compound statement's body belongs to the inner statement
            # (whose assignment targets decide the rebinding check)
            own = [c for c in ast.iter_child_nodes(stmt)
                   if isinstance(c, ast.expr)]
            for call in walk_shallow(own):
                if not isinstance(call, ast.Call):
                    continue
                name = _call_binding_name(call.func)
                if name is None or name not in bindings:
                    continue
                site = bindings[name]
                donated = _donated_arg_exprs(call, site)
                if not donated:
                    continue
                rebound = _stmt_targets(stmt)
                for dchain in donated:
                    if dchain in rebound:
                        continue          # x, ... = f(x, ...) — safe
                    if anc.in_loop(call):
                        # loop carry: the same un-rebound binding is
                        # passed (and thus read) again next iteration
                        f = project.finding(
                            info.sf, "donated-buffer-reuse", call,
                            f"'{dchain}' is donated to '{name}' inside "
                            "a loop without being rebound from the "
                            "result — the next iteration reads a "
                            "donated buffer", scope=qual)
                        if f is not None:
                            out.append(f)
                        continue
                    misuse = _read_after(stmts, si, dchain, call)
                    if misuse is not None:
                        f = project.finding(
                            info.sf, "donated-buffer-reuse", misuse,
                            f"'{dchain}' was donated to '{name}' above "
                            "(donate_argnums) and is read again — the "
                            "buffer may have been invalidated; rebind "
                            "the result or drop the donation",
                            scope=qual)
                        if f is not None:
                            out.append(f)
    return out


def _call_binding_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _donated_arg_exprs(call: ast.Call, site) -> List[str]:
    out = []
    for i in site.donate:
        if i < len(call.args):
            chain = attr_chain(call.args[i])
            if chain:
                out.append(chain)
    for kw in call.keywords:
        if kw.arg in site.donate_names:
            chain = attr_chain(kw.value)
            if chain:
                out.append(chain)
    return out


def _stmt_targets(stmt: ast.AST) -> Set[str]:
    out: Set[str] = set()
    targets: Sequence[ast.AST] = ()
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign, ast.For)):
        targets = (stmt.target,)
    for t in targets:
        for n in ast.walk(t):
            chain = attr_chain(n)
            if chain:
                out.add(chain)
    return out


def _linear_stmts(body) -> List[ast.AST]:
    """Statements in document order, flattened through compound
    statements but not into nested defs."""
    out: List[ast.AST] = []

    def rec(stmts):
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            out.append(s)
            for field in ("body", "orelse", "finalbody"):
                rec(getattr(s, field, []) or [])
            for h in getattr(s, "handlers", []) or []:
                rec(h.body)
    rec(body)
    return out


def _read_after(stmts: List[ast.AST], call_idx: int, chain: str,
                call: ast.Call) -> Optional[ast.AST]:
    """First Load of `chain` after the donating call before any
    rebinding; linear over the flattened statement list."""
    for stmt in stmts[call_idx + 1:]:
        if chain in _stmt_targets(stmt):
            # value side may still read it first (x = g(x)): a read of a
            # donated buffer even here — but rebinding from the donated
            # value is the dominant safe idiom; treat as rebind
            return None
        for n in walk_shallow([stmt]):
            if isinstance(n, (ast.Name, ast.Attribute)) and \
                    attr_chain(n) == chain and \
                    isinstance(getattr(n, "ctx", None), ast.Load):
                return stmt
    return None
