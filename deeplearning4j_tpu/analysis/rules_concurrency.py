"""Rule family CC: concurrency lint for the threaded subsystems.

Three passes over the same lock model:

  * **Lock discovery** — `self.X = threading.Lock()/RLock()/Condition()`
    declares lock identity `(module, Class, X)`; module-level
    `X = threading.Lock()` declares `(module, None, X)`. Identities are
    class-level (all instances of `ModelRegistry._lock` are one node in
    the order graph), the standard coarsening for static deadlock
    analysis.
  * **blocking-call-under-lock** — inside a `with <lock>:` block, flag
    calls that can block indefinitely or for unbounded time: sleeps,
    thread joins, event waits, bare queue gets, network/subprocess I/O,
    device syncs — directly or transitively through package-local calls
    (`offset_ms -> _refresh -> socket.create_connection` is one hop).
    Every other thread touching that lock stalls behind the slow holder.
  * **lock-order-cycle** — acquisition-order edges are extracted from
    `with` nesting plus one level of interprocedural propagation (a call
    made while holding L contributes L -> every lock its callee may
    acquire, transitively). A cycle in that graph is a potential
    deadlock interleaving.

`unlocked-global-mutation` flags in-place mutation of module-level
mutable containers from thread-reachable code outside any lock;
rebinding a module global (`_active = session`) is GIL-atomic and stays
quiet, as do `threading.local()` instances.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import attr_chain, walk_shallow
from .engine import Finding, Project, register_rule_id, rule

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_MUTABLE_CTORS = {"list", "dict", "set", "deque", "defaultdict",
                  "OrderedDict", "Counter"}
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "add", "update", "setdefault", "pop", "popleft", "popitem",
             "remove", "discard", "clear", "__setitem__"}

LockId = Tuple[str, Optional[str], str]     # (module, class or None, attr)


# ---------------------------------------------------------------------------
# Lock model
# ---------------------------------------------------------------------------
class LockModel:
    def __init__(self, project: Project):
        self.project = project
        self.class_locks: Dict[Tuple[str, str], Set[str]] = {}
        self.module_locks: Dict[str, Set[str]] = {}
        self.attr_owners: Dict[str, List[LockId]] = {}
        self._discover()

    def _discover(self):
        for sf in self.project.files:
            for cls, node in _classes(sf.tree):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and \
                            _is_lock_ctor(sub.value):
                        for t in sub.targets:
                            if isinstance(t, ast.Attribute) and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id == "self":
                                self._add((sf.module, cls, t.attr))
            for stmt in sf.tree.body:
                if isinstance(stmt, ast.Assign) and \
                        _is_lock_ctor(stmt.value):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self._add((sf.module, None, t.id))

    def _add(self, lid: LockId):
        module, cls, attr = lid
        if cls is not None:
            self.class_locks.setdefault((module, cls), set()).add(attr)
        else:
            self.module_locks.setdefault(module, set()).add(attr)
        self.attr_owners.setdefault(attr, []).append(lid)

    def resolve(self, expr: ast.AST, module: str,
                class_name: Optional[str]) -> Optional[LockId]:
        """Lock identity of a with-item / receiver expression."""
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks.get(module, set()):
                return (module, None, expr.id)
            owners = self.attr_owners.get(expr.id, [])
            return owners[0] if len(owners) == 1 else None
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            if isinstance(expr.value, ast.Name) and \
                    expr.value.id in ("self", "cls") and class_name:
                if attr in self.class_locks.get((module, class_name),
                                                set()):
                    return (module, class_name, attr)
            owners = self.attr_owners.get(attr, [])
            if len(owners) == 1:
                return owners[0]
        return None


def _classes(tree) -> List[Tuple[str, ast.ClassDef]]:
    return [(n.name, n) for n in ast.walk(tree)
            if isinstance(n, ast.ClassDef)]


def _is_lock_ctor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    chain = attr_chain(value.func)
    return bool(chain) and chain.rsplit(".", 1)[-1] in _LOCK_CTORS


# ---------------------------------------------------------------------------
# Blocking-call classification
# ---------------------------------------------------------------------------
_NET_PREFIXES = ("urllib.", "requests.", "http.client", "socket.")
_SUBPROC = {"subprocess.run", "subprocess.check_call",
            "subprocess.check_output", "subprocess.call"}


def _direct_block_reason(node: ast.Call) -> Optional[str]:
    chain = attr_chain(node.func)
    tail = node.func.attr if isinstance(node.func, ast.Attribute) else chain
    if chain == "time.sleep":
        return "time.sleep"
    if chain and (chain.startswith(_NET_PREFIXES) or chain in _SUBPROC
                  or chain == "socket.create_connection"):
        return chain
    if tail == "block_until_ready" or chain == "jax.block_until_ready" \
            or chain == "jax.device_get":
        return "device sync"
    if tail == "join" and isinstance(node.func, ast.Attribute):
        recv = attr_chain(node.func.value) or ""
        # str.join / os.path.join have an iterable arg & path-ish chains
        if any(k in recv.lower() for k in ("thread", "worker", "proc")) \
                or not node.args:
            return f"{recv or '<thread>'}.join"
    if tail == "wait" and isinstance(node.func, ast.Attribute):
        recv = (attr_chain(node.func.value) or "").lower()
        # Condition.wait releases the held lock — that's its contract
        if not any(k in recv for k in ("cond", "cv", "_not_")):
            return f"{attr_chain(node.func.value) or '<event>'}.wait"
    if tail == "get" and isinstance(node.func, ast.Attribute) \
            and not node.args:
        recv = (attr_chain(node.func.value) or "").lower()
        if "queue" in recv or recv.endswith("_q"):
            return f"{attr_chain(node.func.value)}.get"
    return None


def _direct_blocks(info) -> Dict[int, str]:
    """{Call node id: reason} for direct blocking ops in one function."""
    body = info.node.body if not isinstance(info.node, ast.Lambda) \
        else [info.node.body]
    out: Dict[int, str] = {}
    for node in walk_shallow(body):
        if isinstance(node, ast.Call):
            reason = _direct_block_reason(node)
            if reason:
                out[id(node)] = reason
    return out


# ---------------------------------------------------------------------------
# CC rules
# ---------------------------------------------------------------------------
register_rule_id("lock-order-cycle", "concurrency",
                 "inconsistent lock-acquisition order across the "
                 "codebase can deadlock")
register_rule_id("unlocked-global-mutation", "concurrency",
                 "module-level mutable state mutated from thread-"
                 "reachable code without a lock")


@rule("blocking-call-under-lock", "concurrency",
      "a blocking operation (sleep/join/wait/queue.get/network/device "
      "sync) runs while a lock is held — every waiter stalls behind it")
def check_concurrency(project: Project):
    cg = project.callgraph
    locks = LockModel(project)
    out: List[Finding] = []

    # per-function direct blocking ops and directly-acquired locks
    direct_blocks: Dict[str, Dict[int, str]] = {}
    acquires: Dict[str, Set[LockId]] = {}
    for qual, info in cg.funcs.items():
        direct_blocks[qual] = _direct_blocks(info)
        acq: Set[LockId] = set()
        body = info.node.body if not isinstance(info.node, ast.Lambda) \
            else [info.node.body]
        for node in walk_shallow(body):
            if isinstance(node, ast.With):
                for item in node.items:
                    lid = locks.resolve(item.context_expr, info.module,
                                        info.class_name)
                    if lid:
                        acq.add(lid)
        acquires[qual] = acq

    # transitive closures over the call graph
    block_reason = _transitive(cg, {q: (next(iter(v.values())) if v else
                                        None)
                                    for q, v in direct_blocks.items()})
    locks_reach = _transitive_sets(cg, acquires)

    edges: Dict[Tuple[LockId, LockId], Tuple] = {}
    for qual, info in sorted(cg.funcs.items()):
        sf = info.sf
        body = info.node.body if not isinstance(info.node, ast.Lambda) \
            else [info.node.body]
        self_blocks = direct_blocks[qual]
        _walk_held(project, cg, locks, info, body, [], self_blocks,
                   block_reason, locks_reach, edges, out)

    out.extend(_report_cycles(project, edges))
    out.extend(_check_global_mutation(project, cg, locks))
    return [f for f in out if f is not None]


def _walk_held(project, cg, locks, info, body, held: List[LockId],
               self_blocks, block_reason, locks_reach, edges, out):
    """Recursive descent tracking the with-lock stack."""
    sf = info.sf
    for stmt in (body if isinstance(body, (list, tuple)) else [body]):
        if not isinstance(stmt, ast.AST):
            continue
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(stmt, ast.With):
            new_held = list(held)
            for item in stmt.items:
                lid = locks.resolve(item.context_expr, info.module,
                                    info.class_name)
                if lid:
                    for h in new_held:
                        if h != lid:
                            edges.setdefault(
                                (h, lid), (sf, stmt, info.qualname))
                    new_held.append(lid)
                elif held:
                    # a non-lock context manager acquired while holding a
                    # lock: `with socket.create_connection(...)` blocks
                    # exactly like the plain-call form
                    _check_calls_under_lock(
                        project, cg, locks, info, [item.context_expr],
                        held, self_blocks, block_reason, locks_reach,
                        edges, out)
            _walk_held(project, cg, locks, info, stmt.body, new_held,
                       self_blocks, block_reason, locks_reach, edges, out)
            continue
        # non-with statement: check calls at this nesting level only —
        # compound-statement bodies are handled by the recursion below,
        # so restrict the scan to this statement's own expressions
        if held:
            _check_calls_under_lock(
                project, cg, locks, info, _stmt_exprs(stmt), held,
                self_blocks, block_reason, locks_reach, edges, out)
        # recurse into nested blocks with the same held stack
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                _walk_held(project, cg, locks, info, sub, held,
                           self_blocks, block_reason, locks_reach, edges,
                           out)
        for h in getattr(stmt, "handlers", []) or []:
            _walk_held(project, cg, locks, info, h.body, held,
                       self_blocks, block_reason, locks_reach, edges, out)


def _check_calls_under_lock(project, cg, locks, info, exprs, held,
                            self_blocks, block_reason, locks_reach,
                            edges, out):
    """Flag blocking calls (direct or transitive) inside `exprs` while
    the locks in `held` are held, and record acquisition-order edges for
    locks reachable through the callee."""
    sf = info.sf
    for node in walk_shallow(exprs):
        if not isinstance(node, ast.Call):
            continue
        reason = self_blocks.get(id(node))
        callee = cg.resolve_call_target(
            sf, [info.node], info.class_name, node.func)
        if reason is None and callee is not None:
            reason = block_reason.get(callee)
            if reason is not None:
                reason = f"{callee.split(':')[-1]} -> {reason}"
        if reason is not None:
            out.append(project.finding(
                sf, "blocking-call-under-lock", node,
                f"blocking operation ({reason}) while holding "
                f"{_lid_str(held[-1])} — move the slow work outside "
                "the critical section", scope=info.qualname))
        if callee is not None:
            for lid in locks_reach.get(callee, ()):
                for h in held:
                    if h != lid:
                        edges.setdefault((h, lid), (sf, node,
                                                    info.qualname))


def _stmt_exprs(stmt: ast.AST) -> List[ast.AST]:
    """The expressions evaluated AT this statement's nesting level (the
    bodies of compound statements are visited by the recursion)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, ast.For):
        return [stmt.iter]
    if isinstance(stmt, ast.Try):
        return []
    return [stmt]


def _lid_str(lid: LockId) -> str:
    module, cls, attr = lid
    short = module.rsplit(".", 1)[-1]
    return f"{short}.{cls}.{attr}" if cls else f"{short}.{attr}"


def _transitive(cg, direct: Dict[str, Optional[str]]
                ) -> Dict[str, Optional[str]]:
    """First blocking reason reachable from each function (memoized)."""
    memo: Dict[str, Optional[str]] = {}

    def visit(q, stack):
        if q in memo:
            return memo[q]
        if q in stack:
            return None
        memo[q] = direct.get(q)        # provisional (cycle cut)
        if memo[q] is None:
            stack.add(q)
            for callee in cg.funcs[q].calls:
                if callee in cg.funcs:
                    r = visit(callee, stack)
                    if r is not None:
                        memo[q] = f"{callee.split(':')[-1]} -> {r}" \
                            if " -> " not in r else r
                        break
            stack.discard(q)
        return memo[q]

    for q in cg.funcs:
        visit(q, set())
    return memo


def _transitive_sets(cg, direct: Dict[str, Set[LockId]]
                     ) -> Dict[str, Set[LockId]]:
    memo: Dict[str, Set[LockId]] = {}

    def visit(q, stack) -> Set[LockId]:
        if q in memo:
            return memo[q]
        if q in stack:
            return set()
        stack.add(q)
        acc = set(direct.get(q, ()))
        for callee in cg.funcs[q].calls:
            if callee in cg.funcs:
                acc |= visit(callee, stack)
        stack.discard(q)
        memo[q] = acc
        return acc

    for q in cg.funcs:
        visit(q, set())
    return memo


def _report_cycles(project, edges) -> List[Finding]:
    graph: Dict[LockId, Set[LockId]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    out: List[Finding] = []
    reported: Set[frozenset] = set()
    for start in sorted(graph):
        cycle = _find_cycle(graph, start)
        if not cycle:
            continue
        key = frozenset(cycle)
        if key in reported:
            continue
        reported.add(key)
        a, b = cycle[0], cycle[1 % len(cycle)]
        sf, node, scope = edges.get((a, b)) or next(
            v for k, v in edges.items() if k[0] in key and k[1] in key)
        path = " -> ".join(_lid_str(l) for l in cycle + [cycle[0]])
        out.append(project.finding(
            sf, "lock-order-cycle", node,
            f"lock acquisition order cycle: {path} — two threads taking "
            "these locks in opposite orders deadlock", scope=scope))
    return out


def _find_cycle(graph, start) -> Optional[List]:
    path: List = []
    on_path: Set = set()
    seen: Set = set()

    def dfs(n) -> Optional[List]:
        if n in on_path:
            i = path.index(n)
            return path[i:]
        if n in seen:
            return None
        seen.add(n)
        on_path.add(n)
        path.append(n)
        for m in sorted(graph.get(n, ())):
            found = dfs(m)
            if found:
                return found
        on_path.discard(n)
        path.pop()
        return None

    return dfs(start)


# ---------------------------------------------------------------------------
# unlocked-global-mutation
# ---------------------------------------------------------------------------
def _check_global_mutation(project, cg, locks: LockModel) -> List[Finding]:
    out: List[Finding] = []
    # module -> set of module-level mutable container names
    mutables: Dict[str, Set[str]] = {}
    for sf in project.files:
        names: Set[str] = set()
        for stmt in sf.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            v = stmt.value
            is_mut = isinstance(v, (ast.List, ast.Dict, ast.Set,
                                    ast.ListComp, ast.DictComp,
                                    ast.SetComp))
            if isinstance(v, ast.Call):
                chain = attr_chain(v.func) or ""
                tail = chain.rsplit(".", 1)[-1]
                is_mut = tail in _MUTABLE_CTORS
            if is_mut:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        if names:
            mutables[sf.module] = names
    if not mutables:
        return out

    for qual in sorted(cg.thread_reachable):
        info = cg.funcs[qual]
        names = mutables.get(info.module)
        if not names:
            continue
        body = info.node.body if not isinstance(info.node, ast.Lambda) \
            else [info.node.body]
        # local rebinds shadow the module global
        local = set(info.params)
        for node in walk_shallow(body):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        local.add(t.id)
        anc_with: Set[int] = set()
        _mark_under_lock(locks, info, body, [], anc_with)
        for node in walk_shallow(body):
            target: Optional[str] = None
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS and \
                    isinstance(node.func.value, ast.Name):
                target = node.func.value.id
            elif isinstance(node, (ast.Subscript,)) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)) and \
                    isinstance(node.value, ast.Name):
                target = node.value.id
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Name):
                target = node.target.id
            if target is None or target not in names or target in local:
                continue
            if id(node) in anc_with:
                continue
            out.append(project.finding(
                info.sf, "unlocked-global-mutation", node,
                f"module-level mutable '{target}' is mutated from "
                "thread-reachable code without a lock — wrap the "
                "mutation in a lock or make the state thread-local",
                scope=qual))
    return out


def _mark_under_lock(locks, info, body, held, marked: Set[int]):
    """Collect ids of every node lexically inside a with-lock block."""
    for stmt in (body if isinstance(body, (list, tuple)) else [body]):
        if not isinstance(stmt, ast.AST) or isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                       ast.Lambda)):
            continue
        if isinstance(stmt, ast.With):
            locked = held or any(
                locks.resolve(i.context_expr, info.module, info.class_name)
                for i in stmt.items)
            if locked:
                for sub in walk_shallow(stmt.body):
                    marked.add(id(sub))
            _mark_under_lock(locks, info, stmt.body,
                             held or locked, marked)
            continue
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                _mark_under_lock(locks, info, sub, held, marked)
        for h in getattr(stmt, "handlers", []) or []:
            _mark_under_lock(locks, info, h.body, held, marked)
