from .kmeans import KMeansClustering
from .kdtree import KDTree
from .vptree import VPTree

__all__ = ["KMeansClustering", "KDTree", "VPTree"]
