from .kmeans import KMeansClustering
from .kdtree import KDTree
from .vptree import VPTree
from .sptree import QuadTree, SpTree

__all__ = ["KMeansClustering", "KDTree", "VPTree", "QuadTree", "SpTree"]
