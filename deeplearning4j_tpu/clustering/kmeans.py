"""K-means clustering.

Parity with `deeplearning4j-core/.../clustering/kmeans/` (KMeansClustering
over the generic clustering algorithm SPI). TPU-first: Lloyd iterations as
dense [N,K] distance matmuls + segment means under jit.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["KMeansClustering"]


class KMeansClustering:
    def __init__(self, k: int, max_iterations: int = 100, tol: float = 1e-4,
                 seed: int = 0, distance: str = "euclidean"):
        self.k = int(k)
        self.max_iterations = int(max_iterations)
        self.tol = float(tol)
        self.seed = seed
        self.distance = distance
        self.centers: Optional[np.ndarray] = None

    def _dists(self, x, centers):
        if self.distance == "cosine":
            xn = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), 1e-12)
            cn = centers / jnp.maximum(
                jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-12)
            return 1.0 - xn @ cn.T
        sq_x = jnp.sum(x * x, axis=1)[:, None]
        sq_c = jnp.sum(centers * centers, axis=1)[None, :]
        return sq_x + sq_c - 2.0 * (x @ centers.T)

    def fit(self, x) -> "KMeansClustering":
        x = jnp.asarray(x, jnp.float32)
        n = x.shape[0]
        rng = np.random.default_rng(self.seed)
        centers = x[jnp.asarray(rng.choice(n, self.k, replace=False))]

        @jax.jit
        def step(centers):
            d = self._dists(x, centers)
            assign = jnp.argmin(d, axis=1)
            one_hot = jax.nn.one_hot(assign, self.k, dtype=x.dtype)
            counts = jnp.maximum(one_hot.sum(axis=0), 1.0)
            new_centers = (one_hot.T @ x) / counts[:, None]
            # keep empty clusters where they were
            empty = one_hot.sum(axis=0) == 0
            new_centers = jnp.where(empty[:, None], centers, new_centers)
            shift = jnp.max(jnp.linalg.norm(new_centers - centers, axis=1))
            return new_centers, assign, shift

        for _ in range(self.max_iterations):
            centers, assign, shift = step(centers)
            if float(shift) < self.tol:
                break
        self.centers = np.asarray(centers)
        self.labels_ = np.asarray(assign)
        return self

    def predict(self, x) -> np.ndarray:
        d = self._dists(jnp.asarray(x, jnp.float32), jnp.asarray(self.centers))
        return np.asarray(jnp.argmin(d, axis=1))
