"""KD-tree for exact nearest-neighbor queries.

Parity with `deeplearning4j-core/.../clustering/kdtree/KDTree.java` (insert /
nn / knn over axis-aligned median splits). Host-side numpy: these structures
serve host workloads (NLP wordsNearest, t-SNE input neighbors) — the
pointer-chasing traversal has no MXU mapping, exactly why the reference runs
them on CPU too.
"""
from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["KDTree"]


class _Node:
    __slots__ = ("index", "axis", "left", "right")

    def __init__(self, index: int, axis: int):
        self.index = index
        self.axis = axis
        self.left: Optional["_Node"] = None
        self.right: Optional["_Node"] = None


class KDTree:
    def __init__(self, points):
        self.points = np.asarray(points, dtype=np.float64)
        if self.points.ndim != 2:
            raise ValueError("points must be [N, D]")
        n, self.dims = self.points.shape
        self._root = self._build(np.arange(n), 0)
        self._size = n

    def __len__(self):
        return self._size

    def _build(self, idx: np.ndarray, depth: int) -> Optional[_Node]:
        if idx.size == 0:
            return None
        axis = depth % self.dims
        order = np.argsort(self.points[idx, axis], kind="stable")
        idx = idx[order]
        mid = idx.size // 2
        node = _Node(int(idx[mid]), axis)
        node.left = self._build(idx[:mid], depth + 1)
        node.right = self._build(idx[mid + 1:], depth + 1)
        return node

    # -- queries ---------------------------------------------------------
    def nn(self, query) -> Tuple[int, float]:
        """(index, distance) of the single nearest point."""
        [(dist, index)] = self.knn(query, 1)
        return index, dist

    def knn(self, query, k: int) -> List[Tuple[float, int]]:
        """k nearest as [(distance, index)] sorted ascending."""
        q = np.asarray(query, dtype=np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap via negated dist

        def visit(node: Optional[_Node]):
            if node is None:
                return
            p = self.points[node.index]
            dist = float(np.sqrt(np.sum((p - q) ** 2)))
            if len(heap) < k:
                heapq.heappush(heap, (-dist, node.index))
            elif dist < -heap[0][0]:
                heapq.heapreplace(heap, (-dist, node.index))
            delta = q[node.axis] - p[node.axis]
            near, far = ((node.left, node.right) if delta <= 0
                         else (node.right, node.left))
            visit(near)
            if len(heap) < k or abs(delta) < -heap[0][0]:
                visit(far)

        visit(self._root)
        return sorted((-d, i) for d, i in heap)
