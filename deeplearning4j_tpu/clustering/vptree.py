"""Vantage-point tree for metric-space nearest neighbors.

Parity with `deeplearning4j-core/.../clustering/vptree/VPTree.java` (the
structure the reference's Barnes-Hut t-SNE uses to build its sparse input
similarities, and `BasicModelUtils.wordsNearest`-class queries can use).
Euclidean or cosine ("dot" in the reference) metrics.
"""
from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["VPTree"]


class _VPNode:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index: int):
        self.index = index
        self.threshold = 0.0
        self.inside: Optional["_VPNode"] = None
        self.outside: Optional["_VPNode"] = None


class VPTree:
    def __init__(self, points, metric: str = "euclidean", seed: int = 0):
        self.points = np.asarray(points, dtype=np.float64)
        self.metric = metric
        if metric == "cosine":
            norms = np.linalg.norm(self.points, axis=1, keepdims=True)
            self._unit = self.points / np.maximum(norms, 1e-12)
        rng = np.random.default_rng(seed)
        self._root = self._build(list(range(len(self.points))), rng)

    def _dist_many(self, i: int, idx: List[int]) -> np.ndarray:
        if self.metric == "cosine":
            # angular distance: a true metric (1 - cos violates the triangle
            # inequality, which breaks VP pruning); same neighbor ordering
            cos = np.clip(self._unit[idx] @ self._unit[i], -1.0, 1.0)
            return np.arccos(cos)
        diff = self.points[idx] - self.points[i]
        return np.sqrt(np.sum(diff * diff, axis=1))

    def _dist_query(self, q: np.ndarray, i: int) -> float:
        if self.metric == "cosine":
            qn = q / max(float(np.linalg.norm(q)), 1e-12)
            return float(np.arccos(np.clip(self._unit[i] @ qn, -1.0, 1.0)))
        return float(np.sqrt(np.sum((self.points[i] - q) ** 2)))

    def _build(self, idx: List[int], rng) -> Optional[_VPNode]:
        if not idx:
            return None
        vp = idx[rng.integers(len(idx))]
        rest = [i for i in idx if i != vp]
        node = _VPNode(vp)
        if not rest:
            return node
        d = self._dist_many(vp, rest)
        median = float(np.median(d))
        node.threshold = median
        inside = [i for i, di in zip(rest, d) if di <= median]
        outside = [i for i, di in zip(rest, d) if di > median]
        node.inside = self._build(inside, rng)
        node.outside = self._build(outside, rng)
        return node

    def knn(self, query, k: int) -> List[Tuple[float, int]]:
        q = np.asarray(query, dtype=np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap (negated)
        tau = [np.inf]

        def visit(node: Optional[_VPNode]):
            if node is None:
                return
            d = self._dist_query(q, node.index)
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            elif d < tau[0]:
                heapq.heapreplace(heap, (-d, node.index))
                tau[0] = -heap[0][0]
            if node.inside is None and node.outside is None:
                return
            if d <= node.threshold:
                visit(node.inside)
                if d + tau[0] > node.threshold:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau[0] <= node.threshold:
                    visit(node.inside)

        visit(self._root)
        return sorted((-d, i) for d, i in heap)
