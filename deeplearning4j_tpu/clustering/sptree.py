"""Space-partitioning tree (generalized quadtree/octree) for Barnes-Hut.

Parity with `deeplearning4j-core/.../clustering/sptree/SpTree.java` (n-D
cells, center-of-mass accumulation, `computeNonEdgeForces` with the theta
criterion) and `clustering/quadtree/QuadTree.java` (the 2-D case — here
`QuadTree` is the d=2 instantiation). Used by BarnesHutTsne for the O(N log N)
repulsive-force approximation.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

__all__ = ["SpTree", "QuadTree"]


class _Cell:
    __slots__ = ("center", "width", "n_points", "com", "point_index",
                 "children", "is_leaf")

    def __init__(self, center: np.ndarray, width: np.ndarray):
        self.center = center          # cell midpoint [D]
        self.width = width            # half-extent per dim [D]
        self.n_points = 0
        self.com = np.zeros_like(center)   # center of mass
        self.point_index: Optional[int] = None
        self.children: Optional[List["_Cell"]] = None
        self.is_leaf = True


class SpTree:
    """Build once per t-SNE iteration over the embedding points."""

    def __init__(self, points: np.ndarray):
        self.points = np.asarray(points, dtype=np.float64)
        n, d = self.points.shape
        self.dims = d
        lo = self.points.min(axis=0)
        hi = self.points.max(axis=0)
        center = (lo + hi) / 2.0
        width = np.maximum((hi - lo) / 2.0, 1e-10) * (1.0 + 1e-3)
        self._root = _Cell(center, width)
        for i in range(n):
            self._insert(self._root, i)

    def _child_index(self, cell: _Cell, p: np.ndarray) -> int:
        idx = 0
        for dim in range(self.dims):
            if p[dim] > cell.center[dim]:
                idx |= (1 << dim)
        return idx

    def _make_children(self, cell: _Cell):
        half = cell.width / 2.0
        cell.children = []
        for ci in range(1 << self.dims):
            offset = np.array([half[dim] if (ci >> dim) & 1 else -half[dim]
                               for dim in range(self.dims)])
            cell.children.append(_Cell(cell.center + offset, half))
        cell.is_leaf = False

    def _insert(self, cell: _Cell, i: int, depth: int = 0):
        p = self.points[i]
        cell.com = (cell.com * cell.n_points + p) / (cell.n_points + 1)
        cell.n_points += 1
        if cell.is_leaf and cell.point_index is None:
            cell.point_index = i
            return
        if cell.is_leaf:
            j = cell.point_index
            # identical points would recurse forever; cap the depth
            if depth > 48 or np.allclose(self.points[j], p):
                return
            self._make_children(cell)
            cell.point_index = None
            self._insert(cell.children[self._child_index(cell,
                                                         self.points[j])],
                         j, depth + 1)
        self._insert(cell.children[self._child_index(cell, p)], i, depth + 1)

    # -- Barnes-Hut repulsive force (SpTree.computeNonEdgeForces) ---------
    def compute_non_edge_forces(self, i: int, theta: float):
        """Returns (neg_force [D], sum_q) for point i: the Barnes-Hut
        approximation of sum_j q_ij Z * (y_i - y_j) and Z itself."""
        p = self.points[i]
        neg = np.zeros(self.dims)
        sum_q = 0.0
        max_width = float(np.max(self._root.width)) * 2.0

        stack = [(self._root, max_width)]
        while stack:
            cell, width = stack.pop()
            if cell.n_points == 0:
                continue
            if cell.is_leaf and cell.point_index == i and cell.n_points == 1:
                continue
            diff = p - cell.com
            dist2 = float(diff @ diff)
            if cell.is_leaf or width * width < theta * theta * dist2:
                # treat the cell as one body; exclude self if inside
                n_eff = cell.n_points
                if cell.is_leaf and cell.point_index == i:
                    n_eff -= 1
                    if n_eff == 0:
                        continue
                q = 1.0 / (1.0 + dist2)
                contrib = n_eff * q
                sum_q += contrib
                neg += contrib * q * diff
            else:
                for child in cell.children:
                    stack.append((child, width / 2.0))
        return neg, sum_q


class QuadTree(SpTree):
    """2-D SpTree (`clustering/quadtree/QuadTree.java`)."""

    def __init__(self, points: np.ndarray):
        points = np.asarray(points, dtype=np.float64)
        if points.shape[1] != 2:
            raise ValueError("QuadTree is 2-D; use SpTree for other dims")
        super().__init__(points)
