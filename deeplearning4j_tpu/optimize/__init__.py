from .listeners import (
    IterationListener, TrainingListener, ScoreIterationListener,
    PerformanceListener, CollectScoresIterationListener,
    ComposableIterationListener, ParamAndGradientIterationListener,
)

__all__ = [
    "IterationListener", "TrainingListener", "ScoreIterationListener",
    "PerformanceListener", "CollectScoresIterationListener",
    "ComposableIterationListener", "ParamAndGradientIterationListener",
]
