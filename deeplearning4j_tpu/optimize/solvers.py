"""Line-search optimizers: backtracking line search, conjugate gradient,
L-BFGS, line gradient descent.

Parity with `optimize/solvers/` in the reference: `BaseOptimizer.java:51`
(the optimize loop), `BackTrackLineSearch.java` (Armijo backtracking),
`ConjugateGradient.java` (Polak-Ribiere with restart), `LBFGS.java` (two-loop
recursion), `LineGradientDescent.java` — selected by
`OptimizationAlgorithm` exactly as `Solver.java:41` does.

TPU-native shape: directions and dot-products are pytree ops under jit; only
the backtracking loop runs host-side (a handful of scalar loss evaluations
per batch — the same structure as the reference's line search, which also
re-evaluates the model per trial step).
"""
from __future__ import annotations

import functools
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp

from ..telemetry.compile_watch import watch_compiles

__all__ = ["BackTrackLineSearch", "LineSearchSolver",
           "GraphLineSearchSolver"]


def _tree_dot(a, b):
    parts = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(
            lambda x, y: jnp.vdot(x.astype(jnp.float32),
                                  y.astype(jnp.float32)), a, b))
    total = parts[0]
    for p in parts[1:]:
        total = total + p
    return total


def _axpy(alpha, d, p):
    """p + alpha * d over pytrees."""
    return jax.tree_util.tree_map(lambda pi, di: pi + alpha * di, p, d)


def _scale(alpha, d):
    return jax.tree_util.tree_map(lambda di: alpha * di, d)


class BackTrackLineSearch:
    """Armijo backtracking (`BackTrackLineSearch.java`): shrink alpha by
    `tau` until f(p + alpha d) <= f0 + c1 * alpha * g.d, at most
    `max_iterations` trials. Returns (alpha, f_alpha); alpha=0 with f0 when
    no trial improves (the caller then skips the update — the reference's
    'step <= minStep' bail-out)."""

    def __init__(self, max_iterations: int = 5, c1: float = 1e-4,
                 tau: float = 0.5, initial_step: float = 1.0):
        self.max_iterations = int(max_iterations)
        self.c1 = float(c1)
        self.tau = float(tau)
        self.initial_step = float(initial_step)

    def optimize(self, f, f0: float, gd: float):
        """f(alpha) -> loss at p + alpha d; gd = g.d (must be < 0 for a
        descent direction)."""
        alpha = self.initial_step
        best = (0.0, f0)
        for _ in range(self.max_iterations):
            fa = float(f(alpha))
            if fa <= f0 + self.c1 * alpha * gd and jnp.isfinite(fa):
                return alpha, fa
            if jnp.isfinite(fa) and fa < best[1]:
                best = (alpha, fa)
            alpha *= self.tau
        return best


class LineSearchSolver:
    """Per-batch optimizer for LINE_GRADIENT_DESCENT / CONJUGATE_GRADIENT /
    LBFGS (`Solver.java` → `BaseOptimizer.optimize`). Holds the algorithm
    memory (previous gradient/direction, L-BFGS (s,y) history) across
    batches; `reset()` clears it (new epoch/dataset)."""

    def __init__(self, model, algo: str, max_line_search_iterations: int = 5,
                 lbfgs_memory: int = 10):
        self.model = model
        self.algo = algo
        self.line_search = BackTrackLineSearch(
            max_iterations=max_line_search_iterations)
        self.lbfgs_memory = int(lbfgs_memory)
        self.reset()

    def reset(self):
        self._prev_g = None
        self._prev_d = None
        self._history = deque(maxlen=self.lbfgs_memory)  # (s, y) pairs
        self._prev_params = None

    # -- jitted building blocks -----------------------------------------
    @property
    def _sign(self) -> float:
        # minimize=False: line-search the NEGATED score (maximization),
        # matching the SGD path's gradient negation in _make_train_step
        return 1.0 if self.model.conf.conf.minimize else -1.0

    @functools.cached_property
    def _vag(self):
        sign = self._sign

        def vag(params, state, x, y, rng, fmask, lmask):
            (f, (new_state, _)), g = jax.value_and_grad(
                self.model._loss_fn, has_aux=True)(
                    params, state, x, y, rng, fmask=fmask, lmask=lmask)
            return sign * f, new_state, _scale(sign, g)
        return watch_compiles(jax.jit(vag), "optimize/line_vag")

    @functools.cached_property
    def _loss_at(self):
        sign = self._sign

        def loss_at(alpha, params, d, state, x, y, rng, fmask, lmask):
            p = _axpy(alpha, d, params)
            f, _ = self.model._loss_fn(p, state, x, y, rng, fmask=fmask,
                                       lmask=lmask)
            return sign * f
        return watch_compiles(jax.jit(loss_at), "optimize/line_loss_at")

    # -- directions ------------------------------------------------------
    def _direction(self, g):
        from ..nn.conf import OptimizationAlgorithm as OA

        neg_g = _scale(-1.0, g)
        if self.algo == OA.LINE_GRADIENT_DESCENT:
            return neg_g
        if self.algo == OA.CONJUGATE_GRADIENT:
            if self._prev_g is None:
                return neg_g
            # Polak-Ribiere with automatic restart (beta < 0 -> steepest)
            num = float(_tree_dot(g, jax.tree_util.tree_map(
                lambda a, b: a - b, g, self._prev_g)))
            den = float(_tree_dot(self._prev_g, self._prev_g))
            beta = max(0.0, num / den) if den > 0 else 0.0
            return _axpy(beta, self._prev_d, neg_g)
        if self.algo == OA.LBFGS:
            if not self._history:
                return neg_g
            # two-loop recursion
            q = g
            alphas = []
            for s, yv in reversed(self._history):
                rho = 1.0 / float(_tree_dot(yv, s))
                a = rho * float(_tree_dot(s, q))
                alphas.append((a, rho, s, yv))
                q = _axpy(-a, yv, q)
            s_last, y_last = self._history[-1]
            gamma = float(_tree_dot(s_last, y_last)) / float(
                _tree_dot(y_last, y_last))
            r = _scale(gamma, q)
            for a, rho, s, yv in reversed(alphas):
                b = rho * float(_tree_dot(yv, r))
                r = _axpy(a - b, s, r)
            return _scale(-1.0, r)
        raise ValueError(f"No line-search direction for algorithm "
                         f"'{self.algo}'")

    # -- one batch -------------------------------------------------------
    def fit_batch(self, params, state, x, y, rng, fmask, lmask):
        """Returns (new_params, new_state, score)."""
        f0, new_state, g = self._vag(params, state, x, y, rng, fmask, lmask)
        f0 = float(f0)
        d = self._direction(g)
        gd = float(_tree_dot(g, d))
        if gd >= 0:  # not a descent direction: restart memory, use -g
            self.reset()
            d = _scale(-1.0, g)
            gd = -float(_tree_dot(g, g))
        alpha, f_alpha = self.line_search.optimize(
            lambda a: self._loss_at(a, params, d, state, x, y, rng, fmask,
                                    lmask),
            f0, gd)
        if alpha > 0.0:
            new_params = _axpy(alpha, d, params)
        else:
            new_params = params
            f_alpha = f0

        # memory updates for the next batch
        from ..nn.conf import OptimizationAlgorithm as OA

        if self.algo == OA.CONJUGATE_GRADIENT:
            self._prev_g, self._prev_d = g, d
        elif self.algo == OA.LBFGS and alpha > 0.0:
            # curvature pair: s = alpha*d, y = grad(new) - grad(old); one
            # extra grad eval at the accepted point (the reference's LBFGS
            # gets this from the next optimize() pass — same cost amortized)
            s = _scale(alpha, d)
            _, _, g_new = self._vag(new_params, state, x, y, rng, fmask,
                                    lmask)
            yv = jax.tree_util.tree_map(lambda a, b: a - b, g_new, g)
            if float(_tree_dot(s, yv)) > 1e-10:  # keep B positive-definite
                self._history.append((s, yv))
        # report the raw (unsigned) score — internal values are sign-flipped
        # when maximizing
        return new_params, new_state, self._sign * f_alpha


class GraphLineSearchSolver(LineSearchSolver):
    """ComputationGraph variant: its `_loss_fn` returns (score, new_state)
    (no carries aux) and takes inputs/labels dicts."""

    @functools.cached_property
    def _vag(self):
        sign = self._sign

        def vag(params, state, inputs, labels, rng, fmasks, lmasks):
            (f, new_state), g = jax.value_and_grad(
                self.model._loss_fn, has_aux=True)(
                    params, state, inputs, labels, rng, fmasks=fmasks,
                    lmasks=lmasks)
            return sign * f, new_state, _scale(sign, g)
        return watch_compiles(jax.jit(vag), "optimize/graph_line_vag")

    @functools.cached_property
    def _loss_at(self):
        sign = self._sign

        def loss_at(alpha, params, d, state, inputs, labels, rng, fmasks,
                    lmasks):
            p = _axpy(alpha, d, params)
            f, _ = self.model._loss_fn(p, state, inputs, labels, rng,
                                       fmasks=fmasks, lmasks=lmasks)
            return sign * f
        return watch_compiles(jax.jit(loss_at), "optimize/graph_line_loss_at")
