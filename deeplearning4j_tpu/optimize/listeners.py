"""Training listeners.

Parity with `optimize/api/IterationListener.java` / `TrainingListener.java` and
the impls in `optimize/listeners/`: ScoreIterationListener, PerformanceListener
(samples/sec), CollectScoresIterationListener, ParamAndGradientIterationListener,
ComposableIterationListener.

Listeners run host-side between jitted steps; they see the model, the iteration
number and the (host-synced) score. Heavy introspection (param/gradient stats)
pulls device arrays — the PerformanceListener notes when that forces a sync.
"""
from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

import jax
import numpy as np

log = logging.getLogger("deeplearning4j_tpu")

__all__ = [
    "IterationListener", "TrainingListener", "ScoreIterationListener",
    "PerformanceListener", "CollectScoresIterationListener",
    "ComposableIterationListener", "ParamAndGradientIterationListener",
]


class IterationListener:
    """Per-iteration hook (reference `optimize/api/IterationListener.java`)."""

    invoked = False

    def iteration_done(self, model, iteration: int):
        pass


class TrainingListener(IterationListener):
    """Adds epoch/forward/backward hooks (reference `optimize/api/TrainingListener.java`)."""

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def on_forward_pass(self, model, activations):
        pass

    def on_backward_pass(self, model):
        pass

    def on_gradient_calculation(self, model):
        pass


class ScoreIterationListener(IterationListener):
    """Logs score every N iterations (`optimize/listeners/ScoreIterationListener.java`)."""

    def __init__(self, print_iterations: int = 10, printer: Optional[Callable] = None):
        self.print_iterations = max(1, int(print_iterations))
        self.printer = printer or (lambda s: log.info(s))

    def iteration_done(self, model, iteration: int):
        if iteration % self.print_iterations == 0:
            self.printer(f"Score at iteration {iteration} is {model.score()}")


class PerformanceListener(IterationListener):
    """Samples/sec + batches/sec reporting (`optimize/listeners/PerformanceListener.java`).
    This is the metric surfaced by bench.py.

    Superstep/scan fits replay this hook at the window edge with the
    already-transferred per-window loss vector (model._score holds a HOST
    scalar per replayed iteration), so `report_score=True` reads the
    window vector instead of forcing a device sync per reported iteration;
    only the per-batch (superstep=1) path pays a sync, and only when the
    report fires."""

    def __init__(self, frequency: int = 1, report_score: bool = False,
                 printer: Optional[Callable] = None):
        self.frequency = max(1, int(frequency))
        self.report_score = report_score
        self.printer = printer or (lambda s: log.info(s))
        # the window opens when the listener is attached: the first batch
        # (which pays XLA compilation) is COUNTED, not silently discarded,
        # and its record carries warmup=True so dashboards can exclude it
        self._last_time = time.perf_counter()
        self._samples = 0
        self._batches = 0
        self._first_window = True
        self.history: List[dict] = []

    def iteration_done(self, model, iteration: int):
        now = time.perf_counter()
        batch = getattr(model, "last_batch_size", 0)
        self._samples += batch
        self._batches += 1
        if self._batches >= self.frequency:
            # clamp: back-to-back replayed iterations (fit_scan listener
            # replay) can land in the same perf_counter tick — a rate from
            # a clamped dt is inflated but finite, never NaN
            dt = max(now - self._last_time, 1e-9)
            rec = {
                "iteration": iteration,
                "samples_per_sec": self._samples / dt,
                "batches_per_sec": self._batches / dt,
            }
            if self._first_window:
                rec["warmup"] = True
                self._first_window = False
            if self.report_score:
                rec["score"] = float(model.score())
            self.history.append(rec)
            self.printer(
                f"iteration {iteration}: {rec['samples_per_sec']:.1f} samples/sec, "
                f"{rec['batches_per_sec']:.2f} batches/sec"
                + (" (warmup window)" if rec.get("warmup") else ""))
            self._last_time = now
            self._samples = 0
            self._batches = 0


class CollectScoresIterationListener(IterationListener):
    """Collects (iteration, score) pairs (`optimize/listeners/CollectScoresIterationListener.java`)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, int(frequency))
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(model.score())))

    def export_scores(self, path, delimiter=","):
        # explicit encoding + newline: without them Windows writes CRLF and
        # the platform codec garbles non-ASCII paths/headers on re-import
        with open(path, "w", encoding="utf-8", newline="\n") as f:
            f.write(f"iteration{delimiter}score\n")
            for it, s in self.scores:
                f.write(f"{it}{delimiter}{s}\n")

    @staticmethod
    def load_scores(path, delimiter=",") -> List[tuple]:
        """Round-trip reader for `export_scores` output."""
        out: List[tuple] = []
        with open(path, "r", encoding="utf-8", newline="") as f:
            header = f.readline()
            if not header.startswith("iteration"):
                raise ValueError(f"not an export_scores file: {path}")
            for line in f:
                line = line.strip()
                if not line:
                    continue
                it, s = line.split(delimiter, 1)
                out.append((int(it), float(s)))
        return out


class ParamAndGradientIterationListener(IterationListener):
    """Per-iteration parameter/gradient statistics
    (`optimize/listeners/ParamAndGradientIterationListener.java`). Pulls device
    arrays to host — use sparingly."""

    collects_param_stats = True

    def __init__(self, frequency: int = 1, printer: Optional[Callable] = None):
        self.frequency = max(1, int(frequency))
        self.printer = printer or (lambda s: log.info(s))

    def iteration_done(self, model, iteration: int):
        if iteration % self.frequency != 0:
            return
        leaves = jax.tree_util.tree_leaves(model.params)
        if not leaves:
            return
        flat = np.concatenate([np.asarray(l).ravel() for l in leaves])
        self.printer(
            f"iter {iteration}: |params| mean abs {np.abs(flat).mean():.3e}, "
            f"l2 {np.linalg.norm(flat):.3e}")


def warn_scan_replay(listeners):
    """fit_scan_arrays replays listeners AFTER the on-device scan with
    per-step scores only — every iteration_done sees the FINAL params.
    Warn when attached listeners snapshot params per iteration (histograms
    would record identical end-of-window values for all steps)."""
    def flatten(ls):
        for l in ls:
            yield l
            # ComposableIterationListener (and anything list-like) wraps
            # children in a `listeners` attribute
            yield from flatten(getattr(l, "listeners", ()))

    bad = sorted({type(l).__name__ for l in flatten(listeners)
                  if getattr(l, "collects_param_stats", False)})
    if bad:
        import warnings
        warnings.warn(
            f"listeners {bad} collect per-iteration parameter stats, but "
            "fit_scan_arrays replays iteration_done after the device scan: "
            "scores are per-step, param/update stats are end-of-window "
            "snapshots. Use fit() for faithful per-iteration histograms.",
            stacklevel=3)


class ComposableIterationListener(IterationListener):
    def __init__(self, *listeners):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration: int):
        for l in self.listeners:
            l.iteration_done(model, iteration)
