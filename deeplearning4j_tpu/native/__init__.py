"""Native runtime loader — builds and binds libdl4j_native (C++17).

The reference's data plane is native (DataVec record readers, the custom
MNIST binary reader under `datasets/mnist/`, MagicQueue prefetch); here the
equivalents live in `dl4j_native.cpp`, compiled on first use with the host
toolchain and bound with ctypes (no pybind11 in the image). Everything has
a pure-Python fallback — `native_available()` gates the fast path, exactly
like the reference's runtime cuDNN-helper probe
(`ConvolutionLayer.initializeHelper` pattern).
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

log = logging.getLogger("deeplearning4j_tpu")

__all__ = ["native_available", "lib", "idx_read_native", "csv_read_native",
           "u8_to_f32", "image_decode_native", "PrefetchRing"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "dl4j_native.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _lib_path() -> str:
    cache = os.environ.get(
        "DL4J_TPU_NATIVE_DIR",
        os.path.join(os.path.expanduser("~"), ".deeplearning4j_tpu", "lib"))
    os.makedirs(cache, exist_ok=True)
    return os.path.join(cache, "libdl4j_native.so")


def _build(dest: str) -> bool:
    # build to a temp file in the same dir, then atomically os.replace:
    # concurrent builders don't corrupt each other, and a long-running
    # process with the old .so mmapped keeps its (unlinked) inode instead
    # of taking SIGBUS from an in-place truncate
    tmp = f"{dest}.build.{os.getpid()}"
    base = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
            _SRC, "-o", tmp]
    try:
        # zlib is only needed by the PNG decoder: if the dev files are
        # missing, fall back to a zlib-free build (PNG -> PIL) instead of
        # losing the whole native tier
        out = subprocess.run(base + ["-lz"], capture_output=True, text=True,
                             timeout=180)
        if out.returncode != 0:
            out = subprocess.run(base + ["-DDL4J_NO_ZLIB"],
                                 capture_output=True, text=True, timeout=180)
        if out.returncode != 0:
            log.warning("native build failed:\n%s", out.stderr[-2000:])
            return False
        os.replace(tmp, dest)
        return True
    except (OSError, subprocess.TimeoutExpired) as e:
        log.info("native build unavailable: %s", e)
        return False
    finally:
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass


def _bind(lib: ctypes.CDLL):
    c_char_p, c_int, c_i64 = ctypes.c_char_p, ctypes.c_int, ctypes.c_int64
    u8_p = ctypes.POINTER(ctypes.c_uint8)
    f32_p = ctypes.POINTER(ctypes.c_float)
    i64_p = ctypes.POINTER(c_i64)
    lib.idx_header.argtypes = [c_char_p, ctypes.POINTER(c_int),
                               ctypes.POINTER(c_int), i64_p]
    lib.idx_header.restype = c_int
    lib.idx_payload.argtypes = [c_char_p, u8_p, c_i64]
    lib.idx_payload.restype = c_i64
    lib.u8_to_f32.argtypes = [u8_p, f32_p, c_i64, ctypes.c_float,
                              ctypes.c_float]
    lib.u8_to_f32.restype = None
    lib.u8_binarize_f32.argtypes = [u8_p, f32_p, c_i64, c_int]
    lib.u8_binarize_f32.restype = None
    lib.csv_shape.argtypes = [c_char_p, c_int, i64_p, i64_p]
    lib.csv_shape.restype = c_int
    lib.csv_parse_f32.argtypes = [c_char_p, c_int, f32_p, c_i64, c_i64]
    lib.csv_parse_f32.restype = c_i64
    lib.csv_parse_alloc.argtypes = [c_char_p, c_int,
                                    ctypes.POINTER(f32_p), i64_p, i64_p]
    lib.csv_parse_alloc.restype = c_i64
    lib.csv_free.argtypes = [f32_p]
    lib.csv_free.restype = None
    lib.ring_open.argtypes = [c_char_p, c_i64, c_i64, c_i64, c_i64, c_int]
    lib.ring_open.restype = ctypes.c_void_p
    lib.ring_next.argtypes = [ctypes.c_void_p, u8_p]
    lib.ring_next.restype = c_i64
    lib.ring_close.argtypes = [ctypes.c_void_p]
    lib.ring_close.restype = None
    lib.ring_error.argtypes = [ctypes.c_void_p]
    lib.ring_error.restype = c_int
    int_p = ctypes.POINTER(c_int)
    lib.image_decode_alloc.argtypes = [c_char_p, ctypes.POINTER(u8_p),
                                       int_p, int_p, int_p]
    lib.image_decode_alloc.restype = c_int
    lib.image_free.argtypes = [u8_p]
    lib.image_free.restype = None
    lib.dl4j_native_abi.argtypes = []
    lib.dl4j_native_abi.restype = c_int


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("DL4J_TPU_DISABLE_NATIVE", "").strip().lower() \
                in ("1", "true", "yes", "on"):
            return None
        try:
            path = _lib_path()
            src_mtime = os.path.getmtime(_SRC)
            if not os.path.exists(path) \
                    or os.path.getmtime(path) < src_mtime:
                # the one-time cc build MUST complete under _LOCK:
                # concurrent importers have nothing to do until the
                # artifact exists, and exactly-once is the point
                if not _build(path):  # graftlint: disable=blocking-call-under-lock
                    return None
            lib = ctypes.CDLL(path)
            _bind(lib)
            if lib.dl4j_native_abi() != 2:
                return None
            _LIB = lib
        except Exception as e:   # ANY probe failure degrades to pure Python
            log.info("native tier unavailable: %s", e)
            return None
        return _LIB


def native_available() -> bool:
    return _load() is not None


def lib() -> ctypes.CDLL:
    l = _load()
    if l is None:
        raise RuntimeError("dl4j_native is not available on this host")
    return l


# ---------------------------------------------------------------------------
# numpy-facing wrappers
# ---------------------------------------------------------------------------

_IDX_DTYPES = {0x08: (np.uint8, 1), 0x09: (np.int8, 1), 0x0B: (">i2", 2),
               0x0C: (">i4", 4), 0x0D: (">f4", 4), 0x0E: (">f8", 8)}


def idx_read_native(path: str) -> np.ndarray:
    """Read an (uncompressed) IDX file via the native decoder."""
    l = lib()
    dtype = ctypes.c_int()
    ndim = ctypes.c_int()
    dims = (ctypes.c_int64 * 8)()
    rc = l.idx_header(path.encode(), ctypes.byref(dtype), ctypes.byref(ndim),
                      dims)
    if rc != 0:
        raise ValueError(f"bad IDX file {path!r} (rc={rc})")
    if dtype.value not in _IDX_DTYPES:
        raise ValueError(f"unknown IDX dtype 0x{dtype.value:02x}")
    np_dtype, itemsize = _IDX_DTYPES[dtype.value]
    shape = tuple(dims[i] for i in range(ndim.value))
    n = int(np.prod(shape)) * itemsize
    # validate the untrusted header against the real file size BEFORE
    # allocating (a corrupt header must not drive a multi-TiB np.empty),
    # and reject trailing garbage like the pure-Python parser does
    expected = 4 + 4 * ndim.value + n
    actual = os.path.getsize(path)
    if actual != expected:
        raise ValueError(
            f"{path}: payload size {actual - 4 - 4 * ndim.value} != shape "
            f"{shape} ({n} bytes expected)")
    buf = np.empty(n, np.uint8)
    got = l.idx_payload(path.encode(),
                        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                        n)
    if got != n:
        raise ValueError(f"IDX payload short read: {got} != {n}")
    return buf.view(np_dtype).reshape(shape)


def csv_read_native(path: str, skip_rows: int = 0) -> np.ndarray:
    """Parse a numeric CSV into a float32 [rows, cols] array (single file
    read; ragged rows are an error, matching the numpy fallback)."""
    l = lib()
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    buf = ctypes.POINTER(ctypes.c_float)()
    rc = l.csv_parse_alloc(path.encode(), skip_rows, ctypes.byref(buf),
                           ctypes.byref(rows), ctypes.byref(cols))
    if rc == -5:
        raise ValueError(f"{path}: ragged CSV (rows have differing field "
                         "counts)")
    if rc != 0:
        raise ValueError(f"cannot read CSV {path!r} (rc={rc})")
    try:
        n = rows.value * cols.value
        out = np.ctypeslib.as_array(buf, shape=(n,)).astype(
            np.float32, copy=True).reshape(rows.value, cols.value) \
            if n else np.empty((rows.value, cols.value), np.float32)
    finally:
        if buf:  # free even for 0-element results (malloc(0) may be non-NULL)
            l.csv_free(buf)
    return out


def u8_to_f32(src: np.ndarray, scale: float = 1.0 / 255.0,
              shift: float = 0.0, binarize: bool = False,
              threshold: int = 30) -> np.ndarray:
    """Normalize a uint8 payload to float32 natively (reference
    MnistDataFetcher normalization/binarize flags)."""
    l = lib()
    src = np.ascontiguousarray(src, np.uint8)
    out = np.empty(src.shape, np.float32)
    sp = src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    dp = out.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    if binarize:
        l.u8_binarize_f32(sp, dp, src.size, threshold)
    else:
        l.u8_to_f32(sp, dp, src.size, scale, shift)
    return out


def image_decode_native(path: str) -> Optional[np.ndarray]:
    """Decode PNG/BMP/PPM/PGM natively -> uint8 [H, W, C] in ONE pass.
    Returns None for formats the native tier doesn't cover (JPEG etc., or
    PNG on a zlib-free build) — the caller falls back to PIL. Raises
    ValueError on corrupt files."""
    l = lib()
    w, h, ch = ctypes.c_int(), ctypes.c_int(), ctypes.c_int()
    buf = ctypes.POINTER(ctypes.c_uint8)()
    rc = l.image_decode_alloc(path.encode(), ctypes.byref(buf),
                              ctypes.byref(w), ctypes.byref(h),
                              ctypes.byref(ch))
    if rc == -2:
        return None
    if rc == -1:
        raise FileNotFoundError(path)
    if rc != 0:
        raise ValueError(f"corrupt image file {path!r} (rc={rc})")
    try:
        n = h.value * w.value * ch.value
        out = np.ctypeslib.as_array(buf, shape=(n,)).copy().reshape(
            h.value, w.value, ch.value)
    finally:
        if buf:
            l.image_free(buf)
    return out


class PrefetchRing:
    """Background C++ thread streaming fixed-size records from a binary file
    into a ring of pre-decoded batch buffers (MagicQueue analog). Iterate
    with next_batch() until it returns None (epoch end)."""

    def __init__(self, path: str, record_bytes: int, total_records: int,
                 batch_records: int, header_bytes: int = 0, slots: int = 3):
        self._lib = lib()
        self.record_bytes = int(record_bytes)
        self.batch_records = int(batch_records)
        self._h = self._lib.ring_open(
            path.encode(), header_bytes, record_bytes, total_records,
            batch_records, slots)
        if not self._h:
            raise OSError(f"cannot open {path!r}")
        self._buf = np.empty(self.batch_records * self.record_bytes,
                             np.uint8)

    def next_batch(self) -> Optional[np.ndarray]:
        got = self._lib.ring_next(
            self._h,
            self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        if got == 0:
            return None
        if got < 0:
            raise IOError(f"prefetch ring error {got}")
        n = int(got)
        return (self._buf[:n * self.record_bytes]
                .reshape(n, self.record_bytes).copy())

    def close(self):
        if getattr(self, "_h", None):
            self._lib.ring_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
