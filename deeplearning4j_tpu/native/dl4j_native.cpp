// dl4j_native — native runtime components of the TPU-first DL4J rebuild.
//
// Reference analog: the reference reaches native code for its data plane and
// runtime via DataVec record readers (CSV/image -> INDArray,
// `RecordReaderDataSetIterator`), the custom MNIST binary reader
// (`deeplearning4j-core/src/main/java/org/deeplearning4j/datasets/mnist/`),
// and the device-aware prefetch queue (`MagicQueue.java`). Those live here as
// plain C++17 (no external deps), exposed over a C ABI consumed from Python
// with ctypes. The TPU compute path stays JAX/XLA; this is the host-side IO
// tier that feeds it.
//
// Components:
//   * IDX decode (MNIST format): header parse + payload -> caller buffer
//   * CSV float parser: strtof-based two-pass parse, ~10x numpy.loadtxt
//   * u8 -> f32 normalize: scale/shift image payloads without a Python pass
//   * PrefetchRing: background thread streaming fixed-size records from a
//     binary file into a ring of pre-allocated batch buffers (the
//     MagicQueue/AsyncDataSetIterator analog, file-backed)
//
// Build: g++ -O3 -std=c++17 -shared -fPIC dl4j_native.cpp -o libdl4j_native.so
#ifndef _GNU_SOURCE
#define _GNU_SOURCE  // strtof_l / newlocale
#endif
#include <locale.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <condition_variable>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// IDX (MNIST binary) decode
// ---------------------------------------------------------------------------

// Parse an IDX header. Returns 0 on success. On success: *dtype_code is the
// IDX type byte (0x08=u8, 0x0B=i16, 0x0C=i32, 0x0D=f32, 0x0E=f64), dims[0..
// *ndim-1] the dimension sizes (max 8 dims).
int idx_header(const char* path, int* dtype_code, int* ndim, int64_t* dims) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  unsigned char magic[4];
  if (std::fread(magic, 1, 4, f) != 4 || magic[0] != 0 || magic[1] != 0) {
    std::fclose(f);
    return -2;
  }
  *dtype_code = magic[2];
  int nd = magic[3];
  if (nd <= 0 || nd > 8) {
    std::fclose(f);
    return -3;
  }
  *ndim = nd;
  for (int i = 0; i < nd; i++) {
    unsigned char b[4];
    if (std::fread(b, 1, 4, f) != 4) {
      std::fclose(f);
      return -4;
    }
    dims[i] = ((int64_t)b[0] << 24) | ((int64_t)b[1] << 16) |
              ((int64_t)b[2] << 8) | (int64_t)b[3];
  }
  std::fclose(f);
  return 0;
}

// Read the IDX payload (raw bytes, big-endian element order as stored) into
// `out` (caller-allocated, `out_bytes` long). Returns bytes read or <0.
int64_t idx_payload(const char* path, unsigned char* out, int64_t out_bytes) {
  int dtype, nd;
  int64_t dims[8];
  int rc = idx_header(path, &dtype, &nd, dims);
  if (rc != 0) return rc;
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 4 + 4 * nd, SEEK_SET);
  int64_t got = (int64_t)std::fread(out, 1, (size_t)out_bytes, f);
  std::fclose(f);
  return got;
}

// ---------------------------------------------------------------------------
// u8 -> f32 normalize (image payload -> network input)
// ---------------------------------------------------------------------------

void u8_to_f32(const unsigned char* src, float* dst, int64_t n, float scale,
               float shift) {
  for (int64_t i = 0; i < n; i++) dst[i] = (float)src[i] * scale + shift;
}

// Binarize variant (reference MnistDataFetcher `binarize` flag,
// MnistDataFetcher.java:40): pixel > threshold -> 1 else 0.
void u8_binarize_f32(const unsigned char* src, float* dst, int64_t n,
                     int threshold) {
  for (int64_t i = 0; i < n; i++) dst[i] = src[i] > threshold ? 1.0f : 0.0f;
}

// ---------------------------------------------------------------------------
// CSV float parser
// ---------------------------------------------------------------------------

// Locale-pinned strtof: the caller's process may run under a comma-decimal
// locale (de_DE etc.), where plain strtof("1.5") would stop at the '.'.
static float strtof_c(const char* s, char** end) {
  static locale_t c_loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
  return strtof_l(s, end, c_loc);
}

// Count data rows and columns. Rows = newline-terminated non-empty lines
// minus `skip_rows`. Columns = fields in the first counted row; a later row
// with a different field count is an error (-5, matching the loud failure
// of the numpy fallback on ragged CSVs). Returns 0 on success.
int csv_shape(const char* path, int skip_rows, int64_t* rows, int64_t* cols) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  if (sz < 0) {  // non-seekable (FIFO etc.) — fail cleanly, no OOB write
    std::fclose(f);
    return -1;
  }
  std::fseek(f, 0, SEEK_SET);
  std::vector<char> buf((size_t)sz + 1);
  if (sz > 0 && std::fread(buf.data(), 1, (size_t)sz, f) != (size_t)sz) {
    std::fclose(f);
    return -2;
  }
  std::fclose(f);
  buf[(size_t)sz] = '\0';
  int64_t r = 0, c = 0;
  int skipped = 0;
  const char* p = buf.data();
  const char* end = p + sz;
  while (p < end) {
    const char* line_end = (const char*)memchr(p, '\n', (size_t)(end - p));
    if (!line_end) line_end = end;
    bool empty = true;
    for (const char* q = p; q < line_end; q++)
      if (*q != ' ' && *q != '\r' && *q != '\t') {
        empty = false;
        break;
      }
    if (!empty) {
      if (skipped < skip_rows) {
        skipped++;
      } else {
        int64_t this_c = 1;
        for (const char* q = p; q < line_end; q++)
          if (*q == ',') this_c++;
        if (r == 0) {
          c = this_c;
        } else if (this_c != c) {
          return -5;  // ragged row
        }
        r++;
      }
    }
    p = line_end + 1;
  }
  *rows = r;
  *cols = c;
  return 0;
}

// One-read variant: slurp the file once, derive the shape and parse from
// the same buffer, returning a malloc'd matrix the caller frees with
// csv_free. Returns 0 on success (rows/cols/out filled) or <0 (-5 ragged).
int64_t csv_parse_alloc(const char* path, int skip_rows, float** out,
                        int64_t* rows, int64_t* cols) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  if (sz < 0) {  // non-seekable (FIFO etc.) — fail cleanly, no OOB write
    std::fclose(f);
    return -1;
  }
  std::fseek(f, 0, SEEK_SET);
  std::vector<char> buf((size_t)sz + 1);
  if (sz > 0 && std::fread(buf.data(), 1, (size_t)sz, f) != (size_t)sz) {
    std::fclose(f);
    return -2;
  }
  std::fclose(f);
  buf[(size_t)sz] = '\0';

  auto line_empty = [](const char* a, const char* b) {
    for (const char* q = a; q < b; q++)
      if (*q != ' ' && *q != '\r' && *q != '\t') return false;
    return true;
  };
  // pass 1: shape (over the in-memory buffer)
  int64_t r = 0, c = 0;
  {
    const char* p = buf.data();
    const char* end = p + sz;
    int skipped = 0;
    while (p < end) {
      const char* le = (const char*)memchr(p, '\n', (size_t)(end - p));
      if (!le) le = end;
      if (!line_empty(p, le)) {
        if (skipped < skip_rows) {
          skipped++;
        } else {
          int64_t tc = 1;
          for (const char* q = p; q < le; q++)
            if (*q == ',') tc++;
          if (r == 0) c = tc;
          else if (tc != c) return -5;  // ragged row
          r++;
        }
      }
      p = le + 1;
    }
  }
  float* m = (float*)std::malloc((size_t)(r * c) * sizeof(float));
  if (!m && r * c > 0) return -6;
  // pass 2: parse (same buffer, no second read)
  {
    char* p = buf.data();
    char* end = p + sz;
    int skipped = 0;
    int64_t rr = 0;
    while (p < end && rr < r) {
      char* le = (char*)memchr(p, '\n', (size_t)(end - p));
      if (!le) le = end;
      if (!line_empty(p, le)) {
        if (skipped < skip_rows) {
          skipped++;
        } else {
          char saved = *le;
          *le = '\0';
          char* q = p;
          for (int64_t cc = 0; cc < c; cc++) {
            char* next = nullptr;
            float v = strtof_c(q, &next);
            if (next == q) v = 0.0f;
            m[rr * c + cc] = v;
            q = next;
            while (q < le && *q != ',') q++;
            if (q < le) q++;
          }
          *le = saved;
          rr++;
        }
      }
      p = le + 1;
    }
  }
  *out = m;
  *rows = r;
  *cols = c;
  return 0;
}

void csv_free(float* p) { std::free(p); }

// Parse into caller-allocated out[rows*cols] (row-major f32). Non-numeric
// fields parse as 0. Returns number of rows parsed or <0.
int64_t csv_parse_f32(const char* path, int skip_rows, float* out,
                      int64_t rows, int64_t cols) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  if (sz < 0) {  // non-seekable (FIFO etc.) — fail cleanly, no OOB write
    std::fclose(f);
    return -1;
  }
  std::fseek(f, 0, SEEK_SET);
  std::vector<char> buf((size_t)sz + 1);
  if (sz > 0 && std::fread(buf.data(), 1, (size_t)sz, f) != (size_t)sz) {
    std::fclose(f);
    return -2;
  }
  std::fclose(f);
  buf[(size_t)sz] = '\0';
  char* p = buf.data();
  char* end = p + sz;
  int64_t r = 0;
  int skipped = 0;
  while (p < end && r < rows) {
    char* line_end = (char*)memchr(p, '\n', (size_t)(end - p));
    if (!line_end) line_end = end;
    bool empty = true;
    for (char* q = p; q < line_end; q++)
      if (*q != ' ' && *q != '\r' && *q != '\t') {
        empty = false;
        break;
      }
    if (!empty) {
      if (skipped < skip_rows) {
        skipped++;
      } else {
        char saved = *line_end;
        *line_end = '\0';
        char* q = p;
        for (int64_t cc = 0; cc < cols; cc++) {
          char* next = nullptr;
          float v = strtof_c(q, &next);
          if (next == q) v = 0.0f;  // non-numeric field
          out[r * cols + cc] = v;
          q = next;
          while (q < line_end && *q != ',') q++;
          if (q < line_end) q++;
        }
        *line_end = saved;
        r++;
      }
    }
    p = line_end + 1;
  }
  return r;
}

// ---------------------------------------------------------------------------
// PrefetchRing: background-thread record streaming (MagicQueue analog)
// ---------------------------------------------------------------------------

struct PrefetchRing {
  FILE* f = nullptr;
  int64_t record_bytes = 0;   // bytes per record
  int64_t batch_records = 0;  // records per batch
  int64_t total_records = 0;
  int64_t next_record = 0;    // producer cursor
  int64_t produced = 0;       // batches produced
  int64_t consumed = 0;       // batches consumed
  int64_t n_batches = 0;      // total batches per epoch
  int slots = 0;
  std::vector<std::vector<unsigned char>> ring;
  std::vector<int64_t> fill;  // records actually in each slot
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_can_produce, cv_can_consume;
  std::atomic<bool> stop{false};
  int error = 0;

  void run() {
    while (!stop.load()) {
      std::unique_lock<std::mutex> lk(mu);
      cv_can_produce.wait(lk, [&] {
        return stop.load() || (produced - consumed) < slots;
      });
      if (stop.load()) break;
      if (produced >= n_batches) break;  // epoch done
      int slot = (int)(produced % slots);
      int64_t want = std::min(batch_records, total_records - next_record);
      int64_t off = next_record;
      lk.unlock();
      // read outside the lock
      std::fseek(f, (long)(header_bytes + off * record_bytes), SEEK_SET);
      size_t got = std::fread(ring[slot].data(), (size_t)record_bytes,
                              (size_t)want, f);
      lk.lock();
      if ((int64_t)got != want) error = -5;
      fill[slot] = (int64_t)got;
      next_record += want;
      produced++;
      cv_can_consume.notify_all();
    }
  }

  int64_t header_bytes = 0;
};

void* ring_open(const char* path, int64_t header_bytes, int64_t record_bytes,
                int64_t total_records, int64_t batch_records, int slots) {
  auto* r = new PrefetchRing();
  r->f = std::fopen(path, "rb");
  if (!r->f) {
    delete r;
    return nullptr;
  }
  r->header_bytes = header_bytes;
  r->record_bytes = record_bytes;
  r->batch_records = batch_records;
  r->total_records = total_records;
  r->slots = slots < 1 ? 2 : slots;
  r->n_batches = (total_records + batch_records - 1) / batch_records;
  r->ring.resize((size_t)r->slots);
  r->fill.resize((size_t)r->slots, 0);
  for (auto& b : r->ring)
    b.resize((size_t)(record_bytes * batch_records));
  r->worker = std::thread([r] { r->run(); });
  return r;
}

// Pop the next prefetched batch into `out`. Returns records copied, 0 at
// end of epoch, <0 on error.
int64_t ring_next(void* handle, unsigned char* out) {
  auto* r = (PrefetchRing*)handle;
  std::unique_lock<std::mutex> lk(r->mu);
  if (r->consumed >= r->n_batches) return 0;
  r->cv_can_consume.wait(lk, [&] {
    return r->stop.load() || r->error != 0 || r->produced > r->consumed;
  });
  if (r->error != 0) return r->error;
  if (r->stop.load()) return -9;
  int slot = (int)(r->consumed % r->slots);
  int64_t n = r->fill[slot];
  std::memcpy(out, r->ring[slot].data(), (size_t)(n * r->record_bytes));
  r->consumed++;
  r->cv_can_produce.notify_all();
  return n;
}

void ring_close(void* handle) {
  auto* r = (PrefetchRing*)handle;
  r->stop.store(true);
  r->cv_can_produce.notify_all();
  r->cv_can_consume.notify_all();
  if (r->worker.joinable()) r->worker.join();
  if (r->f) std::fclose(r->f);
  delete r;
}

int ring_error(void* handle) { return ((PrefetchRing*)handle)->error; }

}  // extern "C"

// ---------------------------------------------------------------------------
// Image decode: PNG (zlib), BMP (24/32bpp uncompressed), PPM/PGM binary.
// The reference's image tier is DataVec's JavaCV ImageRecordReader
// (`datavec-data-image` NativeImageLoader); here the common lossless
// formats decode natively and the Python side falls back to PIL for JPEG.
// ---------------------------------------------------------------------------
#ifndef DL4J_NO_ZLIB
#include <zlib.h>
#endif

#include <cctype>

namespace {

// sanity caps on untrusted header dimensions: decoders must return -3 on
// corrupt files, never abort the process on a 30 GB bad_alloc or wrap a
// size_t bounds check
constexpr int64_t kMaxDim = 1 << 16;          // 65536 px per side
constexpr int64_t kMaxPixels = 1LL << 28;     // 256M elements (x channels)

static bool dims_ok(int64_t w, int64_t h, int64_t ch) {
  return w > 0 && h > 0 && w <= kMaxDim && h <= kMaxDim &&
         w * h * ch <= kMaxPixels;
}

struct Bytes {
  std::vector<unsigned char> v;
};

static bool read_file(const char* path, std::vector<unsigned char>& out) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (n < 0) { std::fclose(f); return false; }
  out.resize((size_t)n);
  size_t got = n ? std::fread(out.data(), 1, (size_t)n, f) : 0;
  std::fclose(f);
  return got == (size_t)n;
}

static uint32_t be32(const unsigned char* p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

static int paeth(int a, int b, int c) {
  int p = a + b - c, pa = std::abs(p - a), pb = std::abs(p - b),
      pc = std::abs(p - c);
  if (pa <= pb && pa <= pc) return a;
  if (pb <= pc) return b;
  return c;
}

// Decode an 8-bit non-interlaced PNG. Returns 0 ok, -2 unsupported,
// -3 corrupt. On ok fills w/h/ch and `pix` (interleaved, palette expanded
// to RGB).
#ifdef DL4J_NO_ZLIB
static int png_decode(const std::vector<unsigned char>& buf, int*, int*,
                      int*, std::vector<unsigned char>&) {
  static const unsigned char SIG[8] = {0x89, 'P', 'N', 'G', '\r', '\n',
                                       0x1A, '\n'};
  // zlib-free build: PNG is unsupported (PIL fallback), other formats work
  (void)SIG;
  return -2;
}
#else
static int png_decode(const std::vector<unsigned char>& buf, int* w, int* h,
                      int* ch, std::vector<unsigned char>& pix) {
  static const unsigned char SIG[8] = {0x89, 'P', 'N', 'G', '\r', '\n',
                                       0x1A, '\n'};
  if (buf.size() < 8 || std::memcmp(buf.data(), SIG, 8) != 0) return -2;
  size_t i = 8;
  uint32_t W = 0, H = 0;
  int bit_depth = 0, color_type = -1, interlace = 0;
  std::vector<unsigned char> idat, plte;
  while (i + 8 <= buf.size()) {
    uint32_t len = be32(&buf[i]);
    if (i + 12 + (size_t)len > buf.size()) return -3;
    const unsigned char* tag = &buf[i + 4];
    const unsigned char* data = &buf[i + 8];
    if (!std::memcmp(tag, "IHDR", 4)) {
      if (len < 13) return -3;
      W = be32(data);
      H = be32(data + 4);
      bit_depth = data[8];
      color_type = data[9];
      interlace = data[12];
    } else if (!std::memcmp(tag, "PLTE", 4)) {
      plte.assign(data, data + len);
    } else if (!std::memcmp(tag, "IDAT", 4)) {
      idat.insert(idat.end(), data, data + len);
    } else if (!std::memcmp(tag, "IEND", 4)) {
      break;
    }
    i += 12 + len;
  }
  if (!W || !H || idat.empty()) return -3;
  if (bit_depth != 8 || interlace != 0) return -2;  // PIL fallback
  if (!dims_ok(W, H, 4)) return -3;
  int nch;
  switch (color_type) {
    case 0: nch = 1; break;   // gray
    case 2: nch = 3; break;   // rgb
    case 3: nch = 1; break;   // palette index (expanded below)
    case 4: nch = 2; break;   // gray+alpha
    case 6: nch = 4; break;   // rgba
    default: return -2;
  }
  size_t stride = (size_t)W * nch;
  std::vector<unsigned char> raw(H * (stride + 1));
  uLongf raw_len = (uLongf)raw.size();
  if (uncompress(raw.data(), &raw_len, idat.data(), (uLong)idat.size())
          != Z_OK || raw_len != raw.size())
    return -3;
  // unfilter
  std::vector<unsigned char> img(H * stride);
  for (uint32_t y = 0; y < H; y++) {
    const unsigned char* row = &raw[y * (stride + 1)];
    unsigned char filter = row[0];
    const unsigned char* src = row + 1;
    unsigned char* dst = &img[y * stride];
    const unsigned char* up = y ? &img[(y - 1) * stride] : nullptr;
    for (size_t x = 0; x < stride; x++) {
      int a = x >= (size_t)nch ? dst[x - nch] : 0;
      int b = up ? up[x] : 0;
      int c = (up && x >= (size_t)nch) ? up[x - nch] : 0;
      int v = src[x];
      switch (filter) {
        case 0: break;
        case 1: v += a; break;
        case 2: v += b; break;
        case 3: v += (a + b) / 2; break;
        case 4: v += paeth(a, b, c); break;
        default: return -3;
      }
      dst[x] = (unsigned char)v;
    }
  }
  if (color_type == 3) {  // expand palette to RGB
    if (plte.size() < 3) return -3;
    pix.resize((size_t)W * H * 3);
    for (size_t p = 0; p < (size_t)W * H; p++) {
      size_t idx = (size_t)img[p] * 3;
      if (idx + 2 >= plte.size()) return -3;
      pix[p * 3] = plte[idx];
      pix[p * 3 + 1] = plte[idx + 1];
      pix[p * 3 + 2] = plte[idx + 2];
    }
    nch = 3;
  } else {
    pix.swap(img);
  }
  *w = (int)W;
  *h = (int)H;
  *ch = nch;
  return 0;
}
#endif  // DL4J_NO_ZLIB

// Uncompressed 24/32bpp BMP (bottom-up or top-down), BGR(A) -> RGB(A).
static int bmp_decode(const std::vector<unsigned char>& buf, int* w, int* h,
                      int* ch, std::vector<unsigned char>& pix) {
  if (buf.size() < 54 || buf[0] != 'B' || buf[1] != 'M') return -2;
  auto le32 = [&](size_t o) -> int32_t {
    return (int32_t)(buf[o] | (buf[o + 1] << 8) | (buf[o + 2] << 16) |
                     ((uint32_t)buf[o + 3] << 24));
  };
  auto le16 = [&](size_t o) -> int {
    return buf[o] | (buf[o + 1] << 8);
  };
  uint32_t off = (uint32_t)le32(10);
  int32_t W = le32(18), Hs = le32(22);
  int bpp = le16(28);
  int32_t compression = le32(30);
  if (compression != 0 || (bpp != 24 && bpp != 32)) return -2;
  bool flip = Hs > 0;
  int32_t H = Hs > 0 ? Hs : -Hs;
  if (!dims_ok(W, H, 4)) return -3;
  int sch = bpp / 8;
  int64_t row_in = (((int64_t)W * sch + 3) / 4) * 4;   // 4-byte aligned
  if ((int64_t)off + row_in * H > (int64_t)buf.size()) return -3;
  int nch = sch == 4 ? 4 : 3;
  pix.resize((size_t)W * H * nch);
  for (int32_t y = 0; y < H; y++) {
    const unsigned char* src = &buf[off + (size_t)(flip ? H - 1 - y : y)
                                             * row_in];
    unsigned char* dst = &pix[(size_t)y * W * nch];
    for (int32_t x = 0; x < W; x++) {
      dst[x * nch] = src[x * sch + 2];       // R <- B position
      dst[x * nch + 1] = src[x * sch + 1];   // G
      dst[x * nch + 2] = src[x * sch];       // B <- R position
      if (nch == 4) dst[x * nch + 3] = src[x * sch + 3];
    }
  }
  *w = W;
  *h = H;
  *ch = nch;
  return 0;
}

// Binary PPM (P6, RGB) / PGM (P5, gray), maxval <= 255.
static int pnm_decode(const std::vector<unsigned char>& buf, int* w, int* h,
                      int* ch, std::vector<unsigned char>& pix) {
  if (buf.size() < 2 || buf[0] != 'P' || (buf[1] != '5' && buf[1] != '6'))
    return -2;
  int nch = buf[1] == '6' ? 3 : 1;
  size_t i = 2;
  long vals[3];
  for (int k = 0; k < 3; k++) {
    // skip whitespace + comments
    while (i < buf.size()) {
      if (std::isspace(buf[i])) { i++; continue; }
      if (buf[i] == '#') { while (i < buf.size() && buf[i] != '\n') i++; continue; }
      break;
    }
    long v = 0;
    bool any = false;
    while (i < buf.size() && std::isdigit(buf[i])) {
      v = v * 10 + (buf[i] - '0');
      i++;
      any = true;
    }
    if (!any) return -3;
    vals[k] = v;
  }
  if (i >= buf.size() || !std::isspace(buf[i])) return -3;
  i++;  // single whitespace after maxval
  long W = vals[0], H = vals[1], maxv = vals[2];
  if (maxv <= 0 || maxv > 255) return -2;
  if (!dims_ok(W, H, 3)) return -3;
  size_t need = (size_t)W * H * nch;
  if (buf.size() - i < need) return -3;
  pix.assign(buf.begin() + i, buf.begin() + i + need);
  *w = (int)W;
  *h = (int)H;
  *ch = nch;
  return 0;
}

static int decode_any(const char* path, int* w, int* h, int* ch,
                      std::vector<unsigned char>& pix) {
  // corrupt files must produce an error code, never terminate the host
  // process: guard against bad_alloc/length_error from hostile headers
  try {
    std::vector<unsigned char> buf;
    if (!read_file(path, buf)) return -1;
    int rc = png_decode(buf, w, h, ch, pix);
    if (rc != -2) return rc;
    rc = bmp_decode(buf, w, h, ch, pix);
    if (rc != -2) return rc;
    return pnm_decode(buf, w, h, ch, pix);
  } catch (...) {
    return -3;
  }
}

}  // namespace

extern "C" {

// Decode ONCE into a malloc'd buffer (interleaved u8, row-major) the
// caller frees with image_free. rc: 0 ok (fills *out/w/h/ch), -1 io
// error, -2 unsupported format (caller falls back to PIL), -3 corrupt.
int image_decode_alloc(const char* path, unsigned char** out, int* w,
                       int* h, int* ch) {
  std::vector<unsigned char> pix;
  int rc = decode_any(path, w, h, ch, pix);
  if (rc != 0) return rc;
  *out = (unsigned char*)std::malloc(pix.size() ? pix.size() : 1);
  if (!*out) return -3;
  std::memcpy(*out, pix.data(), pix.size());
  return 0;
}

void image_free(unsigned char* p) { std::free(p); }

// ---------------------------------------------------------------------------
// Version probe
// ---------------------------------------------------------------------------

int dl4j_native_abi() { return 2; }

}  // extern "C"
