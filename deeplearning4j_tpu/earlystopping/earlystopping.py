"""Early stopping.

Parity with the reference's `earlystopping/` package:
`EarlyStoppingConfiguration`, trainers (`trainer/BaseEarlyStoppingTrainer.java:46`
fit:76, `EarlyStoppingTrainer`, `EarlyStoppingGraphTrainer`), score calculators
(`scorecalc/DataSetLossCalculator[CG].java`), termination conditions
(`termination/`: MaxEpochs, MaxTime, ScoreImprovement, BestScore, MaxScore,
InvalidScore), and model savers (`saver/`: LocalFile, InMemory).
"""
from __future__ import annotations

import copy
import math
import os
import time
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = [
    "EarlyStoppingConfiguration", "EarlyStoppingResult",
    "EarlyStoppingTrainer", "EarlyStoppingGraphTrainer",
    "EarlyStoppingParallelTrainer",
    "DataSetLossCalculator", "InMemoryModelSaver", "LocalFileModelSaver",
    "MaxEpochsTerminationCondition",
    "ScoreImprovementEpochTerminationCondition",
    "BestScoreEpochTerminationCondition",
    "MaxScoreIterationTerminationCondition",
    "InvalidScoreIterationTerminationCondition",
    "MaxTimeIterationTerminationCondition",
]


# --------------------------- score calculators -----------------------------

class DataSetLossCalculator:
    """Average loss over a validation iterator (reference
    `scorecalc/DataSetLossCalculator.java`; the CG variant is the same class
    here — both model types expose `score(DataSet)`)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model) -> float:
        total, count = 0.0, 0
        self.iterator.reset()
        while self.iterator.has_next():
            ds = self.iterator.next()
            n = ds.num_examples()
            total += model.score(ds) * n
            count += n
        if count == 0:
            # an exhausted/empty validation iterator would silently score
            # 0.0 (or NaN from 0/0) — and a bogus 0.0 "best score" makes
            # early stopping save garbage as the best model. Fail loudly.
            raise ValueError(
                "DataSetLossCalculator: validation iterator yielded no "
                "examples — the score would be meaningless (0/0). Check "
                "that the iterator reset() works, is not already "
                "exhausted, and that drop_last/batch_size leave at least "
                "one batch")
        return total / count if self.average else total


# --------------------------- termination conditions ------------------------

class MaxEpochsTerminationCondition:
    def __init__(self, max_epochs: int):
        self.max_epochs = int(max_epochs)

    def terminate(self, epoch: int, score: float) -> bool:
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition:
    """Stop after N epochs with no (sufficient) improvement."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = int(max_epochs_without_improvement)
        self.min_improvement = float(min_improvement)
        self._best = math.inf
        self._since = 0

    def terminate(self, epoch: int, score: float) -> bool:
        if self._best - score > self.min_improvement:
            self._best = score
            self._since = 0
            return False
        self._since += 1
        return self._since > self.patience


class BestScoreEpochTerminationCondition:
    """Stop once the score is at least as good as a target."""

    def __init__(self, best_expected_score: float):
        self.best = float(best_expected_score)

    def terminate(self, epoch: int, score: float) -> bool:
        return score <= self.best


class MaxScoreIterationTerminationCondition:
    def __init__(self, max_score: float):
        self.max_score = float(max_score)

    def terminate(self, iteration: int, score: float) -> bool:
        return score > self.max_score


class InvalidScoreIterationTerminationCondition:
    def terminate(self, iteration: int, score: float) -> bool:
        return math.isnan(score) or math.isinf(score)


class MaxTimeIterationTerminationCondition:
    def __init__(self, max_seconds: float):
        self.max_seconds = float(max_seconds)
        self._start = None

    def terminate(self, iteration: int, score: float) -> bool:
        if self._start is None:
            self._start = time.monotonic()
            return False
        return time.monotonic() - self._start > self.max_seconds


# --------------------------- model savers ----------------------------------

class InMemoryModelSaver:
    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, model, score):
        self._best = model.clone()

    def save_latest_model(self, model, score):
        self._latest = model.clone()

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver:
    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name):
        return os.path.join(self.directory, name)

    def save_best_model(self, model, score):
        from ..util.serializer import ModelSerializer
        ModelSerializer.write_model(model, self._path("bestModel.zip"))

    def save_latest_model(self, model, score):
        from ..util.serializer import ModelSerializer
        ModelSerializer.write_model(model, self._path("latestModel.zip"))

    def get_best_model(self):
        from ..util.serializer import ModelSerializer
        return ModelSerializer.restore(self._path("bestModel.zip"))

    def get_latest_model(self):
        from ..util.serializer import ModelSerializer
        return ModelSerializer.restore(self._path("latestModel.zip"))


# --------------------------- configuration + result ------------------------

@dataclass
class EarlyStoppingConfiguration:
    score_calculator: object = None
    model_saver: object = field(default_factory=InMemoryModelSaver)
    epoch_termination_conditions: List = field(default_factory=list)
    iteration_termination_conditions: List = field(default_factory=list)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False

    class Builder:
        def __init__(self):
            self._c = EarlyStoppingConfiguration()

        def score_calculator(self, sc):
            self._c.score_calculator = sc; return self

        def model_saver(self, ms):
            self._c.model_saver = ms; return self

        def epoch_termination_conditions(self, *conds):
            self._c.epoch_termination_conditions = list(conds); return self

        def iteration_termination_conditions(self, *conds):
            self._c.iteration_termination_conditions = list(conds); return self

        def evaluate_every_n_epochs(self, n):
            self._c.evaluate_every_n_epochs = int(n); return self

        def save_last_model(self, b=True):
            self._c.save_last_model = bool(b); return self

        def build(self):
            return self._c


@dataclass
class EarlyStoppingResult:
    termination_reason: str = ""
    termination_details: str = ""
    score_vs_epoch: dict = field(default_factory=dict)
    best_model_epoch: int = -1
    best_model_score: float = math.inf
    total_epochs: int = 0
    best_model: object = None


# --------------------------- trainer ---------------------------------------

class EarlyStoppingTrainer:
    """Epoch loop with score evaluation + termination checks (reference
    `trainer/BaseEarlyStoppingTrainer.java:46`)."""

    def __init__(self, config: EarlyStoppingConfiguration, model, train_iter):
        self.config = config
        self.model = model
        self.train_iter = train_iter

    def _model_for_saving(self):
        """The object handed to the model saver (overridden by the parallel
        trainer, whose `self.model` is a ParallelTrainer)."""
        return self.model

    def _fit_one(self, ds):
        """Train on one minibatch (overridden by the parallel trainer to
        skip the per-call param publish)."""
        self.model.fit(ds)

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        result = EarlyStoppingResult()
        epoch = 0
        terminate = False
        reason, details = "", ""
        while not terminate:
            # one epoch, with iteration-level termination checks
            self.train_iter.reset()
            while self.train_iter.has_next():
                self._fit_one(self.train_iter.next())
                score = self.model.score()
                for cond in cfg.iteration_termination_conditions:
                    if cond.terminate(self.model.iteration_count, score):
                        reason = "IterationTerminationCondition"
                        details = type(cond).__name__
                        terminate = True
                        break
                if terminate:
                    break
            if terminate:
                break
            if (epoch % cfg.evaluate_every_n_epochs) == 0:
                score = (cfg.score_calculator.calculate_score(self.model)
                         if cfg.score_calculator else self.model.score())
                result.score_vs_epoch[epoch] = score
                if score < result.best_model_score:
                    result.best_model_score = score
                    result.best_model_epoch = epoch
                    cfg.model_saver.save_best_model(
                        self._model_for_saving(), score)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest_model(
                        self._model_for_saving(), score)
                for cond in cfg.epoch_termination_conditions:
                    if cond.terminate(epoch, score):
                        reason = "EpochTerminationCondition"
                        details = type(cond).__name__
                        terminate = True
                        break
            epoch += 1
        result.total_epochs = epoch
        result.termination_reason = reason or "Unknown"
        result.termination_details = details
        result.best_model = cfg.model_saver.get_best_model()
        return result


# Graph models share the same trainer logic (both expose fit/score/clone)
EarlyStoppingGraphTrainer = EarlyStoppingTrainer


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """Early stopping driving a multi-device ParallelTrainer — reference
    `deeplearning4j-scaleout-parallelwrapper/src/main/java/org/deeplearning4j/
    parallelism/EarlyStoppingParallelTrainer.java:1` (there: a
    ParallelWrapper with an AveragingIterationListener feeding the early-
    stopping loop; here the ParallelTrainer IS the model-like — `fit` runs
    the sharded step over the mesh, `score(ds)` computes validation scores
    mesh-wide, and every epoch/iteration termination condition and the
    best-model save/restore path work unchanged).

    Pass a ParallelTrainer via `trainer`, or a model plus ParallelTrainer
    kwargs (mesh/mode/strategy/...) to build one.
    """

    def __init__(self, config: EarlyStoppingConfiguration, model=None,
                 train_iter=None, trainer=None, **trainer_kwargs):
        if trainer is None:
            if model is None:
                raise ValueError("need a model or a ParallelTrainer")
            from ..parallel.trainer import ParallelTrainer
            trainer = ParallelTrainer(model, **trainer_kwargs)
        super().__init__(config, trainer, train_iter)
        self.trainer = trainer

    def _fit_one(self, ds):
        # drive the sharded step directly: ParallelTrainer.fit() would
        # _sync_back after every minibatch, and in AVERAGING mode
        # _sync_back averages the replicas — collapsing the local-SGD
        # window that averaging_frequency is supposed to control
        # (review r5); scoring/saving don't need the publish either
        # (score(ds) reads the device arrays, _model_for_saving syncs)
        if self.trainer._pipe is not None:
            self.trainer.fit(ds)
        else:
            self.trainer._fit_batch(ds)

    def _model_for_saving(self):
        tr = self.trainer
        if tr._pipe is not None:
            # stage-partitioned params live in the pipe trainer; publish
            tr._sync_back()
            return tr.model
        # non-destructive publish: SYNC rebinds the replicated trees;
        # AVERAGING binds the averaged VIEW without collapsing the live
        # replicas (tr._sync_back would average them in place, perturbing
        # the local-SGD training that continues after the save)
        return tr.publish_view()
