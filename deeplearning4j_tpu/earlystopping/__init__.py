from .earlystopping import (
    EarlyStoppingConfiguration, EarlyStoppingResult, EarlyStoppingTrainer,
    EarlyStoppingGraphTrainer, DataSetLossCalculator, InMemoryModelSaver,
    LocalFileModelSaver, MaxEpochsTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
    BestScoreEpochTerminationCondition, MaxScoreIterationTerminationCondition,
    InvalidScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
)

__all__ = [
    "EarlyStoppingConfiguration", "EarlyStoppingResult",
    "EarlyStoppingTrainer", "EarlyStoppingGraphTrainer",
    "DataSetLossCalculator", "InMemoryModelSaver", "LocalFileModelSaver",
    "MaxEpochsTerminationCondition",
    "ScoreImprovementEpochTerminationCondition",
    "BestScoreEpochTerminationCondition",
    "MaxScoreIterationTerminationCondition",
    "InvalidScoreIterationTerminationCondition",
    "MaxTimeIterationTerminationCondition",
]
