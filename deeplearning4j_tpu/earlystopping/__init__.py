from .earlystopping import (
    EarlyStoppingConfiguration, EarlyStoppingResult, EarlyStoppingTrainer,
    EarlyStoppingGraphTrainer, EarlyStoppingParallelTrainer,
    DataSetLossCalculator, InMemoryModelSaver,
    LocalFileModelSaver, MaxEpochsTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
    BestScoreEpochTerminationCondition, MaxScoreIterationTerminationCondition,
    InvalidScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
)

__all__ = [
    "EarlyStoppingConfiguration", "EarlyStoppingResult",
    "EarlyStoppingTrainer", "EarlyStoppingGraphTrainer",
    "EarlyStoppingParallelTrainer",
    "DataSetLossCalculator", "InMemoryModelSaver", "LocalFileModelSaver",
    "MaxEpochsTerminationCondition",
    "ScoreImprovementEpochTerminationCondition",
    "BestScoreEpochTerminationCondition",
    "MaxScoreIterationTerminationCondition",
    "InvalidScoreIterationTerminationCondition",
    "MaxTimeIterationTerminationCondition",
]
