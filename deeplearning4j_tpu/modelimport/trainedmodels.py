"""Pretrained model helpers (reference `trainedmodels/TrainedModels.java`,
`TrainedModelHelper.java`, `Utils/ImageNetLabels.java` — SURVEY.md §2.7).

The reference downloads DL4J-converted VGG16 weights; here the canonical
public Keras VGG16 weight file loads directly into the zoo's VGG16
topology. NHWC makes the dim-order conversion trivial (the reference needed
`TensorFlowCnnToFeedForwardPreProcessor` exactly because it was NCHW;
TF-format HWIO conv kernels and NHWC-flattened dense kernels match our
layout as-is).

Downloads go through provision.StorageDownloader's cache; offline hosts
get a FileNotFoundError naming the file to place in the cache (the test
culture runs the weight-mapping logic on small fabricated files instead).
"""
from __future__ import annotations

import json
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["TrainedModels", "TrainedModelHelper", "ImageNetLabels",
           "assign_keras_weights_in_order"]

_VGG16_WEIGHTS_URL = ("https://storage.googleapis.com/tensorflow/"
                      "keras-applications/vgg16/"
                      "vgg16_weights_tf_dim_ordering_tf_kernels.h5")
_IMAGENET_LABELS_URL = ("https://storage.googleapis.com/download.tensorflow."
                        "org/data/imagenet_class_index.json")


class TrainedModels:
    VGG16 = "vgg16"


def _natural_key(s):
    import re as _re
    return [int(t) if t.isdigit() else t for t in _re.split(r"(\d+)", s)]


def _check_order_safe(names, where: str):
    """Alphabetical h5 iteration must equal natural order at EVERY level,
    else default-named children (dense_2 ... dense_10) silently pair
    kernels/biases out of order."""
    if sorted(names) != sorted(names, key=_natural_key):
        raise ValueError(
            f"HDF5 names under {where!r} are not ordering-safe (numeric "
            "suffixes sort differently alphabetically vs naturally); use "
            "the full-model modelimport.keras path instead")


def _collect_weight_pairs(h5file) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Walk an HDF5 weights file and return (kernel, bias) pairs in
    traversal order. Handles both the legacy keras-applications layout
    (`block1_conv1/block1_conv1_W...`) and Keras 3 (`layers/<name>/vars/N`):
    any dataset with ndim >= 2 is a kernel; the next 1-D dataset in the
    same group is its bias."""
    import h5py

    pairs: List[Tuple[np.ndarray, Optional[np.ndarray]]] = []

    def walk(group):
        kernel = None
        # legacy Keras files record the TRUE order in h5 attrs
        # (layer_names at the root, weight_names per layer group) — prefer
        # that; only fall back to alphabetical iteration (with the
        # natural-order safety check) when the attrs are absent (Keras 3)
        keys = None
        attrs = getattr(group, "attrs", {})
        for attr in ("layer_names", "weight_names"):
            if attr in attrs:
                names = [n.decode() if isinstance(n, bytes) else str(n)
                         for n in attrs[attr]]
                missing = [n for n in names if n not in group]
                if missing:
                    # a truncated/renamed weights file would otherwise
                    # silently shift the remaining pairs onto wrong layers
                    raise ValueError(
                        f"HDF5 {attr} attr under "
                        f"{getattr(group, 'name', '/')!r} lists entries "
                        f"missing from the group: {missing[:5]} — the "
                        "weights file is truncated or renamed")
                keys = names
                break
        if keys is None:
            keys = list(group)
            _check_order_safe(keys, getattr(group, "name", "/"))
        for key in keys:
            item = group[key]
            if isinstance(item, h5py.Group):
                walk(item)
            else:
                arr = np.asarray(item)
                if arr.ndim >= 2:
                    if kernel is not None:
                        pairs.append((kernel, None))
                    kernel = arr
                elif arr.ndim == 1 and kernel is not None:
                    pairs.append((kernel, arr))
                    kernel = None
        if kernel is not None:
            pairs.append((kernel, None))

    walk(h5file)
    return pairs


def assign_keras_weights_in_order(net, h5_path: str):
    """Assign a Keras weight file's (kernel, bias) pairs to a
    MultiLayerNetwork's parameterized conv/dense layers in order, with
    shape validation. Returns the network."""
    import h5py

    with h5py.File(h5_path, "r") as f:
        if "layers" in f and isinstance(f["layers"], h5py.Group):
            # Keras 3 .weights.h5: group iteration is alphabetical, so
            # conv2d_10 would sort before conv2d_2 — ordered pairing is
            # unsafe. Proper model files go through modelimport.keras.
            raise ValueError(
                "Keras 3 .weights.h5 layout detected; save the FULL model "
                "(.h5/.keras) and use modelimport.keras import functions, "
                "or use a legacy keras-applications weight file here")
        # ordering safety is checked recursively at every group level
        # inside _collect_weight_pairs (nested numeric-suffixed names are
        # just as unsafe as top-level ones)
        pairs = _collect_weight_pairs(f)
    new_params = list(net.params)
    idx = 0
    for li, p in enumerate(new_params):
        if not p or "W" not in p:
            continue
        if idx >= len(pairs):
            raise ValueError(
                f"weight file has {len(pairs)} kernel/bias pairs but the "
                f"network needs more (layer {li})")
        k, b = pairs[idx]
        idx += 1
        ours = np.shape(p["W"])
        if tuple(k.shape) != tuple(ours):
            raise ValueError(
                f"layer {li}: kernel shape {k.shape} != expected {ours}")
        upd = dict(p)
        import jax.numpy as jnp
        upd["W"] = jnp.asarray(k, jnp.float32)
        if "b" in p and b is not None:
            if np.shape(p["b"]) != np.shape(b):
                raise ValueError(
                    f"layer {li}: bias shape {b.shape} != "
                    f"{np.shape(p['b'])}")
            upd["b"] = jnp.asarray(b, jnp.float32)
        new_params[li] = upd
    if idx != len(pairs):
        raise ValueError(f"weight file has {len(pairs) - idx} unused "
                         "kernel/bias pairs")
    net.params = tuple(new_params)
    return net


class TrainedModelHelper:
    """Download + load pretrained zoo models
    (`TrainedModelHelper.java` role)."""

    def __init__(self, cache_dir: Optional[str] = None):
        from ..provision import StorageDownloader
        self._dl = StorageDownloader(cache_dir)

    def load_model(self, which: str = TrainedModels.VGG16):
        if which != TrainedModels.VGG16:
            raise ValueError(f"unknown pretrained model {which!r}")
        from ..models.zoo import vgg16
        path = self._dl.fetch(_VGG16_WEIGHTS_URL)
        net = vgg16().init()
        return assign_keras_weights_in_order(net, path)


class ImageNetLabels:
    """The 1000 ImageNet class labels + decode helper
    (`Utils/ImageNetLabels.java`)."""

    def __init__(self, cache_dir: Optional[str] = None):
        from ..provision import StorageDownloader
        path = StorageDownloader(cache_dir).fetch(_IMAGENET_LABELS_URL)
        with open(path) as f:
            idx = json.load(f)
        self.labels = [idx[str(i)][1] for i in range(len(idx))]

    def label(self, i: int) -> str:
        return self.labels[i]

    def decode_predictions(self, probs: np.ndarray, top: int = 5):
        """[N, 1000] probabilities -> per-example [(label, p), ...]."""
        probs = np.asarray(probs)
        out = []
        for row in probs:
            order = np.argsort(-row)[:top]
            out.append([(self.labels[int(i)], float(row[int(i)]))
                        for i in order])
        return out
