"""Keras → deeplearning4j_tpu model import.

Parity with the reference's 14 layer mappers
(`modelimport/keras/layers/Keras{Dense,Convolution,Pooling,Lstm,Embedding,
BatchNormalization,Merge,Activation,Dropout,Flatten,GlobalPooling,Input,Loss,
ZeroPadding}.java`), `KerasSequentialModel.java` (→ MultiLayerNetwork) and
`KerasModel.java:59` (functional API → ComputationGraph). Supports Keras
2/3 HDF5 whole-model files with `channels_last` data format (our native NHWC
— the reference needed `TensorFlowCnnToFeedForwardPreProcessor` for exactly
this conversion; we don't).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from .hdf5 import Hdf5Archive
from ..nn.conf import InputType, NeuralNetConfiguration
from ..nn.conf.graph import ElementWiseVertex, MergeVertex
from ..nn.conf.preprocessors import CnnToFeedForwardPreProcessor
from ..nn.graph import ComputationGraph
from ..nn.layers import (ActivationLayer, BatchNormalization,
                         Convolution1DLayer, ConvolutionLayer,
                         ConvolutionMode, DenseLayer, DropoutLayer,
                         EmbeddingLayer, GlobalPoolingLayer, GravesLSTM,
                         LastTimeStep, OutputLayer, PoolingType,
                         Subsampling1DLayer, SubsamplingLayer,
                         ZeroPaddingLayer)
from ..nn.multilayer import MultiLayerNetwork

__all__ = [
    "KerasImportError",
    "import_keras_model_and_weights",
    "import_keras_sequential_model_and_weights",
    "import_keras_model_configuration",
    "import_keras_sequential_configuration",
]


class KerasImportError(Exception):
    """Parity with InvalidKerasConfigurationException /
    UnsupportedKerasConfigurationException."""


_ACTIVATIONS = {
    "linear": "identity", "relu": "relu", "sigmoid": "sigmoid",
    "tanh": "tanh", "softmax": "softmax", "elu": "elu", "selu": "selu",
    "gelu": "gelu", "softplus": "softplus", "softsign": "softsign",
    "hard_sigmoid": "hardsigmoid", "swish": "swish", "silu": "swish",
    "mish": "mish", "leaky_relu": "leakyrelu",
}

_LOSSES = {
    "categorical_crossentropy": "mcxent",
    "sparse_categorical_crossentropy": "mcxent",
    "binary_crossentropy": "xent",
    "mean_squared_error": "mse", "mse": "mse",
    "mean_absolute_error": "mae", "mae": "mae",
    "mean_absolute_percentage_error": "mape",
    "mean_squared_logarithmic_error": "msle",
    "hinge": "hinge", "squared_hinge": "squared_hinge",
    "poisson": "poisson", "kullback_leibler_divergence": "kl_divergence",
    "kl_divergence": "kl_divergence", "cosine_proximity": "cosine_proximity",
}


def _act(name: Optional[str]) -> str:
    if name is None:
        return "identity"
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise KerasImportError(f"Unsupported Keras activation '{name}'")


def _pair(v):
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _conv_mode(padding: str) -> str:
    if padding == "same":
        return ConvolutionMode.SAME
    if padding == "valid":
        return ConvolutionMode.TRUNCATE
    raise KerasImportError(f"Unsupported Keras padding '{padding}'")


def _check_channels_last(cfg: Dict, name: str):
    df = cfg.get("data_format", "channels_last")
    if df != "channels_last":
        raise KerasImportError(
            f"Layer '{name}': data_format='{df}' unsupported — export the "
            "Keras model with channels_last (TF dim ordering)")


def _input_type_from_shape(shape) -> InputType:
    dims = [d for d in shape if d is not None]
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    if len(dims) == 2:
        # [T, F] sequence input
        t = shape[-2]
        return InputType.recurrent(dims[-1], t)
    if len(dims) == 3:
        h, w, c = dims
        return InputType.convolutional(h, w, c)
    raise KerasImportError(f"Unsupported input shape {shape}")


# ---------------------------------------------------------------------------
# per-layer config mappers (KerasLayer.java getLayer equivalents)
# ---------------------------------------------------------------------------

def _map_dense(cfg, is_output, loss):
    act = _act(cfg.get("activation"))
    if is_output:
        if loss is None:
            loss = "mcxent" if act == "softmax" else "mse"
        return OutputLayer(n_out=int(cfg["units"]), activation=act, loss=loss,
                           has_bias=bool(cfg.get("use_bias", True)))
    return DenseLayer(n_out=int(cfg["units"]), activation=act,
                      has_bias=bool(cfg.get("use_bias", True)))


def _map_conv2d(cfg, name):
    _check_channels_last(cfg, name)
    kh, kw = _pair(cfg["kernel_size"])
    sh, sw = _pair(cfg.get("strides", (1, 1)))
    dh, dw = _pair(cfg.get("dilation_rate", (1, 1)))
    return ConvolutionLayer(
        n_out=int(cfg["filters"]), kernel_size=(kh, kw), stride=(sh, sw),
        dilation=(dh, dw), convolution_mode=_conv_mode(cfg.get("padding",
                                                               "valid")),
        activation=_act(cfg.get("activation")),
        has_bias=bool(cfg.get("use_bias", True)))


def _map_conv1d(cfg, name):
    return Convolution1DLayer(
        n_out=int(cfg["filters"]), kernel_size=int(cfg["kernel_size"][0]
        if isinstance(cfg["kernel_size"], (list, tuple))
        else cfg["kernel_size"]),
        stride=int(cfg.get("strides", [1])[0]
                   if isinstance(cfg.get("strides", 1), (list, tuple))
                   else cfg.get("strides", 1)),
        convolution_mode=_conv_mode(cfg.get("padding", "valid")),
        activation=_act(cfg.get("activation")),
        has_bias=bool(cfg.get("use_bias", True)))


def _map_pool2d(cfg, name, ptype):
    _check_channels_last(cfg, name)
    kh, kw = _pair(cfg.get("pool_size", (2, 2)))
    strides = cfg.get("strides") or (kh, kw)
    sh, sw = _pair(strides)
    return SubsamplingLayer(pooling_type=ptype, kernel_size=(kh, kw),
                            stride=(sh, sw),
                            convolution_mode=_conv_mode(cfg.get("padding",
                                                                "valid")))


def _map_pool1d(cfg, ptype):
    k = cfg.get("pool_size", 2)
    k = int(k[0]) if isinstance(k, (list, tuple)) else int(k)
    s = cfg.get("strides") or k
    s = int(s[0]) if isinstance(s, (list, tuple)) else int(s)
    return Subsampling1DLayer(pooling_type=ptype, kernel_size=k, stride=s,
                              convolution_mode=_conv_mode(cfg.get("padding",
                                                                  "valid")))


def _map_batchnorm(cfg, name):
    axis = cfg.get("axis", -1)
    if isinstance(axis, (list, tuple)):
        axis = axis[0]
    # channels_last: feature axis must be the last one
    if axis not in (-1, 3, 1):
        raise KerasImportError(
            f"Layer '{name}': BatchNormalization axis={axis} unsupported "
            "(channels_last/last-axis only)")
    return BatchNormalization(decay=float(cfg.get("momentum", 0.99)),
                              eps=float(cfg.get("epsilon", 1e-3)))


def _map_lstm(cfg):
    return (GravesLSTM(n_out=int(cfg["units"]),
                       activation=_act(cfg.get("activation", "tanh")),
                       gate_activation=_act(cfg.get("recurrent_activation",
                                                    "sigmoid")),
                       forget_gate_bias_init=0.0),
            bool(cfg.get("return_sequences", False)))


def _map_zeropad2d(cfg, name):
    _check_channels_last(cfg, name)
    p = cfg.get("padding", (1, 1))
    if isinstance(p, (list, tuple)) and len(p) == 2 \
            and isinstance(p[0], (list, tuple)):
        (t, b), (l, r) = p
        return ZeroPaddingLayer(pad=(int(t), int(b), int(l), int(r)))
    ph, pw = _pair(p)
    return ZeroPaddingLayer(pad=(ph, pw))


# ---------------------------------------------------------------------------
# weight conversion (KerasLayer.java setWeights equivalents)
# ---------------------------------------------------------------------------

def _lstm_reorder(k: np.ndarray, units: int) -> np.ndarray:
    """Keras gate order (i, f, c, o) -> ours (i, f, o, g=c), last axis."""
    i, f, c, o = np.split(k, 4, axis=-1)
    return np.concatenate([i, f, o, c], axis=-1)


def _convert_weights(layer, kw: Dict[str, np.ndarray]):
    """Returns (params_dict, state_dict) for one of our layers given keras
    weight arrays (already channels_last)."""
    if isinstance(layer, (DenseLayer, OutputLayer)):
        p = {"W": kw["kernel"]}
        if layer.has_bias:
            p["b"] = kw.get("bias", np.zeros(layer.n_out, np.float32))
        return p, {}
    if isinstance(layer, (ConvolutionLayer, Convolution1DLayer)):
        p = {"W": kw["kernel"]}  # HWIO == our layout
        if layer.has_bias:
            p["b"] = kw.get("bias", np.zeros(layer.n_out, np.float32))
        return p, {}
    if isinstance(layer, BatchNormalization):
        nf = None
        for key in ("moving_mean", "moving_variance", "gamma", "beta"):
            if key in kw:
                nf = len(kw[key])
                break
        p = {"gamma": kw.get("gamma", np.ones(nf, np.float32)),
             "beta": kw.get("beta", np.zeros(nf, np.float32))}
        s = {"mean": kw["moving_mean"], "var": kw["moving_variance"]}
        return p, s
    if isinstance(layer, GravesLSTM):
        units = layer.n_out
        kern = _lstm_reorder(kw["kernel"], units)
        rec = _lstm_reorder(kw["recurrent_kernel"], units)
        W = np.concatenate([kern, rec], axis=0)
        b = _lstm_reorder(kw.get("bias", np.zeros(4 * units, np.float32)),
                          units)
        return {"W": W, "b": b,
                "peep": np.zeros(3 * units, np.float32)}, {}
    if isinstance(layer, EmbeddingLayer):
        p = {"W": kw.get("embeddings", kw.get("kernel"))}
        if layer.has_bias:
            p["b"] = np.zeros(layer.n_out, np.float32)
        return p, {}
    raise KerasImportError(
        f"No weight converter for layer type {type(layer).__name__}")


# ---------------------------------------------------------------------------
# Sequential
# ---------------------------------------------------------------------------

def _loss_from_training_config(tc: Optional[Dict]) -> Optional[str]:
    if not tc:
        return None
    loss = tc.get("loss")
    if isinstance(loss, dict):
        loss = next(iter(loss.values())) if loss else None
    if isinstance(loss, dict):  # serialized loss object
        loss = (loss.get("config") or {}).get("name") or loss.get("class_name")
    if loss is None:
        return None
    key = str(loss).lower()
    # class-style names like "CategoricalCrossentropy"
    key = {"categoricalcrossentropy": "categorical_crossentropy",
           "binarycrossentropy": "binary_crossentropy",
           "meansquarederror": "mean_squared_error",
           "meanabsoluteerror": "mean_absolute_error"}.get(key, key)
    return _LOSSES.get(key)


def _sequential_layer_list(model_cfg: Dict) -> List[Dict]:
    layers = model_cfg["config"]
    if isinstance(layers, dict):
        layers = layers["layers"]
    return layers


def import_keras_sequential_configuration(
        model_cfg: Dict, training_cfg: Optional[Dict] = None):
    """Keras Sequential config dict -> (MultiLayerConfiguration,
    [keras_layer_name per our-layer-index or None])."""
    layers_cfg = _sequential_layer_list(model_cfg)
    loss = _loss_from_training_config(training_cfg)

    lb = NeuralNetConfiguration.builder().list()
    names: List[Optional[str]] = []
    input_type = None
    cur: Optional[InputType] = None  # shape *entering* the next layer
    idx = 0

    def add(our_layer, keras_name):
        nonlocal idx, cur
        lb.layer(our_layer)
        names.append(keras_name)
        if cur is not None:
            # n_in filling happens in ListBuilder.build(); only the shape
            # needs tracking here (for Flatten preprocessor insertion)
            cur = our_layer.output_type(cur)
        idx += 1

    seq = list(layers_cfg)
    for j, entry in enumerate(seq):
        cls = entry["class_name"]
        cfg = entry.get("config", {})
        name = cfg.get("name") or entry.get("name")
        is_last = all(e["class_name"] in ("Dropout", "Activation")
                      for e in seq[j + 1:])
        if cls == "InputLayer":
            shape = cfg.get("batch_shape") or cfg.get("batch_input_shape")
            input_type = _input_type_from_shape(shape[1:])
            cur = input_type
            continue
        if "batch_input_shape" in cfg and input_type is None:
            input_type = _input_type_from_shape(cfg["batch_input_shape"][1:])
            cur = input_type
        if cls == "Dense":
            add(_map_dense(cfg, is_last, loss), name)
        elif cls in ("Conv2D", "Convolution2D"):
            add(_map_conv2d(cfg, name), name)
        elif cls in ("Conv1D", "Convolution1D"):
            add(_map_conv1d(cfg, name), name)
        elif cls in ("MaxPooling2D", "MaxPool2D"):
            add(_map_pool2d(cfg, name, PoolingType.MAX), name)
        elif cls in ("AveragePooling2D", "AvgPool2D"):
            add(_map_pool2d(cfg, name, PoolingType.AVG), name)
        elif cls in ("MaxPooling1D",):
            add(_map_pool1d(cfg, PoolingType.MAX), name)
        elif cls in ("AveragePooling1D",):
            add(_map_pool1d(cfg, PoolingType.AVG), name)
        elif cls in ("GlobalMaxPooling2D", "GlobalMaxPooling1D"):
            add(GlobalPoolingLayer(pooling_type=PoolingType.MAX), name)
        elif cls in ("GlobalAveragePooling2D", "GlobalAveragePooling1D"):
            add(GlobalPoolingLayer(pooling_type=PoolingType.AVG), name)
        elif cls == "BatchNormalization":
            add(_map_batchnorm(cfg, name), name)
        elif cls == "Activation":
            add(ActivationLayer(activation=_act(cfg.get("activation"))), name)
        elif cls == "Dropout":
            add(DropoutLayer(dropout=1.0 - float(cfg.get("rate", 0.5))), name)
        elif cls == "Flatten":
            if cur is not None and cur.kind == "cnn":
                lb.input_pre_processor(idx, CnnToFeedForwardPreProcessor(
                    cur.height, cur.width, cur.channels))
                cur = InputType.feed_forward(cur.flat_size())
            elif cur is not None and cur.kind == "rnn":
                # our RnnToFeedForward is [B,T,F]->[B*T,F] (time-distributed),
                # NOT keras Flatten's [B,T*F] — don't silently mis-map
                raise KerasImportError(
                    "Flatten after a recurrent layer is unsupported")
            # ff input: no-op
        elif cls in ("ZeroPadding2D",):
            add(_map_zeropad2d(cfg, name), name)
        elif cls in ("LSTM", "GravesLSTM"):
            lstm, return_seq = _map_lstm(cfg)
            add(lstm, name)
            if not return_seq:
                add(LastTimeStep(), None)
        elif cls == "Embedding":
            add(EmbeddingLayer(n_in=int(cfg["input_dim"]),
                               n_out=int(cfg["output_dim"]),
                               has_bias=False), name)
        elif cls in ("Reshape", "Permute", "RepeatVector", "Masking"):
            raise KerasImportError(f"Unsupported Keras layer '{cls}'")
        else:
            raise KerasImportError(f"Unknown Keras layer '{cls}'")

    if input_type is None:
        raise KerasImportError("Model config declares no input shape")
    conf = lb.set_input_type(input_type).build()
    return conf, names


def import_keras_sequential_model_and_weights(path: str) -> MultiLayerNetwork:
    """HDF5 file -> MultiLayerNetwork with imported weights
    (`KerasModelImport.importKerasSequentialModelAndWeights`)."""
    with Hdf5Archive(path) as ar:
        model_cfg = ar.model_config()
        if model_cfg.get("class_name") != "Sequential":
            raise KerasImportError(
                f"Not a Sequential model: {model_cfg.get('class_name')} — "
                "use import_keras_model_and_weights")
        conf, names = import_keras_sequential_configuration(
            model_cfg, ar.training_config())
        model = MultiLayerNetwork(conf).init()
        params = list(model.params)
        state = list(model.state)
        for i, kname in enumerate(names):
            if kname is None or not model.layers[i].has_params:
                continue
            kw = ar.layer_weights(kname)
            if not kw:
                continue
            p, s = _convert_weights(model.layers[i], kw)
            params[i] = _shaped_like(params[i], p, kname)
            if s:
                state[i] = _shaped_like(state[i], s, kname)
        model.params = tuple(params)
        model.state = tuple(state)
        return model


def _shaped_like(ours: Dict, theirs: Dict, name: str) -> Dict:
    import jax.numpy as jnp

    out = dict(ours)
    for k, v in theirs.items():
        if k not in ours:
            raise KerasImportError(f"Layer '{name}': no param '{k}'")
        if tuple(ours[k].shape) != tuple(np.shape(v)):
            raise KerasImportError(
                f"Layer '{name}' param '{k}': shape {np.shape(v)} != "
                f"expected {tuple(ours[k].shape)}")
        out[k] = jnp.asarray(v, dtype=ours[k].dtype)
    return out


# ---------------------------------------------------------------------------
# Functional (graph)
# ---------------------------------------------------------------------------

def _inbound_names(entry) -> List[str]:
    """Parse inbound layer names from Keras 2 ([[["name",0,0,{}]]]) or
    Keras 3 ({"args": [KerasTensor...]}) inbound_nodes."""
    nodes = entry.get("inbound_nodes") or []
    names: List[str] = []

    def rec(obj):
        if isinstance(obj, dict):
            if obj.get("class_name") == "__keras_tensor__":
                names.append(obj["config"]["keras_history"][0])
                return
            for v in obj.values():
                rec(v)
        elif isinstance(obj, (list, tuple)):
            # keras-2 style ["layer_name", node_idx, tensor_idx, {...}]
            if (len(obj) >= 3 and isinstance(obj[0], str)
                    and isinstance(obj[1], int)):
                names.append(obj[0])
                return
            for v in obj:
                rec(v)

    rec(nodes)
    return names


def import_keras_model_configuration(model_cfg: Dict,
                                     training_cfg: Optional[Dict] = None):
    """Keras functional config -> (ComputationGraphConfiguration,
    {our_vertex_name: keras_layer_name})."""
    cfg = model_cfg["config"]
    layers = cfg["layers"]
    loss = _loss_from_training_config(training_cfg)

    def _names(spec):
        # input_layers/output_layers: ["name",0,0] or [["name",0,0], ...]
        if not spec:
            return []
        if isinstance(spec[0], str):
            return [spec[0]]
        return [s[0] for s in spec]

    in_names = _names(cfg.get("input_layers"))
    out_names = _names(cfg.get("output_layers"))

    gb = (NeuralNetConfiguration.builder().graph_builder()
          .add_inputs(*in_names))
    names_map: Dict[str, str] = {}
    input_types = []
    for entry in layers:
        cls = entry["class_name"]
        lcfg = entry.get("config", {})
        name = lcfg.get("name") or entry.get("name")
        inbound = _inbound_names(entry)
        if cls == "InputLayer":
            shape = lcfg.get("batch_shape") or lcfg.get("batch_input_shape")
            input_types.append(_input_type_from_shape(shape[1:]))
            continue
        is_output = name in out_names
        if cls == "Dense":
            gb.add_layer(name, _map_dense(lcfg, is_output, loss), *inbound)
        elif cls in ("Conv2D", "Convolution2D"):
            gb.add_layer(name, _map_conv2d(lcfg, name), *inbound)
        elif cls in ("MaxPooling2D", "MaxPool2D"):
            gb.add_layer(name, _map_pool2d(lcfg, name, PoolingType.MAX),
                         *inbound)
        elif cls in ("AveragePooling2D", "AvgPool2D"):
            gb.add_layer(name, _map_pool2d(lcfg, name, PoolingType.AVG),
                         *inbound)
        elif cls in ("GlobalMaxPooling2D", "GlobalMaxPooling1D"):
            gb.add_layer(name, GlobalPoolingLayer(
                pooling_type=PoolingType.MAX), *inbound)
        elif cls in ("GlobalAveragePooling2D", "GlobalAveragePooling1D"):
            gb.add_layer(name, GlobalPoolingLayer(
                pooling_type=PoolingType.AVG), *inbound)
        elif cls == "BatchNormalization":
            gb.add_layer(name, _map_batchnorm(lcfg, name), *inbound)
        elif cls == "Activation":
            gb.add_layer(name, ActivationLayer(
                activation=_act(lcfg.get("activation"))), *inbound)
        elif cls == "Dropout":
            gb.add_layer(name, DropoutLayer(
                dropout=1.0 - float(lcfg.get("rate", 0.5))), *inbound)
        elif cls in ("ZeroPadding2D",):
            gb.add_layer(name, _map_zeropad2d(lcfg, name), *inbound)
        elif cls in ("LSTM", "GravesLSTM"):
            lstm, return_seq = _map_lstm(lcfg)
            if not return_seq:
                raise KerasImportError(
                    "functional import: LSTM return_sequences=False "
                    "unsupported — wrap with return_sequences=True + pooling")
            gb.add_layer(name, lstm, *inbound)
        elif cls == "Embedding":
            gb.add_layer(name, EmbeddingLayer(
                n_in=int(lcfg["input_dim"]), n_out=int(lcfg["output_dim"]),
                has_bias=False), *inbound)
        elif cls == "Add":
            gb.add_vertex(name, ElementWiseVertex(op="add"), *inbound)
        elif cls == "Subtract":
            gb.add_vertex(name, ElementWiseVertex(op="subtract"), *inbound)
        elif cls == "Multiply":
            gb.add_vertex(name, ElementWiseVertex(op="product"), *inbound)
        elif cls == "Average":
            gb.add_vertex(name, ElementWiseVertex(op="average"), *inbound)
        elif cls == "Maximum":
            gb.add_vertex(name, ElementWiseVertex(op="max"), *inbound)
        elif cls in ("Concatenate", "Merge"):
            gb.add_vertex(name, MergeVertex(), *inbound)
        elif cls == "Flatten":
            # becomes a preprocessor on the consumer in sequential; in graphs
            # we model it as a PreprocessorVertex
            from ..nn.conf.graph import PreprocessorVertex
            gb.add_vertex(name, PreprocessorVertex(
                _FlattenPreprocessor()), *inbound)
        else:
            raise KerasImportError(f"Unknown Keras layer '{cls}'")
        names_map[name] = name

    gb.set_input_types(*input_types)
    gb.set_outputs(*out_names)
    return gb.build(), names_map


class _FlattenPreprocessor:
    """Shape-agnostic flatten (keras Flatten inside a functional graph)."""

    def apply(self, x):
        return x.reshape(x.shape[0], -1)

    def apply_mask(self, mask):
        return mask

    def output_type(self, it: InputType) -> InputType:
        return InputType.feed_forward(it.flat_size())


def import_keras_model_and_weights(path: str):
    """HDF5 file -> ComputationGraph (functional) or MultiLayerNetwork
    (sequential), with weights (`KerasModelImport.importKerasModelAndWeights`)."""
    with Hdf5Archive(path) as ar:
        model_cfg = ar.model_config()
        if model_cfg.get("class_name") == "Sequential":
            return import_keras_sequential_model_and_weights(path)
        conf, names_map = import_keras_model_configuration(
            model_cfg, ar.training_config())
        graph = ComputationGraph(conf).init()
        for vname, kname in names_map.items():
            layer = graph.conf.vertices.get(vname)
            if layer is None or not getattr(layer, "has_params", False):
                continue
            kw = ar.layer_weights(kname)
            if not kw:
                continue
            p, s = _convert_weights(layer, kw)
            graph.params[vname] = _shaped_like(graph.params[vname], p, kname)
            if s:
                graph.state[vname] = _shaped_like(graph.state[vname], s,
                                                  kname)
        return graph
