"""HDF5 archive reader for Keras files.

Parity with `keras/Hdf5Archive.java:46` (native HDF5 traversal via JavaCPP)
— here a thin h5py wrapper that understands both the Keras 2 layout
(`model_weights/<layer>/<weight_names attr>`) and the Keras 3 legacy-H5
layout (same attrs, nested groups).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Hdf5Archive"]


def _decode(v):
    return v.decode() if isinstance(v, bytes) else v


class Hdf5Archive:
    def __init__(self, path: str):
        import h5py

        self._f = h5py.File(path, "r")

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- config ---------------------------------------------------------
    def model_config(self) -> Dict:
        """Parsed JSON of the `model_config` root attribute."""
        raw = self._f.attrs.get("model_config")
        if raw is None:
            raise ValueError("No model_config attribute — not a Keras "
                             "whole-model HDF5 file")
        return json.loads(_decode(raw))

    def training_config(self) -> Optional[Dict]:
        raw = self._f.attrs.get("training_config")
        return None if raw is None else json.loads(_decode(raw))

    def keras_version(self) -> Optional[str]:
        g = self._f["model_weights"] if "model_weights" in self._f else self._f
        v = g.attrs.get("keras_version")
        return None if v is None else _decode(v)

    # -- weights --------------------------------------------------------
    def _weights_root(self):
        return (self._f["model_weights"] if "model_weights" in self._f
                else self._f)

    def layer_names(self) -> List[str]:
        root = self._weights_root()
        names = root.attrs.get("layer_names")
        if names is not None:
            return [_decode(n) for n in names]
        return list(root.keys())

    def layer_weights(self, layer_name: str) -> Dict[str, np.ndarray]:
        """{short_weight_name: array} for one layer. Short name is the final
        path component with any ':0' suffix stripped (kernel, bias, gamma,
        beta, moving_mean, moving_variance, ...)."""
        root = self._weights_root()
        if layer_name not in root:
            return {}
        g = root[layer_name]
        weight_names = g.attrs.get("weight_names")
        out: Dict[str, np.ndarray] = {}
        if weight_names is not None:
            for wn in weight_names:
                wn = _decode(wn)
                arr = np.asarray(g[wn])
                short = wn.split("/")[-1].split(":")[0]
                out[short] = arr
            return out
        # fallback: walk the group
        def walk(grp, prefix=""):
            for k in grp:
                item = grp[k]
                if hasattr(item, "keys"):
                    walk(item, prefix + k + "/")
                else:
                    out[k.split(":")[0]] = np.asarray(item)
        walk(g)
        return out
