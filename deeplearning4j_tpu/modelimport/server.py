"""Keras backend server (deeplearning4j-keras analog, SURVEY.md §2.8).

Reference: a py4j `GatewayServer` (`keras/Server.java:15-18`) exposing
`DeepLearning4jEntryPoint.fit()` (`DeepLearning4jEntryPoint.java:21`) —
reads a Keras HDF5 model + a directory of HDF5 minibatches and runs
`multiLayerNetwork.fit` (:33), with `HDF5MiniBatchDataSetIterator` and
`NDArrayHDF5Reader` doing the IO.

Here the Python<->JVM gateway is replaced with a plain HTTP JSON API
(stdlib http.server — the TPU host process *is* Python, so the server's
job is remote control, not language bridging):

    POST /fit    {"model": "/path/model.h5", "data_dir": "...",
                  "epochs": 1, "save_to": "..."}   -> trains
    POST /output {"model": "/path/model.h5", "features": [[...]]}
                                                   -> predictions
    GET  /ping                                     -> {"status": "ok"}

`/output` is served by the production inference plane (`serving/`):
models register into a ModelRegistry on first use (loaded + AOT-compiled
once, NOT per request) and concurrent requests run through compiled
bucket executables without any global lock. `/fit` still serializes
training under a fit lock. The full serving surface (multi-model
versioning, hot-swap, dynamic batching, /metrics) lives in
`deeplearning4j_tpu.serving.server`.
"""
from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

import numpy as np

from ..datasets.iterators import DataSet, DataSetIterator

__all__ = ["NDArrayHDF5Reader", "HDF5MiniBatchDataSetIterator",
           "DeepLearning4jEntryPoint", "KerasBackendServer"]


class NDArrayHDF5Reader:
    """Read one dataset from an HDF5 file into numpy
    (`NDArrayHDF5Reader.java` analog)."""

    def read(self, path: str, dataset: str = "data") -> np.ndarray:
        import h5py

        with h5py.File(path, "r") as f:
            if dataset not in f:
                # fall back to the first dataset in the file
                keys = list(f.keys())
                if not keys:
                    raise KeyError(f"{path}: empty HDF5 file")
                dataset = keys[0]
            return np.asarray(f[dataset])


class HDF5MiniBatchDataSetIterator(DataSetIterator):
    """Iterates a directory of HDF5 minibatch files
    (`HDF5MiniBatchDataSetIterator.java` analog). Each file holds
    `features` and `labels` datasets; files iterate in sorted order."""

    def __init__(self, data_dir: str, features_key: str = "features",
                 labels_key: str = "labels"):
        self.data_dir = data_dir
        self.features_key = features_key
        self.labels_key = labels_key
        self._files = sorted(
            os.path.join(data_dir, f) for f in os.listdir(data_dir)
            if f.endswith((".h5", ".hdf5")))
        if not self._files:
            raise FileNotFoundError(f"no .h5 minibatches in {data_dir!r}")
        self._pos = 0

    def reset(self):
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._files)

    def next(self) -> DataSet:
        import h5py

        path = self._files[self._pos]
        self._pos += 1
        with h5py.File(path, "r") as f:
            x = np.asarray(f[self.features_key], np.float32)
            y = np.asarray(f[self.labels_key], np.float32)
        return DataSet(x, y)

    def batch(self) -> int:
        import h5py

        with h5py.File(self._files[0], "r") as f:
            return int(f[self.features_key].shape[0])


class DeepLearning4jEntryPoint:
    """The fit/predict entry point (`DeepLearning4jEntryPoint.java:21`).

    Locking is split by what actually needs serializing: `_cache_lock`
    guards ONLY model-cache lookup/load, and `_fit_lock` serializes
    training (two concurrent fits on one network would interleave weight
    updates). Inference takes neither across the forward — concurrent
    `/output` requests run in parallel through the serving registry's
    compiled executables instead of queueing behind one global lock (the
    old design held a single lock across the entire forward pass).

    `/output` routes through a `serving.ModelRegistry`: the model loads
    and AOT-compiles once at first use, every later request hits the
    registry's compiled bucket executables, and a completed `/fit`
    hot-swaps the registry version so predictions follow training."""

    def __init__(self, registry=None):
        if registry is None:
            from ..serving import ModelRegistry
            registry = ModelRegistry()
        self.registry = registry
        self._models: Dict[str, object] = {}
        self._cache_lock = threading.Lock()
        self._fit_lock = threading.Lock()

    def _load(self, model_path: str):
        """Cache lookup/load — the ONLY thing the cache lock covers."""
        with self._cache_lock:
            net = self._models.get(model_path)
            if net is None:
                from .keras import import_keras_sequential_model_and_weights
                net = self._models[model_path] = \
                    import_keras_sequential_model_and_weights(model_path)
            return net

    def fit(self, model_path: str, data_dir: str, epochs: int = 1,
            save_to: Optional[str] = None) -> Dict:
        net = self._load(model_path)
        with self._fit_lock:
            it = HDF5MiniBatchDataSetIterator(data_dir)
            net.fit(it, epochs=int(epochs))
            if save_to:
                from ..util.serializer import ModelSerializer
                ModelSerializer.write_model(net, save_to)
            result = {"status": "ok", "score": float(net.score()),
                      "iterations": int(net.iteration_count)}
        if model_path in self.registry:
            # hot-swap the served snapshot so /output reflects the fit;
            # same architecture -> the registry reuses its executables.
            # Keep the served input shape: configs without a derivable
            # one were registered with a request-inferred shape, and the
            # swap must not fail a fit that succeeded
            served = self.registry.get(model_path)
            self.registry.swap(model_path, net,
                               input_shape=served.example_shape)
        return result

    def _ensure_served(self, model_path: str, net, features: np.ndarray):
        from ..serving import ServingError
        try:
            return self.registry.ensure(model_path, net)
        except ServingError:
            # model config declares no fixed input shape (some imported
            # configs) — fall back to the request's trailing shape
            return self.registry.ensure(model_path, net,
                                        input_shape=features.shape[1:])

    def output(self, model_path: str, features) -> np.ndarray:
        net = self._load(model_path)
        features = np.asarray(features, np.float32)
        if features.ndim == 1:
            features = features[None]
        v = self._ensure_served(model_path, net, features)
        if tuple(features.shape[1:]) != v.example_shape:
            # the legacy contract accepts shape-varying requests (e.g.
            # variable-length sequences into an RNN import); the serving
            # plane compiles fixed buckets, so off-shape requests keep
            # the old direct net.output() path (jit retraces per shape,
            # exactly as before — and still with no global lock)
            return np.asarray(net.output(features))
        out, _ = self.registry.predict(model_path, features)
        return out


class KerasBackendServer:
    """HTTP control server wrapping the entry point (`Server.java:15`).

    Error semantics match the serving plane (`serving/server.py`): a
    client mistake (malformed JSON, missing keys, bad shapes, nonexistent
    model path) is 400 with a structured `{"error": ...}` body; 500 is
    reserved for genuine server faults."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registry=None):
        from ..serving import ServingError
        from ..serving.server import ClientError, parse_json_body, require

        entry = self.entry_point = DeepLearning4jEntryPoint(
            registry=registry)
        self.registry = entry.registry

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet
                pass

            def _reply(self, code: int, payload: Dict):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/ping":
                    self._reply(200, {"status": "ok"})
                else:
                    self._reply(404, {"error": f"unknown path {self.path}"})

            def do_POST(self):
                try:
                    if self.path not in ("/fit", "/output"):
                        self._reply(404,
                                    {"error": f"unknown path {self.path}"})
                        return
                    body = parse_json_body(self)
                    if self.path == "/fit":
                        out = entry.fit(require(body, "model"),
                                        require(body, "data_dir"),
                                        body.get("epochs", 1),
                                        body.get("save_to"))
                        self._reply(200, out)
                    else:
                        preds = entry.output(require(body, "model"),
                                             require(body, "features"))
                        self._reply(200, {"output": preds.tolist()})
                except (ClientError, ServingError, FileNotFoundError,
                        ValueError, TypeError) as e:
                    self._reply(400, {"error": f"{type(e).__name__}: {e}"})
                except Exception as e:   # genuine server fault
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "KerasBackendServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


def main(argv=None):
    """`python -m deeplearning4j_tpu.modelimport.server --port 8998` —
    the reference's `Server.main` (py4j gateway on a fixed port)."""
    import argparse
    import time

    ap = argparse.ArgumentParser(prog="deeplearning4j_tpu.modelimport.server")
    ap.add_argument("--port", type=int, default=8998)
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args(argv)
    srv = KerasBackendServer(host=args.host, port=args.port).start()
    print(f"Keras backend server on http://{srv.host}:{srv.port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
