"""Keras backend server (deeplearning4j-keras analog, SURVEY.md §2.8).

Reference: a py4j `GatewayServer` (`keras/Server.java:15-18`) exposing
`DeepLearning4jEntryPoint.fit()` (`DeepLearning4jEntryPoint.java:21`) —
reads a Keras HDF5 model + a directory of HDF5 minibatches and runs
`multiLayerNetwork.fit` (:33), with `HDF5MiniBatchDataSetIterator` and
`NDArrayHDF5Reader` doing the IO.

Here the Python<->JVM gateway is replaced with a plain HTTP JSON API
(stdlib http.server — the TPU host process *is* Python, so the server's
job is remote control, not language bridging):

    POST /fit    {"model": "/path/model.h5", "data_dir": "...",
                  "epochs": 1, "save_to": "..."}   -> trains
    POST /output {"model": "/path/model.h5", "features": [[...]]}
                                                   -> predictions
    GET  /ping                                     -> {"status": "ok"}
"""
from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

import numpy as np

from ..datasets.iterators import DataSet, DataSetIterator

__all__ = ["NDArrayHDF5Reader", "HDF5MiniBatchDataSetIterator",
           "DeepLearning4jEntryPoint", "KerasBackendServer"]


class NDArrayHDF5Reader:
    """Read one dataset from an HDF5 file into numpy
    (`NDArrayHDF5Reader.java` analog)."""

    def read(self, path: str, dataset: str = "data") -> np.ndarray:
        import h5py

        with h5py.File(path, "r") as f:
            if dataset not in f:
                # fall back to the first dataset in the file
                keys = list(f.keys())
                if not keys:
                    raise KeyError(f"{path}: empty HDF5 file")
                dataset = keys[0]
            return np.asarray(f[dataset])


class HDF5MiniBatchDataSetIterator(DataSetIterator):
    """Iterates a directory of HDF5 minibatch files
    (`HDF5MiniBatchDataSetIterator.java` analog). Each file holds
    `features` and `labels` datasets; files iterate in sorted order."""

    def __init__(self, data_dir: str, features_key: str = "features",
                 labels_key: str = "labels"):
        self.data_dir = data_dir
        self.features_key = features_key
        self.labels_key = labels_key
        self._files = sorted(
            os.path.join(data_dir, f) for f in os.listdir(data_dir)
            if f.endswith((".h5", ".hdf5")))
        if not self._files:
            raise FileNotFoundError(f"no .h5 minibatches in {data_dir!r}")
        self._pos = 0

    def reset(self):
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._files)

    def next(self) -> DataSet:
        import h5py

        path = self._files[self._pos]
        self._pos += 1
        with h5py.File(path, "r") as f:
            x = np.asarray(f[self.features_key], np.float32)
            y = np.asarray(f[self.labels_key], np.float32)
        return DataSet(x, y)

    def batch(self) -> int:
        import h5py

        with h5py.File(self._files[0], "r") as f:
            return int(f[self.features_key].shape[0])


class DeepLearning4jEntryPoint:
    """The fit/predict entry point (`DeepLearning4jEntryPoint.java:21`).
    A single lock serializes model loading and training: the server is
    threaded for request handling, but two concurrent fits on one network
    would interleave weight updates."""

    def __init__(self):
        self._models: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _load_locked(self, model_path: str):
        if model_path not in self._models:
            from .keras import import_keras_sequential_model_and_weights
            self._models[model_path] = \
                import_keras_sequential_model_and_weights(model_path)
        return self._models[model_path]

    def fit(self, model_path: str, data_dir: str, epochs: int = 1,
            save_to: Optional[str] = None) -> Dict:
        with self._lock:
            net = self._load_locked(model_path)
            it = HDF5MiniBatchDataSetIterator(data_dir)
            net.fit(it, epochs=int(epochs))
            if save_to:
                from ..util.serializer import ModelSerializer
                ModelSerializer.write_model(net, save_to)
            return {"status": "ok", "score": float(net.score()),
                    "iterations": int(net.iteration_count)}

    def output(self, model_path: str, features: np.ndarray) -> np.ndarray:
        with self._lock:
            net = self._load_locked(model_path)
            return np.asarray(net.output(np.asarray(features, np.float32)))


class KerasBackendServer:
    """HTTP control server wrapping the entry point (`Server.java:15`)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        entry = self.entry_point = DeepLearning4jEntryPoint()

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # quiet
                pass

            def _reply(self, code: int, payload: Dict):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/ping":
                    self._reply(200, {"status": "ok"})
                else:
                    self._reply(404, {"error": "unknown path"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    if self.path == "/fit":
                        out = entry.fit(body["model"], body["data_dir"],
                                        body.get("epochs", 1),
                                        body.get("save_to"))
                        self._reply(200, out)
                    elif self.path == "/output":
                        preds = entry.output(
                            body["model"], np.asarray(body["features"],
                                                      np.float32))
                        self._reply(200, {"output": preds.tolist()})
                    else:
                        self._reply(404, {"error": "unknown path"})
                except Exception as e:   # surface errors to the client
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "KerasBackendServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


def main(argv=None):
    """`python -m deeplearning4j_tpu.modelimport.server --port 8998` —
    the reference's `Server.main` (py4j gateway on a fixed port)."""
    import argparse
    import time

    ap = argparse.ArgumentParser(prog="deeplearning4j_tpu.modelimport.server")
    ap.add_argument("--port", type=int, default=8998)
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args(argv)
    srv = KerasBackendServer(host=args.host, port=args.port).start()
    print(f"Keras backend server on http://{srv.host}:{srv.port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
