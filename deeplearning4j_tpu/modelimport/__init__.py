"""Keras model import (L8 interop).

Capability parity with `deeplearning4j-modelimport` — the reference's
flagship interop: HDF5 → network configuration + weights
(`keras/KerasModelImport.java`, `KerasModel.java:59`,
`KerasSequentialModel.java`, `Hdf5Archive.java:46`, 14 `layers/Keras*.java`
mappers). TPU-native: h5py instead of the JavaCPP HDF5 bridge, our NHWC
layout means TF `channels_last` weights import without the dim-order
gymnastics of `TensorFlowCnnToFeedForwardPreProcessor.java`.
"""
from .hdf5 import Hdf5Archive
from .keras import (KerasImportError, import_keras_model_and_weights,
                    import_keras_model_configuration,
                    import_keras_sequential_configuration,
                    import_keras_sequential_model_and_weights)

__all__ = [
    "Hdf5Archive", "KerasImportError",
    "import_keras_model_and_weights",
    "import_keras_sequential_model_and_weights",
    "import_keras_model_configuration",
    "import_keras_sequential_configuration",
]
