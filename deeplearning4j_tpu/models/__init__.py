from . import zoo

__all__ = ["zoo"]
