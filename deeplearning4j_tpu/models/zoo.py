"""Model zoo — the BASELINE.md configs.

LeNet-MNIST mirrors the reference's canonical MNIST CNN example topology
(Conv 5x5x20 → maxpool → Conv 5x5x50 → maxpool → Dense 500 → softmax 10),
the config DL4J ships in its examples and the first BASELINE config.
"""
from __future__ import annotations

import time

import numpy as np

from ..nn.conf import InputType, NeuralNetConfiguration
from ..nn.layers import (ConvolutionLayer, ConvolutionMode, DenseLayer,
                         OutputLayer, PoolingType, SubsamplingLayer)
from ..nn.multilayer import MultiLayerNetwork
from ..nn.updaters import Adam, Nesterovs

__all__ = ["lenet_mnist", "bench_lenet", "bench_lenet_ragged",
           "bench_lenet_superstep", "mlp_mnist",
           "char_rnn", "bench_char_rnn", "resnet50", "bench_resnet50",
           "vgg16", "vgg19", "alexnet", "googlenet", "sample_characters"]


def lenet_mnist(seed: int = 42, updater=None) -> MultiLayerNetwork:
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(updater or Nesterovs(learning_rate=0.01, momentum=0.9))
            .l2(5e-4)
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5),
                                    stride=(1, 1), activation="identity",
                                    convolution_mode=ConvolutionMode.TRUNCATE))
            .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                    kernel_size=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5),
                                    stride=(1, 1), activation="identity"))
            .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                    kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())
    return MultiLayerNetwork(conf)


def mlp_mnist(seed: int = 42) -> MultiLayerNetwork:
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(n_out=1024, activation="relu"))
            .layer(DenseLayer(n_out=1024, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(784))
            .build())
    return MultiLayerNetwork(conf)


def char_rnn(vocab_size: int = 77, lstm_size: int = 200, seq_len: int = 64,
             seed: int = 42, tbptt: int = 50) -> MultiLayerNetwork:
    """GravesLSTM char-RNN (BASELINE config #3) — the reference's
    char-modelling example topology: 2xLSTM + RnnOutputLayer, TBPTT."""
    from ..nn.conf import BackpropType
    from ..nn.layers import GravesLSTM, RnnOutputLayer

    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Adam(2e-3))
            .list()
            .layer(GravesLSTM(n_out=lstm_size, activation="tanh"))
            .layer(GravesLSTM(n_out=lstm_size, activation="tanh"))
            .layer(RnnOutputLayer(n_out=vocab_size, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(vocab_size, seq_len))
            .backprop_type(BackpropType.TRUNCATED_BPTT)
            .t_bptt_forward_length(tbptt)
            .t_bptt_backward_length(tbptt)
            .build())
    return MultiLayerNetwork(conf)


def bench_char_rnn(batch: int = 64, seq_len: int = 128, steps: int = 240,
                   warmup: int = 3, vocab: int = 77):
    """tokens/sec for char-RNN training (BASELINE config #3). Steps are
    sized so the one-time dispatch+sync round trip through the remote
    tunnel (~95 ms measured, an attach-mode artifact, not chip time)
    amortizes below ~5%: the number reports training throughput, not RPC
    latency. Device-time cross-check via the profiler: see BASELINE.md."""
    from ..datasets.iterators import DataSet

    model = char_rnn(vocab_size=vocab, seq_len=seq_len, tbptt=64).init()
    r = np.random.default_rng(0)
    idx = r.integers(0, vocab, (batch, seq_len))
    x = np.eye(vocab, dtype=np.float32)[idx]
    y = np.eye(vocab, dtype=np.float32)[np.roll(idx, -1, axis=1)]
    import jax
    import jax.numpy as jnp

    # device-resident [T,...] batches: transfer ONE batch over the link and
    # broadcast on device (the tunnel, not the chip, is the bottleneck);
    # warmup with the SAME scan length (the epoch fn specializes on T)
    xs = jnp.broadcast_to(jax.device_put(x), (steps,) + x.shape)
    ys = jnp.broadcast_to(jax.device_put(y), (steps,) + y.shape)
    model.fit_scan_arrays(xs, ys)
    float(model.score())  # host materialization: a real sync barrier even on
    # remote-tunnel backends where block_until_ready can no-op
    t0 = time.perf_counter()
    model.fit_scan_arrays(xs, ys)
    float(model.score())
    dt = time.perf_counter() - t0
    return batch * seq_len * steps / dt, "charRNN-tokens"


def resnet50(n_classes: int = 1000, image: int = 224, seed: int = 42,
             updater=None, blocks=(3, 4, 6, 3), width: int = 64,
             compute_dtype: str | None = "bfloat16",
             remat: str | None = None,
             activation_store_dtype: str | None = None):
    """ResNet-50 as a ComputationGraph (BASELINE config #2): bottleneck
    residual blocks via ElementWiseVertex(add) — the reference expresses
    ResNet the same way with its vertex API. NHWC, bottleneck 1-3-1 convs,
    BN+ReLU. Default policy: bf16 compute on the MXU, f32 master weights."""
    from ..nn.conf import InputType
    from ..nn.conf.graph import ElementWiseVertex
    from ..nn.graph import ComputationGraph
    from ..nn.layers import (ActivationLayer, BatchNormalization,
                             GlobalPoolingLayer)

    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .updater(updater or Adam(1e-3))
         .weight_init("relu")
         .compute_dtype(compute_dtype)
         .remat(remat)
         .activation_store_dtype(activation_store_dtype)
         .graph_builder()
         .add_inputs("input")
         .set_input_types(InputType.convolutional(image, image, 3)))

    def conv_bn_relu(name, inp, n_out, k, s, relu=True):
        b.add_layer(f"{name}_conv",
                    ConvolutionLayer(n_out=n_out, kernel_size=(k, k),
                                     stride=(s, s), activation="identity",
                                     convolution_mode=ConvolutionMode.SAME,
                                     has_bias=False), inp)
        b.add_layer(f"{name}_bn",
                    BatchNormalization(activation="relu" if relu else "identity"),
                    f"{name}_conv")
        return f"{name}_bn"

    top = conv_bn_relu("stem", "input", width, 7, 2)
    b.add_layer("stem_pool",
                SubsamplingLayer(pooling_type=PoolingType.MAX,
                                 kernel_size=(3, 3), stride=(2, 2),
                                 convolution_mode=ConvolutionMode.SAME),
                top)
    top = "stem_pool"

    ch = width
    for stage, n_blocks in enumerate(blocks):
        out_ch = ch * 4
        for blk in range(n_blocks):
            name = f"s{stage}b{blk}"
            stride = 2 if (blk == 0 and stage > 0) else 1
            t1 = conv_bn_relu(f"{name}_1", top, ch, 1, stride)
            t2 = conv_bn_relu(f"{name}_2", t1, ch, 3, 1)
            t3 = conv_bn_relu(f"{name}_3", t2, out_ch, 1, 1, relu=False)
            if blk == 0:
                sc = conv_bn_relu(f"{name}_sc", top, out_ch, 1, stride,
                                  relu=False)
            else:
                sc = top
            b.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), t3, sc)
            b.add_layer(f"{name}_relu", ActivationLayer(activation="relu"),
                        f"{name}_add")
            top = f"{name}_relu"
        ch *= 2

    b.add_layer("avgpool", GlobalPoolingLayer(pooling_type=PoolingType.AVG),
                top)
    b.add_layer("fc", OutputLayer(n_out=n_classes, activation="softmax",
                                  loss="mcxent"), "avgpool")
    b.set_outputs("fc")
    return ComputationGraph(b.build())


def bench_resnet50(batch: int = 256, steps: int = 30,
                   image: int = 224, n_classes: int = 1000,
                   compute_dtype: str | None = "bfloat16"):
    """samples/sec for ResNet-50 ImageNet-shaped training (BASELINE #2):
    the [steps]-pass runs as one device-resident `fit_scan_arrays`
    dispatch, so the number measures the training step, not the host link
    or per-step dispatch. Warmup = one full same-length scan (the epoch fn
    specializes on T). Round-4 ablation winners applied (see BASELINE.md
    ablation table): Adam m/v stored bf16, bf16 input window (the model
    casts inputs to the compute dtype at entry anyway — pre-casting halves
    the scanned window's HBM read), 30-step window (tunnel round trip
    amortizes to ~3%)."""
    import jax
    import jax.numpy as jnp

    model = resnet50(image=image, n_classes=n_classes,
                     compute_dtype=compute_dtype,
                     updater=Adam(1e-3, state_dtype="bfloat16")).init()
    r = np.random.default_rng(0)
    x = r.normal(size=(batch, image, image, 3)).astype(np.float32)
    y = np.eye(n_classes, dtype=np.float32)[r.integers(0, n_classes, batch)]
    if compute_dtype is not None:
        x = x.astype(jnp.dtype(compute_dtype))
    # device-resident [T,...] batches: transfer ONE batch over the link and
    # broadcast on device; the whole [steps]-pass runs as one scan dispatch
    # (same device-resident policy as the LeNet/charRNN benches)
    xs = jnp.broadcast_to(jax.device_put(x), (steps,) + x.shape)
    ys = jnp.broadcast_to(jax.device_put(y), (steps,) + y.shape)
    model.fit_scan_arrays(xs, ys)
    float(model.score())  # host materialization: a real sync barrier even on
    # remote-tunnel backends where block_until_ready can no-op
    t0 = time.perf_counter()
    model.fit_scan_arrays(xs, ys)
    float(model.score())
    dt = time.perf_counter() - t0
    return batch * steps / dt, "ResNet50-ImageNet"


def _vgg(cfg, n_classes, image, seed, updater) -> MultiLayerNetwork:
    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .updater(updater or Nesterovs(learning_rate=0.01, momentum=0.9))
         .weight_init("relu")
         .list())
    for v in cfg:
        if v == "M":
            b.layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                     kernel_size=(2, 2), stride=(2, 2)))
        else:
            b.layer(ConvolutionLayer(n_out=v, kernel_size=(3, 3),
                                     stride=(1, 1), activation="relu",
                                     convolution_mode=ConvolutionMode.SAME))
    b.layer(DenseLayer(n_out=4096, activation="relu"))
    b.layer(DenseLayer(n_out=4096, activation="relu"))
    b.layer(OutputLayer(n_out=n_classes, activation="softmax", loss="mcxent"))
    conf = b.set_input_type(InputType.convolutional(image, image, 3)).build()
    return MultiLayerNetwork(conf)


def vgg16(n_classes: int = 1000, image: int = 224, seed: int = 42,
          updater=None) -> MultiLayerNetwork:
    """VGG-16 (BASELINE config #5 uses this for multi-host data parallel).
    Mirrors the reference's TrainedModels.VGG16 topology."""
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
           512, 512, 512, "M", 512, 512, 512, "M"]
    return _vgg(cfg, n_classes, image, seed, updater)


def bench_lenet(batch: int = 512, steps: int = 800, warmup: int = 5):
    """samples/sec for LeNet-MNIST training steps (BASELINE config #1).
    Step count amortizes the fixed ~95 ms tunnel dispatch+sync round trip
    (attach-mode artifact) below ~5% — see bench_char_rnn note."""
    from ..datasets.iterators import DataSet

    model = lenet_mnist().init()
    r = np.random.default_rng(0)
    x = r.normal(size=(batch, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[r.integers(0, 10, batch)]
    import jax
    import jax.numpy as jnp

    # device-resident [T,...] batches: transfer ONE batch over the link and
    # broadcast on device (the tunnel, not the chip, is the bottleneck);
    # warmup with the SAME scan length (the epoch fn specializes on T)
    xs = jnp.broadcast_to(jax.device_put(x), (steps,) + x.shape)
    ys = jnp.broadcast_to(jax.device_put(y), (steps,) + y.shape)
    model.fit_scan_arrays(xs, ys)
    float(model.score())  # host materialization: a real sync barrier even on
    # remote-tunnel backends where block_until_ready can no-op
    t0 = time.perf_counter()
    model.fit_scan_arrays(xs, ys)
    float(model.score())
    dt = time.perf_counter() - t0
    return batch * steps / dt, "LeNet-MNIST"


def bench_lenet_dispatch(batch: int = 512, steps: int = 300, warmup: int = 20):
    """samples/sec for LeNet through the PER-BATCH fit() path (one jitted
    step dispatch per batch — the reference's actual usage pattern,
    `MultiLayerNetwork.fit(DataSetIterator)`). Complements the
    device-resident fit_scan number: together they track both the
    dispatch path and the scan fast path (BASELINE row 1)."""
    from ..datasets.iterators import DataSet

    model = lenet_mnist().init()
    r = np.random.default_rng(0)
    x = r.normal(size=(batch, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[r.integers(0, 10, batch)]
    ds = DataSet(x, y)   # device_tuple cache: transfer paid once
    for _ in range(warmup):
        model.fit(ds)
    float(model.score())
    t0 = time.perf_counter()
    for _ in range(steps):
        model.fit(ds)
    float(model.score())
    dt = time.perf_counter() - t0
    return batch * steps / dt, "LeNet-MNIST-dispatch"


def bench_char_rnn_dispatch(batch: int = 64, seq_len: int = 128,
                            steps: int = 150, warmup: int = 10,
                            vocab: int = 77):
    """tokens/sec for char-RNN through the per-batch fit() path (TBPTT
    chunking included) — the dispatch-path complement of bench_char_rnn."""
    from ..datasets.iterators import DataSet

    model = char_rnn(vocab_size=vocab, seq_len=seq_len, tbptt=64).init()
    r = np.random.default_rng(0)
    idx = r.integers(0, vocab, (batch, seq_len))
    x = np.eye(vocab, dtype=np.float32)[idx]
    y = np.eye(vocab, dtype=np.float32)[np.roll(idx, -1, axis=1)]
    ds = DataSet(x, y)
    for _ in range(warmup):
        model.fit(ds)
    float(model.score())
    t0 = time.perf_counter()
    for _ in range(steps):
        model.fit(ds)
    float(model.score())
    dt = time.perf_counter() - t0
    return batch * seq_len * steps / dt, "charRNN-tokens-dispatch"


def bench_lenet_ragged(batch: int = 256, full_batches: int = 5,
                       ragged: int = 255, epochs: int = 4, warmup: int = 1):
    """Ragged-final-batch LeNet through the per-batch fit() path, three
    ways — the input-pipeline before/after artifact (ISSUE 3):

      serial           plain iterator: the ragged tail costs a SECOND
                       nn/train_step compile (the HEAD pathology)
      padded           fit(pad_ragged=True): weight-zero padding, ONE
                       compile, pad_fraction reported
      padded_prefetch  + fit(prefetch=True): device_tuple() staged one
                       batch ahead on a background thread

    Each variant runs under its OWN telemetry session on a FRESH model so
    compile counts attribute cleanly. Timing excludes the warmup epoch
    (compiles); samples/sec counts REAL rows only, so serial and padded
    are directly comparable."""
    from ..datasets.iterators import ArrayDataSetIterator
    from ..telemetry import runtime as telemetry_runtime
    from ..telemetry.runtime import TelemetrySession

    n = batch * full_batches + ragged
    r = np.random.default_rng(0)
    x = r.normal(size=(n, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[r.integers(0, 10, n)]
    variants = (("serial", {}),
                ("padded", dict(pad_ragged=True)),
                ("padded_prefetch", dict(pad_ragged=True, prefetch=True)))
    state = {}
    for name, kw in variants:   # per-variant session + model: compile
        sess = TelemetrySession()   # counts attribute cleanly
        model = lenet_mnist().init()
        it = ArrayDataSetIterator(x, y, batch_size=batch)
        with telemetry_runtime.enabled(sess):
            model.fit(it, epochs=warmup, **kw)   # pays the compiles
            float(model.score())
        state[name] = (sess, model, it, kw, [])
    rounds = []
    for _ in range(3):   # ALTERNATING reps: clock/thermal drift hits every
        times = {}       # variant equally, not just the last one
        for name, kw in variants:
            sess, model, it, kw, reps = state[name]
            with telemetry_runtime.enabled(sess):
                t0 = time.perf_counter()
                model.fit(it, epochs=epochs, **kw)
                float(model.score())
                times[name] = time.perf_counter() - t0
                reps.append(times[name])
        rounds.append(times)
    out = {}
    steps = (full_batches + 1) * epochs
    for name, _ in variants:
        sess, model, it, kw, reps = state[name]
        reps.sort()
        dt = reps[len(reps) // 2]
        rec = {"samples_per_s": round(n * epochs / dt, 1),
               "steps_per_s": round(steps / dt, 2),
               "steps_per_s-spread": [round(steps / reps[-1], 2),
                                      round(steps / reps[0], 2)],
               "train_step_compiles": sess.compiles.count("nn/train_step")}
        pipe = sess.pipeline_summary()
        if pipe:
            rec["pipeline"] = pipe
        out[name] = rec
    # paired per-round comparison: each round's variants run back-to-back,
    # so the host's load/thermal drift (which swamps a sub-1% effect across
    # minutes) cancels; ratio > 1 means prefetch was faster that round
    ratios = sorted(r["serial"] / r["padded_prefetch"] for r in rounds)
    out["prefetch_vs_serial_paired_ratio"] = round(
        ratios[len(ratios) // 2], 4)
    out["prefetch_ge_serial"] = ratios[len(ratios) // 2] >= 1.0
    return out


def _paired_superstep(model_fn, x, y, batch, epochs, warmup, superstep):
    """Alternating paired reps of fit(superstep=K) vs fit(superstep=1) —
    the SAME `fit(iterator)` call, only the knob differs, so the paired
    ratio isolates exactly the host-dispatch floor the superstep removes.
    Per-variant telemetry session + fresh model (compile counts attribute
    cleanly, same protocol as bench_lenet_ragged)."""
    from ..datasets.iterators import ArrayDataSetIterator
    from ..nn.superstep import auto_superstep_k
    from ..telemetry import runtime as telemetry_runtime
    from ..telemetry.runtime import TelemetrySession

    n = x.shape[0]
    variants = (("perbatch", 1), ("superstep", superstep))
    state = {}
    for name, k in variants:
        sess = TelemetrySession()
        model = model_fn()
        it = ArrayDataSetIterator(x, y, batch_size=batch)
        with telemetry_runtime.enabled(sess):
            model.fit(it, epochs=warmup, superstep=k)   # pays the compiles
            float(model.score())
        state[name] = (sess, model, it, k, [], [])
    rounds = []
    for _ in range(3):   # ALTERNATING reps: drift hits every variant
        times = {}
        for name, _k in variants:
            sess, model, it, k, reps, disp = state[name]
            with telemetry_runtime.enabled(sess):
                d0 = sess.span_totals().get("device/dispatch", 0.0)
                t0 = time.perf_counter()
                model.fit(it, epochs=epochs, superstep=k)
                float(model.score())
                dt = time.perf_counter() - t0
                disp.append(sess.span_totals().get("device/dispatch", 0.0)
                            - d0)
            times[name] = dt
            reps.append(dt)
        rounds.append(times)
    out = {}
    for name, _k in variants:
        sess, model, it, k, reps, disp = state[name]
        order = sorted(range(len(reps)), key=lambda i: reps[i])
        mid = order[len(order) // 2]
        dt = reps[mid]
        out[name] = {
            "samples_per_s": round(n * epochs / dt, 1),
            "samples_per_s-spread": [round(n * epochs / max(reps), 1),
                                     round(n * epochs / min(reps), 1)],
            # host seconds inside dispatch calls / wall — the r05
            # device/dispatch attribution, expected to collapse under
            # the superstep (one dispatch per window, not per batch)
            "dispatch_share": round(disp[mid] / dt, 4),
            "superstep_compiles": sess.compiles.count("nn/superstep"),
            "train_step_compiles": sess.compiles.count("nn/train_step"),
        }
    out["superstep_k"] = (auto_superstep_k(x[:batch].nbytes + y[:batch].nbytes)
                          if superstep == "auto" else superstep)
    ratios = sorted(r["perbatch"] / r["superstep"] for r in rounds)
    out["superstep_vs_perbatch_paired_ratio"] = round(
        ratios[len(ratios) // 2], 4)
    out["paired_ratios"] = [round(v, 4) for v in ratios]
    return out


def bench_lenet_superstep(batch: int = 512, n_batches: int = 24,
                          epochs: int = 3, warmup: int = 1,
                          superstep="auto"):
    """Per-batch-API training through the device-resident superstep loop
    vs the K=1 per-batch dispatch loop (ISSUE 11), alternating paired
    reps: the headline LeNet config (the r05 per-batch-vs-fit_scan gap)
    plus a dispatch-bound mlp128 config.

    CPU-sandbox caveat (same class of artifact the serving bench
    documents): XLA:CPU executes convolutions inside a `lax.scan` body
    markedly slower than standalone, so on a CPU host the LeNet pairing
    can INVERT — the seed's `fit_scan_arrays` shows the identical
    inversion, while on the accelerator r05 measured that same scan at
    ~6.7x the per-batch path. The mlp128 pairing is dispatch-bound and
    shows the superstep win on any host; on accelerator hardware both do."""
    r = np.random.default_rng(0)
    n = batch * n_batches
    x = r.normal(size=(n, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[r.integers(0, 10, n)]
    out = _paired_superstep(lambda: lenet_mnist().init(), x, y, batch,
                            epochs, warmup, superstep)

    def mlp128():
        from ..nn.conf import NeuralNetConfiguration
        conf = (NeuralNetConfiguration.builder()
                .seed(7).updater(Adam(1e-3)).list()
                .layer(DenseLayer(n_out=128, activation="relu"))
                .layer(OutputLayer(n_out=10, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(64))
                .build())
        from ..nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(conf).init()

    b2 = 64
    x2 = r.normal(size=(b2 * 64, 64)).astype(np.float32)
    y2 = np.eye(10, dtype=np.float32)[r.integers(0, 10, b2 * 64)]
    out["mlp128"] = _paired_superstep(mlp128, x2, y2, b2, epochs, warmup,
                                      superstep)
    return out


def alexnet(n_classes: int = 1000, image: int = 224, seed: int = 42,
            updater=None) -> MultiLayerNetwork:
    """AlexNet (Krizhevsky 2012, single-tower variant — the topology the
    reference era's model zoo shipped). NHWC; LRN after the first two conv
    blocks as in the paper."""
    from ..nn.conf import InputType
    from ..nn.layers import LocalResponseNormalization

    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .updater(updater or Nesterovs(learning_rate=0.01, momentum=0.9))
         .weight_init("relu")
         .list()
         .layer(ConvolutionLayer(n_out=96, kernel_size=(11, 11),
                                 stride=(4, 4), activation="relu",
                                 convolution_mode=ConvolutionMode.SAME))
         .layer(LocalResponseNormalization())
         .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                 kernel_size=(3, 3), stride=(2, 2)))
         .layer(ConvolutionLayer(n_out=256, kernel_size=(5, 5),
                                 stride=(1, 1), activation="relu",
                                 convolution_mode=ConvolutionMode.SAME))
         .layer(LocalResponseNormalization())
         .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                 kernel_size=(3, 3), stride=(2, 2)))
         .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                 stride=(1, 1), activation="relu",
                                 convolution_mode=ConvolutionMode.SAME))
         .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                 stride=(1, 1), activation="relu",
                                 convolution_mode=ConvolutionMode.SAME))
         .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3),
                                 stride=(1, 1), activation="relu",
                                 convolution_mode=ConvolutionMode.SAME))
         .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                 kernel_size=(3, 3), stride=(2, 2)))
         .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
         .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
         .layer(OutputLayer(n_out=n_classes, activation="softmax",
                            loss="mcxent")))
    conf = b.set_input_type(InputType.convolutional(image, image, 3)).build()
    return MultiLayerNetwork(conf)


def vgg19(n_classes: int = 1000, image: int = 224, seed: int = 42,
          updater=None) -> MultiLayerNetwork:
    """VGG-19 (TrainedModels.VGG19 topology analog): VGG-16 with the extra
    conv in blocks 3-5."""
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
           512, 512, 512, 512, "M", 512, 512, 512, 512, "M"]
    return _vgg(cfg, n_classes, image, seed, updater)


def googlenet(n_classes: int = 1000, image: int = 224, seed: int = 42,
              updater=None):
    """GoogLeNet / Inception-v1 (Szegedy 2014) as a ComputationGraph:
    inception modules = four parallel branches concatenated with
    MergeVertex — the multi-branch DAG workload the vertex API exists for
    (reference expresses it identically with its graph API)."""
    from ..nn.conf import InputType
    from ..nn.conf.graph import MergeVertex
    from ..nn.graph import ComputationGraph
    from ..nn.layers import GlobalPoolingLayer

    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .updater(updater or Adam(1e-3))
         .weight_init("relu")
         .graph_builder()
         .add_inputs("input")
         .set_input_types(InputType.convolutional(image, image, 3)))

    def conv(name, inp, n_out, k, s=1):
        b.add_layer(name, ConvolutionLayer(
            n_out=n_out, kernel_size=(k, k), stride=(s, s),
            activation="relu", convolution_mode=ConvolutionMode.SAME), inp)
        return name

    def pool(name, inp, k=3, s=2):
        b.add_layer(name, SubsamplingLayer(
            pooling_type=PoolingType.MAX, kernel_size=(k, k), stride=(s, s),
            convolution_mode=ConvolutionMode.SAME), inp)
        return name

    def inception(name, inp, c1, c3r, c3, c5r, c5, pp):
        b1 = conv(f"{name}_1x1", inp, c1, 1)
        b3 = conv(f"{name}_3x3", conv(f"{name}_3x3r", inp, c3r, 1), c3, 3)
        b5 = conv(f"{name}_5x5", conv(f"{name}_5x5r", inp, c5r, 1), c5, 5)
        bp = conv(f"{name}_poolproj",
                  pool(f"{name}_pool", inp, 3, 1), pp, 1)
        b.add_vertex(f"{name}_concat", MergeVertex(), b1, b3, b5, bp)
        return f"{name}_concat"

    top = conv("stem1", "input", 64, 7, 2)
    top = pool("stem1_pool", top)
    top = conv("stem2a", top, 64, 1)
    top = conv("stem2b", top, 192, 3)
    top = pool("stem2_pool", top)
    top = inception("i3a", top, 64, 96, 128, 16, 32, 32)
    top = inception("i3b", top, 128, 128, 192, 32, 96, 64)
    top = pool("pool3", top)
    top = inception("i4a", top, 192, 96, 208, 16, 48, 64)
    top = inception("i4b", top, 160, 112, 224, 24, 64, 64)
    top = inception("i4c", top, 128, 128, 256, 24, 64, 64)
    top = inception("i4d", top, 112, 144, 288, 32, 64, 64)
    top = inception("i4e", top, 256, 160, 320, 32, 128, 128)
    top = pool("pool4", top)
    top = inception("i5a", top, 256, 160, 320, 32, 128, 128)
    top = inception("i5b", top, 384, 192, 384, 48, 128, 128)
    b.add_layer("gap", GlobalPoolingLayer(pooling_type=PoolingType.AVG),
                top)
    b.add_layer("out", OutputLayer(n_out=n_classes, activation="softmax",
                                   loss="mcxent", dropout=0.6), "gap")
    conf = b.set_outputs("out").build()
    return ComputationGraph(conf)


def sample_characters(net, char_to_idx: dict, seed_text: str, n_chars: int,
                      temperature: float = 1.0, rng_seed: int = 0):
    """Generate text with a trained char-RNN via stateful rnn_time_step
    (the reference's GravesLSTMCharModellingExample sampling loop)."""
    if not seed_text:
        raise ValueError("seed_text must contain at least one character")
    idx_to_char = {i: c for c, i in char_to_idx.items()}
    vocab = len(char_to_idx)
    net.rnn_clear_previous_state()
    out = None
    for ch in seed_text:
        x = np.zeros((1, vocab), np.float32)
        x[0, char_to_idx[ch]] = 1.0
        out = net.rnn_time_step(x)
    rng = np.random.default_rng(rng_seed)
    generated = []
    for _ in range(n_chars):
        p = np.asarray(out, np.float64).reshape(-1)
        if temperature != 1.0:
            logp = np.log(np.maximum(p, 1e-12)) / temperature
            p = np.exp(logp - logp.max())
        p = p / p.sum()
        nxt = int(rng.choice(vocab, p=p))
        generated.append(idx_to_char[nxt])
        x = np.zeros((1, vocab), np.float32)
        x[0, nxt] = 1.0
        out = net.rnn_time_step(x)
    net.rnn_clear_previous_state()
    return "".join(generated)
