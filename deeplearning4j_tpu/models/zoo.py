"""Model zoo — the BASELINE.md configs.

LeNet-MNIST mirrors the reference's canonical MNIST CNN example topology
(Conv 5x5x20 → maxpool → Conv 5x5x50 → maxpool → Dense 500 → softmax 10),
the config DL4J ships in its examples and the first BASELINE config.
"""
from __future__ import annotations

import time

import numpy as np

from ..nn.conf import InputType, NeuralNetConfiguration
from ..nn.layers import (ConvolutionLayer, ConvolutionMode, DenseLayer,
                         OutputLayer, PoolingType, SubsamplingLayer)
from ..nn.multilayer import MultiLayerNetwork
from ..nn.updaters import Adam, Nesterovs

__all__ = ["lenet_mnist", "bench_lenet", "mlp_mnist", "char_rnn",
           "bench_char_rnn"]


def lenet_mnist(seed: int = 42, updater=None) -> MultiLayerNetwork:
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(updater or Nesterovs(learning_rate=0.01, momentum=0.9))
            .l2(5e-4)
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5),
                                    stride=(1, 1), activation="identity",
                                    convolution_mode=ConvolutionMode.TRUNCATE))
            .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                    kernel_size=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5),
                                    stride=(1, 1), activation="identity"))
            .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                    kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())
    return MultiLayerNetwork(conf)


def mlp_mnist(seed: int = 42) -> MultiLayerNetwork:
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(n_out=1024, activation="relu"))
            .layer(DenseLayer(n_out=1024, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(784))
            .build())
    return MultiLayerNetwork(conf)


def char_rnn(vocab_size: int = 77, lstm_size: int = 200, seq_len: int = 64,
             seed: int = 42, tbptt: int = 50) -> MultiLayerNetwork:
    """GravesLSTM char-RNN (BASELINE config #3) — the reference's
    char-modelling example topology: 2xLSTM + RnnOutputLayer, TBPTT."""
    from ..nn.conf import BackpropType
    from ..nn.layers import GravesLSTM, RnnOutputLayer

    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Adam(2e-3))
            .list()
            .layer(GravesLSTM(n_out=lstm_size, activation="tanh"))
            .layer(GravesLSTM(n_out=lstm_size, activation="tanh"))
            .layer(RnnOutputLayer(n_out=vocab_size, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(vocab_size, seq_len))
            .backprop_type(BackpropType.TRUNCATED_BPTT)
            .t_bptt_forward_length(tbptt)
            .t_bptt_backward_length(tbptt)
            .build())
    return MultiLayerNetwork(conf)


def bench_char_rnn(batch: int = 64, seq_len: int = 128, steps: int = 20,
                   warmup: int = 3, vocab: int = 77):
    """tokens/sec for char-RNN training (BASELINE config #3)."""
    import jax

    from ..datasets.iterators import DataSet

    model = char_rnn(vocab_size=vocab, seq_len=seq_len).init()
    r = np.random.default_rng(0)
    idx = r.integers(0, vocab, (batch, seq_len))
    x = np.eye(vocab, dtype=np.float32)[idx]
    y = np.eye(vocab, dtype=np.float32)[np.roll(idx, -1, axis=1)]
    ds = DataSet(x, y)
    for _ in range(warmup):
        model.fit(ds)
    jax.block_until_ready(model.params)
    t0 = time.perf_counter()
    for _ in range(steps):
        model.fit(ds)
    jax.block_until_ready(model.params)
    dt = time.perf_counter() - t0
    return batch * seq_len * steps / dt, "charRNN-tokens"


def bench_lenet(batch: int = 512, steps: int = 40, warmup: int = 5):
    """samples/sec for LeNet-MNIST training steps (BASELINE config #1)."""
    import jax

    from ..datasets.iterators import DataSet

    model = lenet_mnist().init()
    r = np.random.default_rng(0)
    x = r.normal(size=(batch, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[r.integers(0, 10, batch)]
    ds = DataSet(x, y)
    for _ in range(warmup):
        model.fit(ds)
    jax.block_until_ready(model.params)
    t0 = time.perf_counter()
    for _ in range(steps):
        model.fit(ds)
    jax.block_until_ready(model.params)
    dt = time.perf_counter() - t0
    return batch * steps / dt, "LeNet-MNIST"
