"""Fused BatchNorm + ReLU Pallas kernels.

Reference analog: `CudnnBatchNormalizationHelper.java` (289 LoC of cuDNN
descriptor plumbing) — here the fusion is one VMEM pass: batch statistics,
normalization, scale/shift, and the ReLU are computed without writing the
intermediate normalized tensor to HBM. The backward kernel fuses the ReLU
mask with the three BN reductions.

Layout: channels-last [N, C] (the wrapper flattens NHWC conv activations to
[N*H*W, C]); the grid tiles C so each program owns a channel block with the
full batch resident in VMEM. Stats are stop-gradient (running-average
semantics, as in the reference's BatchNormalization layer).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_bn_relu", "bn_relu_inference", "bn_relu_reference"]


def bn_relu_reference(x, gamma, beta, eps: float = 1e-5):
    """jnp oracle: batch-stat BN + ReLU over [N, C]. Returns (y, mean, var)
    (biased variance, the reference's batch-stats convention)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=0)
    var = jnp.mean(jnp.square(xf - mean), axis=0)
    inv = jax.lax.rsqrt(var + eps)
    y = jnp.maximum((xf - mean) * inv * gamma + beta, 0.0)
    return y.astype(x.dtype), mean, var


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, var_ref, *, n, eps):
    x = x_ref[:].astype(jnp.float32)                 # [N, bc]
    mean = jnp.sum(x, axis=0, keepdims=True) / n     # [1, bc]
    xc = x - mean
    var = jnp.sum(xc * xc, axis=0, keepdims=True) / n
    inv = jax.lax.rsqrt(var + eps)
    y = jnp.maximum(xc * inv * g_ref[:] + b_ref[:], 0.0)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    var_ref[:] = var


def _bwd_kernel(x_ref, g_ref, b_ref, mean_ref, var_ref, dy_ref,
                dx_ref, dg_ref, db_ref, *, n, eps):
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    mean = mean_ref[:]
    inv = jax.lax.rsqrt(var_ref[:] + eps)
    xhat = (x - mean) * inv
    pre = xhat * g_ref[:] + b_ref[:]
    dyr = jnp.where(pre > 0.0, dy, 0.0)              # fused ReLU mask
    dg = jnp.sum(dyr * xhat, axis=0, keepdims=True)
    db = jnp.sum(dyr, axis=0, keepdims=True)
    dx = (g_ref[:] * inv / n) * (n * dyr - db - xhat * dg)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dg_ref[:] = dg
    db_ref[:] = db


def _block_c(C: int, N: int) -> Optional[int]:
    """Channel tile: TPU lowering needs the lane dim to be a multiple of
    128 or the full array width, and the full batch stays in VMEM with
    in/out blocks double-buffered — cap one block at ~2MB. Returns None
    when the batch dim alone exceeds the budget (caller falls back to the
    XLA path)."""
    bc = 128 if C >= 128 else C
    if N * bc * 4 > 2 * 1024 * 1024:
        return None
    return bc


def _fwd_call(x, gamma, beta, eps, interpret):
    N, C = x.shape
    bc = _block_c(C, N)
    Cp = -(-C // bc) * bc
    xp = jnp.pad(x, ((0, 0), (0, Cp - C)))
    gp = jnp.pad(gamma.reshape(1, -1).astype(jnp.float32),
                 ((0, 0), (0, Cp - C)))
    bp = jnp.pad(beta.reshape(1, -1).astype(jnp.float32),
                 ((0, 0), (0, Cp - C)))
    y, mean, var = pl.pallas_call(
        functools.partial(_fwd_kernel, n=float(N), eps=float(eps)),
        out_shape=(jax.ShapeDtypeStruct((N, Cp), x.dtype),
                   jax.ShapeDtypeStruct((1, Cp), jnp.float32),
                   jax.ShapeDtypeStruct((1, Cp), jnp.float32)),
        grid=(Cp // bc,),
        in_specs=[pl.BlockSpec((N, bc), lambda c: (0, c),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((1, bc), lambda c: (0, c),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((1, bc), lambda c: (0, c),
                               memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((N, bc), lambda c: (0, c),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, bc), lambda c: (0, c),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, bc), lambda c: (0, c),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(xp, gp, bp)
    return y[:, :C], mean[0, :C], var[0, :C]


def _bwd_call(x, gamma, beta, mean, var, dy, eps, interpret):
    N, C = x.shape
    bc = _block_c(C, N)
    Cp = -(-C // bc) * bc
    pc = lambda a: jnp.pad(a, ((0, 0), (0, Cp - C)))
    xp, dyp = pc(x), pc(dy)
    gp = pc(gamma.reshape(1, -1).astype(jnp.float32))
    bp = pc(beta.reshape(1, -1).astype(jnp.float32))
    mp = pc(mean.reshape(1, -1).astype(jnp.float32))
    # pad var with 1s so rsqrt(0+eps) on dead channels stays finite
    vp = jnp.pad(var.reshape(1, -1).astype(jnp.float32),
                 ((0, 0), (0, Cp - C)), constant_values=1.0)
    dx, dg, db = pl.pallas_call(
        functools.partial(_bwd_kernel, n=float(N), eps=float(eps)),
        out_shape=(jax.ShapeDtypeStruct((N, Cp), x.dtype),
                   jax.ShapeDtypeStruct((1, Cp), jnp.float32),
                   jax.ShapeDtypeStruct((1, Cp), jnp.float32)),
        grid=(Cp // bc,),
        in_specs=[pl.BlockSpec((N, bc), lambda c: (0, c),
                               memory_space=pltpu.VMEM)] +
                 [pl.BlockSpec((1, bc), lambda c: (0, c),
                               memory_space=pltpu.VMEM)] * 4 +
                 [pl.BlockSpec((N, bc), lambda c: (0, c),
                               memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec((N, bc), lambda c: (0, c),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, bc), lambda c: (0, c),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, bc), lambda c: (0, c),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(xp, gp, bp, mp, vp, dyp)
    return dx[:, :C], dg[0, :C], db[0, :C]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_relu(x, gamma, beta, eps, interpret):
    return _fwd_call(x, gamma, beta, eps, interpret)


def _bn_relu_fwd(x, gamma, beta, eps, interpret):
    y, mean, var = _fwd_call(x, gamma, beta, eps, interpret)
    return (y, mean, var), (x, gamma, beta, mean, var)


def _bn_relu_bwd(eps, interpret, res, cotangents):
    x, gamma, beta, mean, var = res
    dy, _dmean, _dvar = cotangents   # stats are stop-gradient (running avg)
    dx, dg, db = _bwd_call(x, gamma, beta, mean, var, dy, eps, interpret)
    return dx, dg.astype(gamma.dtype), db.astype(beta.dtype)


_bn_relu.defvjp(_bn_relu_fwd, _bn_relu_bwd)


def fused_bn_relu(x, gamma, beta, eps: float = 1e-5,
                  interpret: Optional[bool] = None):
    """Fused training-mode BatchNorm + ReLU. x: [N, C] or [N, H, W, C]
    (channels last). Returns (y, batch_mean, batch_var); the caller updates
    its running statistics from the returned batch stats, exactly like the
    reference's BatchNormalization layer does around its cuDNN helper."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    shape = x.shape
    if x.ndim > 2:
        x = x.reshape(-1, shape[-1])
    if _block_c(x.shape[1], x.shape[0]) is None:
        # batch dim alone would blow VMEM — XLA's two-pass BN handles it
        y, mean, var = bn_relu_reference(x, gamma, beta, eps)
        return y.reshape(shape), mean, var
    y, mean, var = _bn_relu(x, gamma, beta, float(eps), bool(interpret))
    return y.reshape(shape), mean, var


def bn_relu_inference(x, gamma, beta, mean, var, eps: float = 1e-5):
    """Inference-mode fused path with running stats: a single elementwise
    expression, left to XLA (it fuses this perfectly — the kernel tier is
    only for the batch-stat reductions)."""
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    y = (x.astype(jnp.float32) - mean) * inv * gamma + beta
    return jnp.maximum(y, 0.0).astype(x.dtype)
