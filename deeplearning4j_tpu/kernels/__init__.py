"""Pallas TPU kernel layer — the accelerated-helper tier (SURVEY.md §2.3/§7.7).

Reference analog: `deeplearning4j-cuda` — cuDNN-backed implementations of the
layer-helper SPI, probed at runtime by layer impls
(`CudnnConvolutionHelper.java:49`). Here the "hand kernel" tier is Pallas:
layers/ops call these when `pallas_supported()` and fall back to the plain
XLA path otherwise; every kernel is validated against its jnp reference and
gradient-checked (the `CuDNNGradientChecks` pattern,
`deeplearning4j-cuda/src/test/.../CuDNNGradientChecks.java`).

Kernels run compiled on TPU and in interpreter mode on CPU (so the same
tests cover both, like the reference's backend-profile test matrix).
"""
from __future__ import annotations

import os

__all__ = ["pallas_supported", "flash_attention", "flash_attention_spmd",
           "fused_bn_relu", "bn_relu_inference"]


def pallas_supported() -> bool:
    """True when the Pallas kernel tier should be used: a TPU backend is
    live and kernels are not disabled via DL4J_TPU_DISABLE_PALLAS."""
    flag = os.environ.get("DL4J_TPU_DISABLE_PALLAS", "").strip().lower()
    if flag not in ("", "0", "false", "no", "off"):
        return False
    import jax

    return jax.default_backend() == "tpu"


from .attention import flash_attention, flash_attention_spmd  # noqa: E402
from .bn_relu import bn_relu_inference, fused_bn_relu      # noqa: E402
