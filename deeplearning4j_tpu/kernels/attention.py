"""Flash attention — blockwise streaming-softmax attention as a Pallas
TPU kernel.

The single-device building block of the long-context stack: exact softmax
attention in O(T) memory, with the K/V stream tiled through VMEM and the
running (m, l, acc) statistics held on-chip instead of materializing the
[T, S] score matrix in HBM. The ring layer
(`parallel/ring_attention.py`) runs the same math across devices; this
kernel is the within-device tier (the reference's analog of a cuDNN
helper, `CudnnConvolutionHelper.java:49` pattern — selected when
available, plain-XLA `blockwise_attention` otherwise).

Grid layout: (batch, q_blocks, kv_blocks) — the kv axis is innermost so
the (m, l, acc) VMEM scratch carries across kv steps of one q block
(TPU grids are sequential). Causal masking and ragged (non-multiple)
sequence lengths are handled with index masks.

Backward pass: the kernel is wrapped in `jax.custom_vjp`; the backward
recomputes attention with the plain-jnp reference (rematerialization —
O(T*S) transient inside XLA, which is the standard memory/compute trade
at this tier; the ring layer keeps the global memory O(T/devices)).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "attention_reference"]

_NEG_INF = float("-inf")


def attention_reference(q, k, v, causal: bool = False,
                        sm_scale: Optional[float] = None):
    """Plain softmax attention oracle. q: [B, T, D], k/v: [B, S, D]."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    logits = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
    if causal:
        T, S = logits.shape[-2], logits.shape[-1]
        qi = jnp.arange(T)[:, None]
        ki = jnp.arange(S)[None, :]
        logits = jnp.where(ki <= qi, logits, _NEG_INF)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", w, v.astype(jnp.float32)).astype(q.dtype)


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _make_kernel(causal: bool, sm_scale: float, bq: int, bk: int,
                 s_len: int):
    def kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref):
        i = pl.program_id(1)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _():
            m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        # causal: a kv block strictly above the q block's diagonal is dead
        live = (j * bk <= i * bq + bq - 1) if causal else (j >= 0)

        @pl.when(live)
        def _():
            q_blk = q_ref[0]                    # [bq, D]
            k_blk = k_ref[0]                    # [bk, D]
            v_blk = v_ref[0]                    # [bk, D]
            s = jax.lax.dot_general(
                q_blk, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            kv_idx = j * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            mask = kv_idx < s_len               # ragged tail
            if causal:
                q_idx = i * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0)
                mask = mask & (kv_idx <= q_idx)
            s = jnp.where(mask, s, _NEG_INF)

            m_prev = m_ref[:]                   # [bq, 128] lane-replicated
            m_cur = jnp.max(s, axis=-1, keepdims=True)     # [bq, 1]
            m_new = jnp.maximum(m_prev, m_cur)
            m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
            p = jnp.exp(s - m_safe[:, :1])
            p = jnp.where(mask, p, 0.0)
            corr = jnp.where(jnp.isneginf(m_prev), 0.0,
                             jnp.exp(m_prev - m_safe))
            m_ref[:] = m_new
            l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
            acc_ref[:] = acc_ref[:] * corr[:, :1] + jax.lax.dot_general(
                p, v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(j == pl.num_programs(2) - 1)
        def _():
            o_ref[0] = (acc_ref[:]
                        / jnp.maximum(l_ref[:, :1], 1e-30)).astype(
                            o_ref.dtype)

    return kernel


def _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    B, T, D = q.shape
    S = k.shape[1]
    bq = min(block_q, _round_up(T, 8))
    bk = min(block_k, _round_up(S, 8))
    Tp, Sp = _round_up(T, bq), _round_up(S, bk)
    qp = jnp.pad(q, ((0, 0), (0, Tp - T), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, Sp - S), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, Sp - S), (0, 0)))
    grid = (B, Tp // bq, Sp // bk)
    kernel = _make_kernel(causal, sm_scale, bq, bk, S)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, Tp, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max m
            pltpu.VMEM((bq, 128), jnp.float32),   # running denom l
            pltpu.VMEM((bq, D), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :T]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    return _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                           interpret)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out = _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)
    return out, (q, k, v)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(q_, k_, v_, causal, sm_scale),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Blockwise flash attention. q: [B, T, D], k/v: [B, S, D].

    Compiled Pallas on TPU; `interpret=True` (automatic off-TPU) runs the
    identical kernel through the Pallas interpreter so CPU CI validates the
    same code path the TPU executes."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # TPU lowering needs sublane-dim blocks in multiples of 8
    block_q = max(8, _round_up(int(block_q), 8))
    block_k = max(8, _round_up(int(block_k), 8))
    return _flash(q, k, v, bool(causal), float(sm_scale), block_q,
                  block_k, bool(interpret))
