"""Flash attention — blockwise streaming-softmax attention as a Pallas
TPU kernel.

The single-device building block of the long-context stack: exact softmax
attention in O(T) memory, with the K/V stream tiled through VMEM and the
running (m, l, acc) statistics held on-chip instead of materializing the
[T, S] score matrix in HBM. The ring layer
(`parallel/ring_attention.py`) runs the same math across devices; this
kernel is the within-device tier (the reference's analog of a cuDNN
helper, `CudnnConvolutionHelper.java:49` pattern — selected when
available, plain-XLA `blockwise_attention` otherwise).

Grid layout: (batch, q_blocks, kv_blocks) — the kv axis is innermost so
the (m, l, acc) VMEM scratch carries across kv steps of one q block
(TPU grids are sequential). Causal masking and ragged (non-multiple)
sequence lengths are handled with index masks.

Backward pass: blockwise Pallas kernels (FlashAttention-2 style). The
forward additionally emits the per-row logsumexp L = m + log(l); the
backward recomputes each [bq, bk] probability tile from (q, k, L) in VMEM
— never materializing the [T, S] matrix in HBM — and accumulates
  dv += p^T do,   ds = p * (do v^T - D),   dq += ds k,   dk += ds^T q
with D = rowsum(do * o). Memory stays O(T), matching the forward.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "flash_attention_spmd", "attention_reference"]

_NEG_INF = float("-inf")


def attention_reference(q, k, v, causal: bool = False,
                        sm_scale: Optional[float] = None,
                        q_positions=None, kv_length=None):
    """Plain softmax attention oracle. q: [B, T, D], k/v: [B, S, D].

    Decode extension (serving/decode): queries may sit at arbitrary
    offsets inside a LONGER key cache, so a square causal mask is not
    enough. `q_positions` [B, T] gives each query row's absolute key
    index (causal then means key j attends iff j <= q_positions[b, t] —
    a causal OFFSET, defaulting to the classic arange diagonal), and
    `kv_length` ([B] or scalar) the per-row count of valid cache slots:
    keys at j >= kv_length[b] (block-table padding, slots not yet
    written) get no attention weight. A row whose mask admits zero keys
    produces NaN — callers guarantee kv_length >= 1 for live rows (the
    decode plane parks padded batch slots at position 0 of a reserved
    block, so every row keeps one valid key)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    logits = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
    T, S = logits.shape[-2], logits.shape[-1]
    ki = jnp.arange(S)
    if causal:
        if q_positions is None:
            qi = jnp.arange(T)[None, :]            # classic diagonal
        else:
            qi = jnp.asarray(q_positions)           # [B, T] offsets
        logits = jnp.where(ki[None, None, :] <= qi[:, :, None],
                           logits, _NEG_INF)
    if kv_length is not None:
        lengths = jnp.reshape(jnp.asarray(kv_length, jnp.int32), (-1,))
        logits = jnp.where(ki[None, None, :] < lengths[:, None, None],
                           logits, _NEG_INF)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", w, v.astype(jnp.float32)).astype(q.dtype)


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _make_kernel(causal: bool, sm_scale: float, bq: int, bk: int,
                 s_len: int, emit_lse: bool = True):
    def kernel(q_ref, k_ref, v_ref, o_ref, *rest):
        if emit_lse:
            lse_ref, m_ref, l_ref, acc_ref = rest
        else:
            m_ref, l_ref, acc_ref = rest
        i = pl.program_id(1)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _():
            m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        # causal: a kv block strictly above the q block's diagonal is dead
        live = (j * bk <= i * bq + bq - 1) if causal else (j >= 0)

        @pl.when(live)
        def _():
            q_blk = q_ref[0]                    # [bq, D]
            k_blk = k_ref[0]                    # [bk, D]
            v_blk = v_ref[0]                    # [bk, D]
            s = jax.lax.dot_general(
                q_blk, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            kv_idx = j * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            mask = kv_idx < s_len               # ragged tail
            if causal:
                q_idx = i * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0)
                mask = mask & (kv_idx <= q_idx)
            s = jnp.where(mask, s, _NEG_INF)

            m_prev = m_ref[:]                   # [bq, 128] lane-replicated
            m_cur = jnp.max(s, axis=-1, keepdims=True)     # [bq, 1]
            m_new = jnp.maximum(m_prev, m_cur)
            m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
            p = jnp.exp(s - m_safe[:, :1])
            p = jnp.where(mask, p, 0.0)
            corr = jnp.where(jnp.isneginf(m_prev), 0.0,
                             jnp.exp(m_prev - m_safe))
            m_ref[:] = m_new
            l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
            acc_ref[:] = acc_ref[:] * corr[:, :1] + jax.lax.dot_general(
                p, v_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(j == pl.num_programs(2) - 1)
        def _():
            o_ref[0] = (acc_ref[:]
                        / jnp.maximum(l_ref[:, :1], 1e-30)).astype(
                            o_ref.dtype)
            if emit_lse:
                m_safe = jnp.where(jnp.isneginf(m_ref[:]), 0.0, m_ref[:])
                lse_ref[0] = m_safe + jnp.log(jnp.maximum(l_ref[:], 1e-30))

    return kernel


def _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k, interpret,
                    emit_lse: bool = True):
    """emit_lse=False (the primal/inference path) skips computing AND
    writing the lane-replicated [B, Tp, 128] f32 logsumexp output — that
    write is up to 2x the HBM output traffic of a bf16 D=128 out row, and
    only the fwd-for-vjp path needs it."""
    B, T, D = q.shape
    S = k.shape[1]
    bq = min(block_q, _round_up(T, 8))
    bk = min(block_k, _round_up(S, 8))
    Tp, Sp = _round_up(T, bq), _round_up(S, bk)
    qp = jnp.pad(q, ((0, 0), (0, Tp - T), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, Sp - S), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, Sp - S), (0, 0)))
    grid = (B, Tp // bq, Sp // bk)
    kernel = _make_kernel(causal, sm_scale, bq, bk, S, emit_lse)
    o_spec = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    lse_spec = pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0),
                            memory_space=pltpu.VMEM)
    out_shape = (jax.ShapeDtypeStruct((B, Tp, D), q.dtype),)
    out_specs = (o_spec,)
    if emit_lse:
        out_shape += (jax.ShapeDtypeStruct((B, Tp, 128), jnp.float32),)
        out_specs += (lse_spec,)
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max m
            pltpu.VMEM((bq, 128), jnp.float32),   # running denom l
            pltpu.VMEM((bq, D), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
    )(qp, kp, vp)
    if not emit_lse:
        return res[0][:, :T], None
    out, lse = res
    # keep only one lane of the lane-replicated LSE: the residual held from
    # forward to backward is [B, Tp], not [B, Tp, 128]
    return out[:, :T], lse[:, :, 0]


def _bwd_masks(causal, bq, bk, i, j, t_len, s_len):
    """[bq, bk] validity mask for tile (i, j): ragged tails + causal."""
    q_idx = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kv_idx = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = (q_idx < t_len) & (kv_idx < s_len)
    if causal:
        mask = mask & (kv_idx <= q_idx)
    return mask


def _make_dq_kernel(causal, sm_scale, bq, bk, t_len, s_len):
    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
               dq_ref, acc_ref):
        i = pl.program_id(1)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        live = (j * bk <= i * bq + bq - 1) if causal else (j >= 0)

        @pl.when(live)
        def _():
            q_blk = q_ref[0]
            k_blk = k_ref[0]
            v_blk = v_ref[0]
            do_blk = do_ref[0].astype(jnp.float32)
            s = jax.lax.dot_general(
                q_blk, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            mask = _bwd_masks(causal, bq, bk, i, j, t_len, s_len)
            p = jnp.where(mask, jnp.exp(s - lse_ref[0][:, :1]), 0.0)
            dp = jax.lax.dot_general(
                do_blk, v_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - dsum_ref[0][:, :1]) * sm_scale
            acc_ref[:] += jax.lax.dot_general(
                ds, k_blk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(j == pl.num_programs(2) - 1)
        def _():
            dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)

    return kernel


def _make_dkv_kernel(causal, sm_scale, bq, bk, t_len, s_len):
    """Grid (B, kv_blocks, q_blocks) — q axis innermost so the dk/dv VMEM
    accumulators carry across q steps of one kv block."""
    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
               dk_ref, dv_ref, dk_acc, dv_acc):
        j = pl.program_id(1)   # kv block
        i = pl.program_id(2)   # q block (inner)

        @pl.when(i == 0)
        def _():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        live = (i * bq + bq - 1 >= j * bk) if causal else (i >= 0)

        @pl.when(live)
        def _():
            q_blk = q_ref[0]
            k_blk = k_ref[0]
            v_blk = v_ref[0]
            do_blk = do_ref[0].astype(jnp.float32)
            s = jax.lax.dot_general(
                q_blk, k_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            mask = _bwd_masks(causal, bq, bk, i, j, t_len, s_len)
            p = jnp.where(mask, jnp.exp(s - lse_ref[0][:, :1]), 0.0)
            dv_acc[:] += jax.lax.dot_general(
                p, do_blk, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do_blk, v_blk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - dsum_ref[0][:, :1]) * sm_scale
            dk_acc[:] += jax.lax.dot_general(
                ds, q_blk, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(i == pl.num_programs(2) - 1)
        def _():
            dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)

    return kernel


def _flash_bwd_impl(q, k, v, o, lse, g, causal, sm_scale, block_q, block_k,
                    interpret):
    B, T, D = q.shape
    S = k.shape[1]
    bq = min(block_q, _round_up(T, 8))
    bk = min(block_k, _round_up(S, 8))
    Tp, Sp = _round_up(T, bq), _round_up(S, bk)
    qp = jnp.pad(q, ((0, 0), (0, Tp - T), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, Sp - S), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, Sp - S), (0, 0)))
    gp = jnp.pad(g, ((0, 0), (0, Tp - T), (0, 0)))
    # lane-replicate the [B, Tp] row statistics at kernel-call time
    lse = jnp.broadcast_to(lse[:, :, None], (B, lse.shape[1], 128))
    dsum = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    dsum = jnp.pad(dsum, ((0, 0), (0, Tp - T)))
    dsum = jnp.broadcast_to(dsum[:, :, None], (B, Tp, 128))

    q_spec = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                           memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0),
                            memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        _make_dq_kernel(causal, sm_scale, bq, bk, T, S),
        out_shape=jax.ShapeDtypeStruct((B, Tp, D), q.dtype),
        grid=(B, Tp // bq, Sp // bk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, gp, lse, dsum)

    # kv-major grid: swap the roles of the index maps
    q_spec2 = pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    kv_spec2 = pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0),
                            memory_space=pltpu.VMEM)
    row_spec2 = pl.BlockSpec((1, bq, 128), lambda b, j, i: (b, i, 0),
                             memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        _make_dkv_kernel(causal, sm_scale, bq, bk, T, S),
        out_shape=(jax.ShapeDtypeStruct((B, Sp, D), k.dtype),
                   jax.ShapeDtypeStruct((B, Sp, D), v.dtype)),
        grid=(B, Sp // bk, Tp // bq),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=(kv_spec2, kv_spec2),
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, gp, lse, dsum)
    return dq[:, :T], dk[:, :S], dv[:, :S]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, _ = _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                             interpret, emit_lse=False)
    return out


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(q, k, v, causal, sm_scale, block_q, block_k,
                               interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    return _flash_bwd_impl(q, k, v, o, lse, g, causal, sm_scale, block_q,
                           block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Blockwise flash attention. q: [B, T, D], k/v: [B, S, D].

    Compiled Pallas on TPU; `interpret=True` (automatic off-TPU) runs the
    identical kernel through the Pallas interpreter so CPU CI validates the
    same code path the TPU executes."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # TPU lowering needs sublane-dim blocks in multiples of 8
    block_q = max(8, _round_up(int(block_q), 8))
    block_k = max(8, _round_up(int(block_k), 8))
    return _flash(q, k, v, bool(causal), float(sm_scale), block_q,
                  block_k, bool(interpret))


def flash_attention_spmd(q, k, v, causal: bool = False, *, mesh,
                         data_axis: str = "data", model_axis: str = "model",
                         sm_scale: Optional[float] = None,
                         block_q: int = 128, block_k: int = 128,
                         interpret: Optional[bool] = None):
    """Multi-head flash attention under `shard_map` over a (data, model)
    mesh: q/k/v [B, T, H, Dh] with the batch axis sharded over
    `data_axis` and the head axis over `model_axis` (the Megatron layout
    `nn/layers/transformer.py` produces — column-parallel QKV projections
    leave the head axis model-sharded).

    GSPMD has no partitioning rule for a Pallas custom call, so a flash
    kernel placed directly inside a sharded jit forces replication (or
    fails to partition). Attention, however, is INDEPENDENT per
    (batch row, head): each shard's local [B/d, T, H/m, Dh] block is
    exactly a standalone multi-head attention problem, so running the
    kernel per-shard inside `shard_map` needs ZERO collectives — the IR
    probes budget the surrounding step at the einsum baseline's per-axis
    bytes to prove nothing leaked. Requires B % d == 0 and H % m == 0
    (the trainer's batch sharding and `tp_validate` already enforce
    both)."""
    from ..parallel.compat import shard_map   # lazy: no parallel-stack
                                              # import at kernel load
    from jax.sharding import PartitionSpec as P

    spec = P(data_axis, None, model_axis, None)

    def local_block(qb, kb, vb):
        f = lambda q2, k2, v2: flash_attention(
            q2, k2, v2, causal, sm_scale, block_q, block_k, interpret)
        return jax.vmap(f, in_axes=2, out_axes=2)(qb, kb, vb)

    return shard_map(local_block, mesh=mesh,
                     in_specs=(spec, spec, spec), out_specs=spec,
                     check_vma=False)(q, k, v)
