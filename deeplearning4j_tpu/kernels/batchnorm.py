"""Fused training-mode BatchNorm + activation for TPU — the XLA-epilogue
helper.

Reference analog: `CudnnBatchNormalizationHelper.java:49` — the accelerated
implementation a layer probes for at runtime. On TPU the fastest formulation
is NOT a standalone kernel: profiling ResNet-50 b256 on a v5e chip shows the
stage activations are HBM-bandwidth-bound and XLA fuses the one-pass stat
reductions into the *producing convolution's epilogue* and the normalize +
activation into the *consuming op* — a separate kernel (Pallas or otherwise)
adds a full extra read+write pass over the activation and measures ~35%
slower end-to-end (optimization_barrier ablation: 138 vs 100 ms/step).
So the TPU "kernel" is a formulation engineered for XLA's fuser:

  * ONE reduction pass over x (sum + sum-of-squares, f32 accumulation),
    fused by XLA into the producer — vs. the two serialized passes of the
    numerically-exact path (mean, then centered variance).  E[x^2]-E[x]^2
    cancellation is acceptable exactly where this path is selected: bf16/f16
    activations whose own 8-bit mantissa already bounds precision (cuDNN's
    batch-norm makes the same trade).
  * normalize folded to y = act(x * scale + shift) — one fused elementwise
    consumer, no materialized f32 copy of x.
  * custom_vjp backward with the hand-derived 2-pass formula; the ReLU mask
    is RECOMPUTED from the saved x (sign of xhat*gamma+beta) instead of
    saving/reading the forward output — one fewer full activation pass in
    backward (measured ~3 ms/step on ResNet-50 b256).

`kernels/bn_relu.py` keeps the true Pallas tier for [N, C] batches that fit
VMEM (the FF/MLP case, where a single-pass on-chip kernel does win);
`nn/layers/normalization.py` probes Pallas -> this -> plain jnp, the same
chain as the reference's ConvolutionLayer.initializeHelper.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["fused_bn_act", "FUSED_BN_ACTIVATIONS"]

# activations the fused backward knows how to mask/derive
FUSED_BN_ACTIVATIONS = ("identity", "relu")


def _stats(x, axes):
    """One-pass sum/sumsq stats in f32 (XLA fuses into the producer)."""
    xf = x.astype(jnp.float32)
    n = 1
    for a in axes:
        n *= x.shape[a]
    s1 = jnp.sum(xf, axis=axes)
    s2 = jnp.sum(lax.square(xf), axis=axes)
    mean = s1 / n
    var = jnp.maximum(s2 / n - lax.square(mean), 0.0)
    return mean, var, float(n)


def _normalize(x, mean, var, gamma, beta, eps, act):
    inv = lax.rsqrt(var + eps)
    scale = gamma * inv
    shift = beta - mean * scale
    y = x.astype(jnp.float32) * scale + shift
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype), inv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_bn_act(x, gamma, beta, eps: float, act: str,
                 store_dtype: str = ""):
    """Training-mode BN + activation over channels-last `x` (any rank >= 2;
    stats over all axes but the last). Returns (y, batch_mean, batch_var);
    the stats are stop-gradient (running-average semantics, as the
    reference's BatchNormalization treats them). `act` must be in
    FUSED_BN_ACTIVATIONS. `store_dtype` (e.g. "float8_e4m3fn") stores the
    saved-for-backward x compactly — an HBM traffic/precision trade."""
    y, mean, var, _ = _fwd_math(x, gamma, beta, eps, act)
    return y, mean, var


def _fwd_math(x, gamma, beta, eps, act):
    axes = tuple(range(x.ndim - 1))
    mean, var, n = _stats(x, axes)
    y, inv = _normalize(x, mean, var, gamma.astype(jnp.float32),
                        beta.astype(jnp.float32), eps, act)
    return y, mean, var, (x, mean, inv, n)


def _fwd(x, gamma, beta, eps, act, store_dtype):
    y, mean, var, res = _fwd_math(x, gamma, beta, eps, act)
    if store_dtype:
        x_saved, rest = res[0], res[1:]
        res = (x_saved.astype(jnp.dtype(store_dtype)),) + rest
    return (y, mean, var), res + (gamma, beta)


def _bwd(eps, act, store_dtype, res, cotangents):
    x, mean, inv, n, gamma, beta = res
    dy, _dmean, _dvar = cotangents  # stats are stop-gradient
    axes = tuple(range(x.ndim - 1))
    gf = gamma.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    xhat = (x.astype(jnp.float32) - mean) * inv
    if act == "relu":
        # recompute the mask from xhat (x is already being read) instead of
        # saving + re-reading the forward output: one fewer HBM pass
        mask = xhat * gf + beta.astype(jnp.float32) > 0
        dyf = jnp.where(mask, dyf, 0.0)
    dg = jnp.sum(dyf * xhat, axis=axes)
    db = jnp.sum(dyf, axis=axes)
    # dx in the ORIGINAL activation dtype (dy carries it — x may be stored
    # compactly via store_dtype)
    dx = ((gf * inv) * (dyf - (db + xhat * dg) / n)).astype(dy.dtype)
    return dx, dg.astype(gamma.dtype), db.astype(beta.dtype)


fused_bn_act.defvjp(_fwd, _bwd)
