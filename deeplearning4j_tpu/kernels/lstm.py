"""Fused Graves-LSTM sequence kernel — the whole scan in ONE Pallas call.

The SURVEY §7 phase-7 kernel target ("fused LSTM cell"), and the analog of
the cuDNN RNN API the reference era lacked (SURVEY notes no cuDNN LSTM
helper existed at v0.8; `LSTMHelpers.java` ran generic per-timestep ops).

Why a kernel wins here where conv/BN kernels lost (see BASELINE.md): the
XLA path is a `lax.scan` whose per-timestep work is a tiny [B, F+H] x
[F+H, 4H] matmul — too small to hide per-op overhead, and the weights are
re-read from HBM every timestep. At char-RNN size the FULL working set
(weights + biases + peepholes + [B, H] carries) fits VMEM, so one Pallas
kernel holds the carry on-chip across the whole sequence and reads the
weights once per *sequence* instead of once per *timestep* (the TPU grid
is sequential — exactly a time loop). Each step is ONE [B, F+H] x
[F+H, 4H] MXU matmul; gate splits are in-register slices.

Backward is a second Pallas kernel running the standard Graves-LSTM
adjoint in reverse time (peepholes included): per step one [B,4H] x
[4H, F+H] matmul for dz and one [F+H, B] x [B, 4H] matmul accumulating
dW in VMEM scratch; saved residuals are the forward's per-step gate
activations and cell states (the same tensors XLA's autodiff would save).

Selection follows the helper probing pattern
(`CudnnBatchNormalizationHelper` style): the layer uses this kernel only
on TPU for mask-free sigmoid/tanh LSTMs whose working set fits VMEM;
everything else takes the lax.scan path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_lstm_sequence", "lstm_fits_vmem"]


def _sig(x):
    return jax.nn.sigmoid(x)


def lstm_fits_vmem(n_in: int, n_out: int, batch: int,
                   dtype_bytes: int = 4, budget: int = 10 << 20) -> bool:
    """Rough VMEM feasibility: weights (x2 for the backward's dW
    accumulator) + a few [B, 4H] temporaries must fit."""
    f, h = n_in + n_out, n_out
    weights = f * 4 * h * dtype_bytes
    temps = 10 * batch * 4 * h * dtype_bytes
    return 2 * weights + temps < budget


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(x_ref, w_ref, b_ref, peep_ref, h0_ref, c0_ref,
                *out_refs, offs: float, H: int, save_residuals: bool):
    if save_residuals:
        hs_ref, cs_ref, ii_ref, ff_ref, oo_ref, gg_ref, h_scr, c_scr = \
            out_refs
    else:
        hs_ref, cT_ref, h_scr, c_scr = out_refs
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    h_prev = h_scr[:]
    c_prev = c_scr[:]
    zcat = jnp.concatenate([x_ref[0], h_prev], axis=-1)   # [B, F+H]
    gates = jax.lax.dot_general(
        zcat, w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + b_ref[:]     # [B, 4H]
    i = _sig(gates[:, :H] + c_prev * peep_ref[:, :H])
    f = _sig(gates[:, H:2 * H] + c_prev * peep_ref[:, H:2 * H] + offs)
    g = jnp.tanh(gates[:, 3 * H:])
    c = f * c_prev + i * g
    o = _sig(gates[:, 2 * H:3 * H] + c * peep_ref[:, 2 * H:])
    h = o * jnp.tanh(c)
    hs_ref[0] = h
    if save_residuals:
        cs_ref[0] = c
        ii_ref[0] = i
        ff_ref[0] = f
        oo_ref[0] = o
        gg_ref[0] = g
    else:
        @pl.when(t == pl.num_programs(0) - 1)
        def _():
            cT_ref[:] = c
    h_scr[:] = h
    c_scr[:] = c


def _fwd_impl(x, W, b, peep, h0, c0, offs, interpret,
              save_residuals: bool = True):
    """save_residuals=True (the fwd-for-vjp path) emits the per-step gate
    activations and cell states the adjoint needs; False (the primal /
    inference path) emits only hs + the final cell state — 4 fewer
    [T, B, H] HBM writes per call."""
    T, B, F = x.shape
    H = h0.shape[-1]
    f32 = jnp.float32
    step = lambda shp: pl.BlockSpec((1,) + shp, lambda t: (t, 0, 0),
                                    memory_space=pltpu.VMEM)
    full = lambda a: pl.BlockSpec(a.shape, lambda t: (0,) * a.ndim,
                                  memory_space=pltpu.VMEM)
    if save_residuals:
        out_shape = tuple(jax.ShapeDtypeStruct((T, B, H), f32)
                          for _ in range(6))
        out_specs = tuple(step((B, H)) for _ in range(6))
    else:
        out_shape = (jax.ShapeDtypeStruct((T, B, H), f32),
                     jax.ShapeDtypeStruct((B, H), f32))
        out_specs = (step((B, H)),
                     pl.BlockSpec((B, H), lambda t: (0, 0),
                                  memory_space=pltpu.VMEM))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, offs=float(offs), H=H,
                          save_residuals=save_residuals),
        grid=(T,),
        in_specs=[step((B, F)), full(W), full(b), full(peep),
                  full(h0), full(c0)],
        out_shape=out_shape,
        out_specs=out_specs,
        scratch_shapes=[pltpu.VMEM((B, H), f32), pltpu.VMEM((B, H), f32)],
        interpret=interpret,
    )(x, W, b, peep, h0, c0)


# ---------------------------------------------------------------------------
# backward (reverse-time adjoint)
# ---------------------------------------------------------------------------
def _bwd_kernel(x_ref, w_ref, peep_ref,
                hs_prev_ref, cs_ref, cs_prev_ref,
                ii_ref, ff_ref, oo_ref, gg_ref,
                h0_ref, c0_ref, dhs_ref, dhT_ref, dcT_ref,
                dx_ref, dw_ref, db_ref, dpeep_ref, dh0_ref, dc0_ref,
                dh_scr, dc_scr, aw, ab, apeep,
                *, T: int, H: int):
    r = pl.program_id(0)          # runs t = T-1 .. 0 (reverse index maps)

    @pl.when(r == 0)
    def _():
        dh_scr[:] = dhT_ref[:]
        dc_scr[:] = dcT_ref[:]
        aw[:] = jnp.zeros_like(aw)
        ab[:] = jnp.zeros_like(ab)
        apeep[:] = jnp.zeros_like(apeep)

    i = ii_ref[0]
    f = ff_ref[0]
    o = oo_ref[0]
    g = gg_ref[0]
    c = cs_ref[0]
    # at the earliest step (t == 0) the "previous" state is the initial
    # carry; the t-1 block specs clamp to index 0 there, so override
    first = r == T - 1
    c_prev = jnp.where(first, c0_ref[:], cs_prev_ref[0])
    h_prev = jnp.where(first, h0_ref[:], hs_prev_ref[0])

    dh = dhs_ref[0] + dh_scr[:]
    tc = jnp.tanh(c)
    do_pre = dh * tc * o * (1.0 - o)
    dc = (dh * o * (1.0 - tc * tc) + dc_scr[:]
          + do_pre * peep_ref[:, 2 * H:])
    di_pre = dc * g * i * (1.0 - i)
    df_pre = dc * c_prev * f * (1.0 - f)
    dg_pre = dc * i * (1.0 - g * g)
    dc_prev = (dc * f + di_pre * peep_ref[:, :H]
               + df_pre * peep_ref[:, H:2 * H])

    zcat = jnp.concatenate([x_ref[0], h_prev], axis=-1)     # [B, F+H]
    dgates = jnp.concatenate([di_pre, df_pre, do_pre, dg_pre],
                             axis=-1)                        # [B, 4H]
    aw[:] = aw[:] + jax.lax.dot_general(
        zcat, dgates, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                  # [F+H, 4H]
    ab[:] = ab[:] + jnp.sum(dgates, axis=0, keepdims=True)
    apeep[:] = apeep[:] + jnp.concatenate(
        [jnp.sum(di_pre * c_prev, axis=0, keepdims=True),
         jnp.sum(df_pre * c_prev, axis=0, keepdims=True),
         jnp.sum(do_pre * c, axis=0, keepdims=True)], axis=-1)

    dz = jax.lax.dot_general(
        dgates, w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                  # [B, F+H]
    F = x_ref.shape[-1]
    dx_ref[0] = dz[:, :F]
    dh_scr[:] = dz[:, F:]
    dc_scr[:] = dc_prev

    @pl.when(r == T - 1)
    def _():
        dw_ref[:] = aw[:]
        db_ref[:] = ab[:]
        dpeep_ref[:] = apeep[:]
        dh0_ref[:] = dh_scr[:]
        dc0_ref[:] = dc_scr[:]


def _bwd_impl(x, W, peep, h0, c0, hs, cs, ii, ff, oo, gg,
              dhs, dhT, dcT, interpret):
    T, B, F = x.shape
    H = h0.shape[-1]
    f32 = jnp.float32
    rev = lambda shp: pl.BlockSpec(
        (1,) + shp, lambda t: (T - 1 - t, 0, 0), memory_space=pltpu.VMEM)
    rev_prev = lambda shp: pl.BlockSpec(
        (1,) + shp, lambda t: (jnp.maximum(T - 2 - t, 0), 0, 0),
        memory_space=pltpu.VMEM)
    full = lambda a: pl.BlockSpec(a.shape, lambda t: (0,) * a.ndim,
                                  memory_space=pltpu.VMEM)
    small = lambda shp: pl.BlockSpec(shp, lambda t: (0, 0),
                                     memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_bwd_kernel, T=T, H=H),
        grid=(T,),
        in_specs=[rev((B, F)), full(W), full(peep),
                  rev_prev((B, H)),               # hs at t-1
                  rev((B, H)), rev_prev((B, H)),  # cs at t, t-1
                  rev((B, H)), rev((B, H)), rev((B, H)), rev((B, H)),
                  full(h0), full(c0), rev((B, H)), full(dhT), full(dcT)],
        out_shape=(jax.ShapeDtypeStruct((T, B, F), f32),
                   jax.ShapeDtypeStruct(W.shape, f32),
                   jax.ShapeDtypeStruct((1, 4 * H), f32),
                   jax.ShapeDtypeStruct((1, 3 * H), f32),
                   jax.ShapeDtypeStruct((B, H), f32),
                   jax.ShapeDtypeStruct((B, H), f32)),
        out_specs=(rev((B, F)), full(W), small((1, 4 * H)),
                   small((1, 3 * H)), full(h0), full(c0)),
        scratch_shapes=[pltpu.VMEM((B, H), f32), pltpu.VMEM((B, H), f32),
                        pltpu.VMEM(W.shape, f32),
                        pltpu.VMEM((1, 4 * H), f32),
                        pltpu.VMEM((1, 3 * H), f32)],
        interpret=interpret,
    )(x, W, peep, hs, cs, cs, ii, ff, oo, gg, h0, c0, dhs, dhT, dcT)


# ---------------------------------------------------------------------------
# custom_vjp wrapper
# ---------------------------------------------------------------------------
def _canon(x, W, b, peep, h0, c0):
    f32 = lambda a: a.astype(jnp.float32)
    return (f32(x), f32(W), b.reshape(1, -1).astype(jnp.float32),
            peep.reshape(1, -1).astype(jnp.float32), f32(h0), f32(c0))


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def fused_lstm_sequence(x, W, b, peep, h0, c0, offs: float,
                        interpret: bool):
    """x: [T, B, F] (time-major), W: [F+H, 4H] (i|f|o|g column blocks),
    b: [4H], peep: [3H] (i|f|o), carries [B, H]. Returns
    (hs [T, B, H], h_T, c_T) — semantics identical to the layer's
    lax.scan `_lstm_cell` path with sigmoid gates / tanh cell. The
    primal (inference) path skips the gate/cell residual outputs."""
    hs, cT = _fwd_impl(*_canon(x, W, b, peep, h0, c0), offs, interpret,
                       save_residuals=False)
    return hs.astype(x.dtype), hs[-1].astype(x.dtype), cT.astype(x.dtype)


def _vjp_fwd(x, W, b, peep, h0, c0, offs, interpret):
    hs, cs, ii, ff, oo, gg = _fwd_impl(*_canon(x, W, b, peep, h0, c0),
                                       offs, interpret)
    out = (hs.astype(x.dtype), hs[-1].astype(x.dtype),
           cs[-1].astype(x.dtype))
    return out, (x, W, b, peep, h0, c0, hs, cs, ii, ff, oo, gg)


def _vjp_bwd(offs, interpret, res, cots):
    x, W, b, peep, h0, c0, hs, cs, ii, ff, oo, gg = res
    dhs, dhT, dcT = cots
    f32 = lambda a: a.astype(jnp.float32)
    # the hT/cT cotangents flow into the last step's dh/dc carries
    (dx, dW, db, dp, dh0, dc0) = _bwd_impl(
        f32(x), f32(W), peep.reshape(1, -1).astype(jnp.float32),
        f32(h0), f32(c0), hs, cs, ii, ff, oo, gg,
        f32(dhs), f32(dhT), f32(dcT), interpret)
    return (dx.astype(x.dtype), dW.astype(W.dtype),
            db.reshape(-1).astype(b.dtype),
            dp.reshape(-1).astype(peep.dtype), dh0.astype(h0.dtype),
            dc0.astype(c0.dtype))


fused_lstm_sequence.defvjp(_vjp_fwd, _vjp_bwd)
