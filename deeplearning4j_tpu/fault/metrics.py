"""Fault-subsystem telemetry: thin helpers over the PR-2 registry.

All helpers are no-ops (one global read) when no telemetry session is
active, matching the hot-path contract in telemetry/runtime.py.

Families:
  dl4j_fault_nonfinite_steps_total{policy}   non-finite loss steps seen
  dl4j_fault_retries_total{kind}             transient-error retries
  dl4j_fault_rollbacks_total{policy}         guard restores (skip/rollback)
  dl4j_checkpoint_save_seconds{kind}         save wall time (zip|sharded)
  dl4j_checkpoint_restore_seconds{kind}      restore wall time
  dl4j_elastic_worker_losses_total           stale-lease worker losses seen
  dl4j_elastic_rejoins_total                 workers (re)joining the fleet
  dl4j_elastic_resizes_total                 mesh re-formations (loss/join)
  dl4j_elastic_drains_total                  cross-process SIGTERM drains
  dl4j_elastic_snapshot_seconds              coordinated snapshot wall time
"""
from __future__ import annotations

import contextlib

from ..telemetry.runtime import active as _tel_active

__all__ = ["count_nonfinite", "count_retry", "count_rollback",
           "checkpoint_timer", "count_elastic", "elastic_snapshot_timer"]

#: the elastic event vocabulary (ElasticTrainer supervision loop)
ELASTIC_EVENTS = ("worker_losses", "rejoins", "resizes", "drains")

_ELASTIC_HELP = {
    "worker_losses": "workers declared lost (stale heartbeat lease)",
    "rejoins": "workers that (re)joined the fleet",
    "resizes": "elastic mesh re-formations after worker loss/join",
    "drains": "cross-process SIGTERM-window drains",
}


def count_nonfinite(policy: str, n: int = 1):
    tel = _tel_active()
    if tel is not None:
        tel.registry.counter(
            "dl4j_fault_nonfinite_steps_total",
            "training steps whose loss was NaN/Inf",
            labels=("policy",)).inc(n, policy=policy)


def count_retry(kind: str = "iterator"):
    tel = _tel_active()
    if tel is not None:
        tel.registry.counter(
            "dl4j_fault_retries_total",
            "transient-error retries (bounded exponential backoff)",
            labels=("kind",)).inc(kind=kind)


def count_rollback(policy: str):
    tel = _tel_active()
    if tel is not None:
        tel.registry.counter(
            "dl4j_fault_rollbacks_total",
            "guard-initiated state restores",
            labels=("policy",)).inc(policy=policy)


def checkpoint_timer(op: str, kind: str):
    """Context manager timing a checkpoint `op` ("save"|"restore") of
    `kind` ("zip"|"sharded") into the active registry; null when
    telemetry is disabled."""
    tel = _tel_active()
    if tel is None:
        return contextlib.nullcontext()
    return tel.registry.timer(
        f"dl4j_checkpoint_{op}_seconds",
        f"checkpoint {op} wall seconds", labels=("kind",)).time(kind=kind)


def count_elastic(event: str, n: int = 1):
    """Count an elastic supervision event; `event` is one of
    ELASTIC_EVENTS (each its own label-less counter family, so the
    Prometheus names match the ISSUE contract exactly:
    ``dl4j_elastic_<event>_total``)."""
    if event not in _ELASTIC_HELP:
        raise ValueError(
            f"unknown elastic event {event!r}; one of {ELASTIC_EVENTS}")
    tel = _tel_active()
    if tel is not None:
        tel.registry.counter(
            f"dl4j_elastic_{event}_total", _ELASTIC_HELP[event]).inc(n)


def elastic_snapshot_timer():
    """Context manager timing one coordinated (two-phase-commit) elastic
    snapshot into ``dl4j_elastic_snapshot_seconds``; null when telemetry
    is disabled."""
    tel = _tel_active()
    if tel is None:
        return contextlib.nullcontext()
    return tel.registry.timer(
        "dl4j_elastic_snapshot_seconds",
        "coordinated elastic snapshot wall seconds").time()
