"""Fault-subsystem telemetry: thin helpers over the PR-2 registry.

All helpers are no-ops (one global read) when no telemetry session is
active, matching the hot-path contract in telemetry/runtime.py.

Families:
  dl4j_fault_nonfinite_steps_total{policy}   non-finite loss steps seen
  dl4j_fault_retries_total{kind}             transient-error retries
  dl4j_fault_rollbacks_total{policy}         guard restores (skip/rollback)
  dl4j_checkpoint_save_seconds{kind}         save wall time (zip|sharded)
  dl4j_checkpoint_restore_seconds{kind}      restore wall time
"""
from __future__ import annotations

import contextlib

from ..telemetry.runtime import active as _tel_active

__all__ = ["count_nonfinite", "count_retry", "count_rollback",
           "checkpoint_timer"]


def count_nonfinite(policy: str, n: int = 1):
    tel = _tel_active()
    if tel is not None:
        tel.registry.counter(
            "dl4j_fault_nonfinite_steps_total",
            "training steps whose loss was NaN/Inf",
            labels=("policy",)).inc(n, policy=policy)


def count_retry(kind: str = "iterator"):
    tel = _tel_active()
    if tel is not None:
        tel.registry.counter(
            "dl4j_fault_retries_total",
            "transient-error retries (bounded exponential backoff)",
            labels=("kind",)).inc(kind=kind)


def count_rollback(policy: str):
    tel = _tel_active()
    if tel is not None:
        tel.registry.counter(
            "dl4j_fault_rollbacks_total",
            "guard-initiated state restores",
            labels=("policy",)).inc(policy=policy)


def checkpoint_timer(op: str, kind: str):
    """Context manager timing a checkpoint `op` ("save"|"restore") of
    `kind` ("zip"|"sharded") into the active registry; null when
    telemetry is disabled."""
    tel = _tel_active()
    if tel is None:
        return contextlib.nullcontext()
    return tel.registry.timer(
        f"dl4j_checkpoint_{op}_seconds",
        f"checkpoint {op} wall seconds", labels=("kind",)).time(kind=kind)
