"""Auto-resume plumbing: checkpoint stores + the fit-loop checkpointer.

`CheckpointManager` keeps a directory of crash-safe ModelSerializer zips
(`ckpt_<iteration>.zip`); a file's existence IS its commit (the atomic
rename in util/serializer.py), so `restore_latest` only ever sees complete
files, and still verifies the sha256 manifest and falls back to the next
older checkpoint if one fails.

`FitCheckpointer` is the piece the fit loops talk to: interval saves keyed
on iteration count, resume bookkeeping (how many epochs completed, how
many batches into the current epoch, which shuffle-epoch the iterator must
replay), and a SIGTERM handler that snapshots before exit so a preemption
behaves like a planned checkpoint. Resume restores params, optimizer
state, layer state, iteration/epoch counters AND the model's RNG key, so
a resumed fit replays the identical batch order and dropout keys — the
resumed run matches an uninterrupted one.
"""
from __future__ import annotations

import contextlib
import json
import logging
import math
import os
import re
import zipfile
from typing import Dict, List, Optional, Tuple

from .atomic import CorruptCheckpointError

log = logging.getLogger("deeplearning4j_tpu")

__all__ = ["CheckpointManager", "FitCheckpointer", "maybe_fit_checkpointer",
           "sharded_fit_checkpointer"]

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.zip$")


class CheckpointManager:
    """Directory of crash-safe single-host checkpoints with retention.

    Retention keeps the newest `keep` checkpoints plus (with `keep_best`)
    the one with the best (lowest) recorded score — the reference's
    "best model" idea applied at the checkpoint layer, so a long run can
    always get back both "latest" and "best so far"."""

    def __init__(self, directory: str, keep: int = 3, keep_best: bool = True):
        self.directory = os.path.abspath(directory)
        self.keep = max(1, int(keep))
        self.keep_best = bool(keep_best)
        os.makedirs(self.directory, exist_ok=True)
        self._scores: Dict[str, Optional[float]] = {}

    def _path(self, iteration: int) -> str:
        return os.path.join(self.directory, f"ckpt_{iteration:09d}.zip")

    def entries(self) -> List[Tuple[int, str]]:
        """(iteration, path) ascending — non-matching names (stray files,
        in-flight temp files) are ignored, never crashed on."""
        out = []
        for name in os.listdir(self.directory):
            m = _CKPT_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        return sorted(out)

    # ------------------------------------------------------------------
    def save(self, model, score: Optional[float] = None,
             extra: Optional[Dict] = None) -> str:
        from ..util.serializer import ModelSerializer

        path = self._path(model.iteration_count)
        meta = dict(extra or {})
        if score is not None:
            meta["score"] = float(score)
        ModelSerializer.write_model(model, path, extra_meta=meta)
        self._scores[os.path.basename(path)] = score
        self._gc()
        return path

    def restore_latest(self, model) -> Optional[Dict]:
        """Restore the newest verifiable checkpoint into `model` (params,
        state, updater state, counters, RNG). Corrupt/unverifiable files
        are skipped with a warning — the last good one wins. Returns its
        metadata dict, or None when no usable checkpoint exists."""
        from ..util.serializer import ModelSerializer

        for it, path in reversed(self.entries()):
            try:
                meta = ModelSerializer.restore_into(model, path)
                log.info("resumed from checkpoint %s (iteration %d)",
                         path, it)
                return meta
            except (CorruptCheckpointError, OSError, KeyError,
                    ValueError, zipfile.BadZipFile) as e:
                log.warning("checkpoint %s unusable (%s: %s) — falling "
                            "back to an older one", path,
                            type(e).__name__, e)
        return None

    def _score_of(self, path: str) -> Optional[float]:
        name = os.path.basename(path)
        if name in self._scores:
            return self._scores[name]
        score = None
        try:
            with zipfile.ZipFile(path) as z:
                meta = json.loads(z.read("metadata.json").decode())
            s = meta.get("score")
            score = float(s) if s is not None else None
        except Exception:
            pass
        self._scores[name] = score
        return score

    def _gc(self):
        entries = self.entries()
        keep_paths = {p for _, p in entries[-self.keep:]}
        if self.keep_best:
            scored = [(self._score_of(p), p) for _, p in entries]
            scored = [(s, p) for s, p in scored
                      if s is not None and math.isfinite(s)]
            if scored:
                keep_paths.add(min(scored)[1])
        for _, p in entries:
            if p not in keep_paths:
                try:
                    os.unlink(p)
                except OSError:
                    pass
                self._scores.pop(os.path.basename(p), None)
        # sweep temp files from crashed writes (single-writer directory)
        for name in os.listdir(self.directory):
            if name.endswith(".tmp") and name.startswith("."):
                try:
                    os.unlink(os.path.join(self.directory, name))
                except OSError:
                    pass


# ----------------------------------------------------------------------
# stores: the two backends FitCheckpointer can save through
# ----------------------------------------------------------------------

class _ZipModelStore:
    """Single-host store: the model itself through CheckpointManager."""

    kind = "zip"

    def __init__(self, model, directory: str, keep: int = 3):
        self.model = model
        self.manager = CheckpointManager(directory, keep=keep)

    def iteration(self) -> int:
        return self.model.iteration_count

    def _score(self) -> Optional[float]:
        try:
            import jax.numpy as jnp
            s = float(jnp.asarray(self.model._score))
            return s if math.isfinite(s) else None
        except Exception:
            return None

    def save(self, extra: Dict):
        self.manager.save(self.model, score=self._score(), extra=extra)

    def restore(self) -> Optional[Dict]:
        return self.manager.restore_latest(self.model)


class _ShardedTrainerStore:
    """Mesh store: ParallelTrainer through ShardedCheckpoint (orbax) —
    each step dir commits via its COMMIT marker."""

    kind = "sharded"

    def __init__(self, trainer, directory: str, keep: int = 3):
        from ..parallel.checkpoint import ShardedCheckpoint

        self.trainer = trainer
        self.manager = ShardedCheckpoint(directory, keep=keep)

    def iteration(self) -> int:
        return self.trainer.iteration_count

    def save(self, extra: Dict):
        import numpy as np

        tr = self.trainer
        model = tr.publish_view()
        score = None
        try:
            score = tr.score()
            if not math.isfinite(score):
                score = None
        except Exception:
            pass
        extra = dict(extra)
        extra["trainer_rng"] = np.asarray(tr._rng).tolist()
        self.manager.save(model, tr.iteration_count, score=score,
                          extra=extra)

    def restore(self) -> Optional[Dict]:
        import jax.numpy as jnp
        import numpy as np

        tr = self.trainer
        step = self.manager.restore_latest(tr.model)
        if step is None:
            return None
        meta = self.manager.meta(step) or {}
        # re-place the restored host-side trees onto the mesh (resets
        # iteration/rng, so reinstate them from the checkpoint after)
        tr._prepare()
        tr.iteration_count = tr.model.iteration_count
        rng = meta.get("trainer_rng")
        if rng is not None:
            tr._rng = jnp.asarray(np.asarray(rng, dtype=np.uint32))
        return meta


# ----------------------------------------------------------------------
# the fit-loop checkpointer
# ----------------------------------------------------------------------

class FitCheckpointer:
    """Interval checkpointing + resume bookkeeping for one fit() call.

    The fit loop drives it:
        skip, done = ckpt.resume_into(iterator)     # before the epoch loop
        ...for each trained batch:  ckpt.on_batch()
        ...after each epoch:        ckpt.on_epoch()
        ...after the last epoch:    ckpt.on_fit_end()
    wrapped in `with ckpt.sigterm_snapshot(): ...` so a preemption SIGTERM
    saves a snapshot before the process exits.

    Saved metadata records (epoch_in_fit, batches_into_epoch): resume
    skips the already-trained prefix of the current epoch after
    positioning the iterator's shuffle epoch (`set_epoch`), so the
    resumed run consumes exactly the batches the uninterrupted run would
    have."""

    def __init__(self, store, every: int = 0, resume: bool = False,
                 context: Optional[Dict] = None):
        self.store = store
        self.every = max(0, int(every))
        self.resume = bool(resume)
        # fit-call context recorded into every save's metadata — knobs
        # that are part of the TRAINING MATH (grad_accumulation) so a
        # resume with different values can warn instead of silently
        # diverging from the uninterrupted run
        self.context = dict(context or {})
        self._epoch_in_fit = 0
        self._batches = 0
        self._last_saved_iter = store.iteration()
        self._sigterm_pending = False
        self._sigterm_prev = None

    # ------------------------------------------------------------------
    def resume_into(self, iterator=None) -> Tuple[int, int]:
        """Restore the newest checkpoint (when `resume=True`). Returns
        (batches_to_skip, epochs_already_done); (0, 0) when starting
        fresh."""
        if not self.resume:
            return 0, 0
        meta = self.store.restore()
        if meta is None:
            return 0, 0
        stored_m = meta.get("grad_accumulation")
        cur_m = self.context.get("grad_accumulation")
        if (stored_m is not None and cur_m is not None
                and int(stored_m) != int(cur_m)):
            log.warning(
                "resuming with grad_accumulation=%s but the checkpoint "
                "was written with grad_accumulation=%s — accumulation is "
                "part of the training MATH (unlike superstep grouping), "
                "so the resumed run will not match the uninterrupted one",
                cur_m, stored_m)
        # precision/remat policy mismatches (ISSUE 18): compute_dtype
        # changes the training math; remat/remat_policy only the
        # memory/recompute profile (numerics no-ops) — warn accordingly
        stored_cdt = meta.get("compute_dtype")
        cur_cdt = self.context.get("compute_dtype")
        if ("compute_dtype" in meta and "compute_dtype" in self.context
                and stored_cdt != cur_cdt):
            log.warning(
                "resuming with compute_dtype=%s but the checkpoint was "
                "written with compute_dtype=%s — the compute precision is "
                "part of the training MATH, so the resumed run will not "
                "match the uninterrupted one", cur_cdt, stored_cdt)
        for key in ("remat", "remat_policy"):
            if key in meta and key in self.context \
                    and meta.get(key) != self.context.get(key):
                log.warning(
                    "resuming with %s=%s but the checkpoint was written "
                    "with %s=%s — rematerialization is a numerics no-op "
                    "(memory/recompute profile only), training math is "
                    "unchanged", key, self.context.get(key), key,
                    meta.get(key))
        done = int(meta.get("epoch_in_fit", 0))
        skip = int(meta.get("batches_into_epoch", 0))
        self._epoch_in_fit = done
        self._batches = skip
        self._last_saved_iter = self.store.iteration()
        if iterator is not None and (done or skip):
            if hasattr(iterator, "set_epoch"):
                iterator.set_epoch(done)
            elif getattr(iterator, "shuffle", False):
                log.warning(
                    "resuming a shuffled iterator (%s) without set_epoch() "
                    "support — the replayed epoch may use a different "
                    "permutation than the interrupted run",
                    type(iterator).__name__)
        return skip, done

    # ------------------------------------------------------------------
    def save(self, reason: str = "interval"):
        extra = dict(self.context)
        extra.update({"epoch_in_fit": self._epoch_in_fit,
                      "batches_into_epoch": self._batches,
                      "reason": reason})
        self.store.save(extra)
        self._last_saved_iter = self.store.iteration()

    def maybe_save(self):
        """Interval save keyed on the store's iteration count."""
        if (self.every
                and self.store.iteration() - self._last_saved_iter
                >= self.every):
            self.save()

    def on_batch(self):
        self.on_batches(1)

    def on_batches(self, n: int):
        """Advance the batch cursor by a whole superstep window (n trained
        batches) and act at the window EDGE: any deferred SIGTERM snapshot
        and any due interval save fire here — the first boundary where the
        model's state and the recorded `batches_into_epoch` agree. A
        `checkpoint_every=` cadence therefore rounds up to superstep
        edges; resume composes with any window length because window
        grouping never changes the per-batch math (see nn/superstep.py)."""
        self._batches += int(n)
        self._flush_sigterm()
        self.maybe_save()

    def on_epoch(self):
        self._epoch_in_fit += 1
        self._batches = 0
        self._flush_sigterm()

    def on_fit_end(self):
        self.save(reason="fit_end")

    # ------------------------------------------------------------------
    def _flush_sigterm(self):
        """Act on a deferred SIGTERM at a consistent batch/epoch boundary:
        snapshot, then honor the previous disposition (ignore, chain, or
        exit 143)."""
        import signal

        if not self._sigterm_pending:
            return
        self._sigterm_pending = False
        prev = self._sigterm_prev
        log.warning("SIGTERM received — snapshotting checkpoint at the "
                    "batch boundary before exit")
        self.save(reason="sigterm")
        if prev is signal.SIG_IGN:
            return   # the app chose to ignore SIGTERM; honor that
        if callable(prev) and prev is not signal.SIG_DFL:
            prev(signal.SIGTERM, None)
            return
        raise SystemExit(143)

    @contextlib.contextmanager
    def sigterm_snapshot(self):
        """Install a SIGTERM handler that checkpoints before exiting —
        cluster preemptions (k8s, borg, spot VMs) send SIGTERM with a
        grace window; the snapshot turns them into planned resume points.
        The handler only sets a flag; the save happens at the next
        batch/epoch boundary (or at fit exit), so a signal landing
        mid-train-step can never snapshot torn half-updated state.
        Main-thread only (signal module restriction); elsewhere a no-op."""
        import signal
        import threading

        if threading.current_thread() is not threading.main_thread():
            yield
            return
        prev = signal.getsignal(signal.SIGTERM)
        self._sigterm_prev = prev

        def handler(signum, frame):
            log.warning("SIGTERM received — checkpoint snapshot deferred "
                        "to the next batch boundary")
            self._sigterm_pending = True

        signal.signal(signal.SIGTERM, handler)
        try:
            yield
            # a signal after the last boundary still gets its snapshot
            self._flush_sigterm()
        finally:
            self._sigterm_pending = False
            signal.signal(signal.SIGTERM, prev)


def maybe_fit_checkpointer(model, checkpoint_dir: Optional[str],
                           checkpoint_every: int, resume: bool,
                           keep: int = 3, context: Optional[Dict] = None
                           ) -> Optional[FitCheckpointer]:
    """Build the zip-backed checkpointer for a model fit, or None when
    checkpointing is off. Actionable error on inconsistent knobs."""
    if checkpoint_dir is None:
        if resume or checkpoint_every:
            raise ValueError(
                "resume=True / checkpoint_every need checkpoint_dir= "
                "(the directory checkpoints live in)")
        return None
    return FitCheckpointer(_ZipModelStore(model, checkpoint_dir, keep=keep),
                           every=checkpoint_every, resume=resume,
                           context=context)


def sharded_fit_checkpointer(trainer, checkpoint_dir: Optional[str],
                             checkpoint_every: int, resume: bool,
                             keep: int = 3, context: Optional[Dict] = None
                             ) -> Optional[FitCheckpointer]:
    """Sharded (orbax) checkpointer for ParallelTrainer fits."""
    if checkpoint_dir is None:
        if resume or checkpoint_every:
            raise ValueError(
                "resume=True / checkpoint_every need checkpoint_dir=")
        return None
    return FitCheckpointer(
        _ShardedTrainerStore(trainer, checkpoint_dir, keep=keep),
        every=checkpoint_every, resume=resume, context=context)
