"""TrainingGuard — non-finite-step detection + transient-error retry.

One bad batch (a NaN in the data, an overflowing loss) silently poisons
params forever: every subsequent step multiplies NaNs through the whole
tree, and the first visible symptom is an evaluation that returns garbage
hours later. The guard checks `isfinite(loss)` after every step — the loss
is the one scalar the train step already returns, so the only added cost
is the host sync that reads it (opt-in, like `collect_stats`) — and applies
a policy:

  warn        log + count; keep the (possibly poisoned) step.
  skip_batch  restore the pre-batch snapshot (params/state/updater/rng/
              counters) and continue — the offending batch simply never
              happened. Costs one device-side copy of the model trees per
              step (donation invalidates the originals).
  rollback    restore the last *known-good* snapshot, refreshed every
              `refresh_every` finite steps — reaches further back than
              skip_batch for losses that go bad a few steps after the
              params do.
  halt        raise NonFiniteScoreError immediately.

Plus `next_batch`: bounded exponential-backoff retry around
`iterator.next()` for transient data-source errors (flaky network reader,
NFS hiccup). SimulatedCrash/KeyboardInterrupt are BaseExceptions and are
never retried.
"""
from __future__ import annotations

import logging
import math
import os
import time

from . import metrics as _m
from ..telemetry.recorder import flight_recorder

log = logging.getLogger("deeplearning4j_tpu")

__all__ = ["GuardPolicy", "NonFiniteScoreError", "TrainingGuard"]


class GuardPolicy:
    WARN = "warn"
    SKIP_BATCH = "skip_batch"
    ROLLBACK = "rollback"
    HALT = "halt"

    ALL = (WARN, SKIP_BATCH, ROLLBACK, HALT)


class NonFiniteScoreError(RuntimeError):
    """Loss went NaN/Inf under the `halt` policy (or the guard gave up
    after `max_consecutive` non-finite steps in a row)."""


def _copy_val(v):
    """Deep copy for snapshot entries: jax pytrees get fresh device
    buffers (the train step donates the originals); python scalars pass
    through."""
    if v is None or isinstance(v, (int, float, bool, str)):
        return v
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(lambda a: jnp.array(a, copy=True), v)


class TrainingGuard:
    """Wraps the per-batch fit step of any model-like (MultiLayerNetwork,
    ComputationGraph, ParallelTrainer — anything declaring
    `_fault_state_attrs`) with non-finite detection + snapshot/restore.

    Stateless across fits except the known-good snapshot and counters, so
    one guard can follow a model through several `fit` calls.
    """

    def __init__(self, policy: str = GuardPolicy.WARN, *,
                 refresh_every: int = 10, max_consecutive: int = 25,
                 max_retries: int = 3, backoff_s: float = 0.05,
                 backoff_max_s: float = 2.0, flight_dump_dir=None):
        if policy not in GuardPolicy.ALL:
            raise ValueError(f"unknown guard policy {policy!r}; choose from "
                             f"{GuardPolicy.ALL}")
        self.policy = policy
        # where the flight-recorder dump lands when the guard trips; None
        # keeps it in-memory only (recorder.last_dump / the HTTP debug
        # endpoint)
        self.flight_dump_dir = flight_dump_dir
        self.last_flight_dump = None
        self.refresh_every = max(1, int(refresh_every))
        self.max_consecutive = int(max_consecutive)
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.nonfinite_steps = 0        # total seen (mirrors telemetry)
        self.skipped_batches = 0
        self._consecutive = 0
        self._good_streak = 0
        self._known_good = None

    # ------------------------------------------------------------------
    # snapshots
    # ------------------------------------------------------------------
    @staticmethod
    def _state_attrs(model):
        attrs = getattr(model, "_fault_state_attrs", None)
        if attrs is None:
            raise TypeError(
                f"{type(model).__name__} does not declare _fault_state_attrs"
                " — TrainingGuard cannot snapshot it")
        return attrs

    def _snapshot(self, model):
        return {a: _copy_val(getattr(model, a, None))
                for a in self._state_attrs(model)}

    def _restore(self, model, snap):
        for a, v in snap.items():
            setattr(model, a, _copy_val(v))
        # rollback rewinds counters like iteration_count, so any derived
        # state keyed on them (ParallelTrainer's per-step eval-view
        # caches) would otherwise serve pre-rollback values at a reused
        # key — let the model-like drop it
        hook = getattr(model, "_fault_restored", None)
        if hook is not None:
            hook()

    # ------------------------------------------------------------------
    # per-batch stepping
    # ------------------------------------------------------------------
    @property
    def _needs_snapshot(self) -> bool:
        return self.policy in (GuardPolicy.SKIP_BATCH, GuardPolicy.ROLLBACK)

    def run_step(self, model, step_fn) -> bool:
        """Execute one training step (`step_fn()` mutates `model`) under
        the guard. Returns True if the step was kept, False if it was
        undone (skip_batch/rollback)."""
        snap = self._snapshot(model) if self._needs_snapshot else None
        if self.policy == GuardPolicy.ROLLBACK and self._known_good is None:
            self._known_good = snap
        step_fn()
        import jax.numpy as jnp
        score = float(jnp.asarray(model._score))
        rec = flight_recorder()
        if rec.enabled:
            # the score is ALREADY host-materialized here (the guard's
            # sanctioned sync point) — recording it adds no device sync
            rec.record("train/step", score=score,
                       iteration=getattr(model, "iteration_count", None),
                       finite=math.isfinite(score))
        if math.isfinite(score):
            self._consecutive = 0
            self._good_streak += 1
            if (self.policy == GuardPolicy.ROLLBACK
                    and self._good_streak >= self.refresh_every):
                self._known_good = self._snapshot(model)
                self._good_streak = 0
            return True
        return self._handle_nonfinite(model, snap, score)

    def check_scores(self, model, scores, snap) -> bool:
        """Epoch-granular check for the scan paths: `scores` is the host
        array of per-step losses the epoch dispatch produced, `snap` the
        pre-epoch snapshot (or None for warn/halt). Returns True to keep
        the epoch. Rollback works at epoch granularity here: the
        known-good snapshot refreshes every `refresh_every` finite
        EPOCHS, and a non-finite epoch with no known-good yet falls back
        to the pre-epoch snapshot."""
        import numpy as np
        host = np.asarray(scores, dtype=np.float64)
        bad = int((~np.isfinite(host)).sum())
        rec = flight_recorder()
        if rec.enabled and host.size:
            finite = host[np.isfinite(host)]
            rec.record("train/window_scores", n=int(host.size),
                       nonfinite=bad,
                       last=float(host[-1]),
                       lo=float(finite.min()) if finite.size else None,
                       hi=float(finite.max()) if finite.size else None)
        if bad == 0:
            self._consecutive = 0
            self._good_streak += 1
            if (self.policy == GuardPolicy.ROLLBACK
                    and self._good_streak >= self.refresh_every):
                self._known_good = self._snapshot(model)
                self._good_streak = 0
            return True
        if self.policy == GuardPolicy.ROLLBACK and self._known_good is None:
            self._known_good = snap
        return self._handle_nonfinite(model, snap, float("nan"), n=bad)

    def _dump_flightrecord(self, model, score, action: str):
        """Atomically freeze the flight-recorder ring the moment the guard
        trips, so the dump holds the failing step plus the events leading
        up to it (step scores, collective hashes, KV pressure...). Stored
        on `recorder.last_dump` (served at /debug/flightrecord) and, when
        `flight_dump_dir` is set, written to a timestamped JSON file."""
        rec = flight_recorder()
        if not rec.enabled:
            return None
        path = None
        if self.flight_dump_dir is not None:
            os.makedirs(self.flight_dump_dir, exist_ok=True)
            path = os.path.join(
                self.flight_dump_dir,
                f"flightrecord-{action}-{int(time.time() * 1000)}.json")
        doc = rec.dump(reason=f"guard/{action}", path=path, extra={
            "policy": self.policy,
            "score": score,
            "iteration": getattr(model, "iteration_count", None),
            "nonfinite_steps": self.nonfinite_steps,
            "consecutive": self._consecutive,
        })
        self.last_flight_dump = doc
        return doc

    def _handle_nonfinite(self, model, snap, score, n: int = 1) -> bool:
        self.nonfinite_steps += n
        _m.count_nonfinite(self.policy, n)
        self._consecutive += 1
        self._good_streak = 0
        if self._consecutive > self.max_consecutive:
            self._dump_flightrecord(model, score, "circuit_breaker")
            raise NonFiniteScoreError(
                f"{self._consecutive} consecutive non-finite training steps "
                f"under policy={self.policy!r} — data or learning rate is "
                "systematically bad, refusing to spin")
        if self.policy == GuardPolicy.HALT:
            self._dump_flightrecord(model, score, "halt")
            raise NonFiniteScoreError(
                f"training loss went non-finite ({score}) at iteration "
                f"{getattr(model, 'iteration_count', '?')} (policy=halt)")
        if self.policy == GuardPolicy.WARN:
            log.warning(
                "non-finite training loss (%s) at iteration %s kept under "
                "policy=warn — params may now be poisoned; consider "
                "skip_batch/rollback", score,
                getattr(model, "iteration_count", "?"))
            return True
        if self.policy == GuardPolicy.SKIP_BATCH:
            self._dump_flightrecord(model, score, "skip_batch")
            self._restore(model, snap)
            self.skipped_batches += 1
            _m.count_rollback(self.policy)
            log.warning(
                "non-finite training loss (%s) — batch skipped, state "
                "restored to pre-batch snapshot (policy=skip_batch)", score)
            return False
        # ROLLBACK
        self._dump_flightrecord(model, score, "rollback")
        self._restore(model, self._known_good)
        self.skipped_batches += 1
        _m.count_rollback(self.policy)
        log.warning(
            "non-finite training loss (%s) — rolled back to last known-good "
            "snapshot at iteration %s (policy=rollback)", score,
            getattr(model, "iteration_count", "?"))
        return False

    def note_skipped_micros(self, model, n: int):
        """Accumulated-step skip accounting (nn/superstep.py): under
        policy=skip_batch with grad_accumulation>1 a non-finite MICROBATCH
        loss is neutralized in-trace — its gradient zeroed, the
        accumulated mean renormalized over the finite microbatches — so
        the optimizer step itself survives and no snapshot restore runs.
        This only records that `n` microbatch contributions were dropped
        (counters + telemetry + one warning); the consecutive-step circuit
        breaker is untouched because the STEP was finite."""
        n = int(n)
        if n <= 0:
            return
        self.nonfinite_steps += n
        self.skipped_batches += n
        _m.count_nonfinite(self.policy, n)
        log.warning(
            "%d non-finite microbatch loss(es) near iteration %s — "
            "gradient contribution(s) zeroed, accumulated step "
            "renormalized over the finite microbatches "
            "(policy=skip_batch, grad_accumulation)", n,
            getattr(model, "iteration_count", "?"))

    # ------------------------------------------------------------------
    # transient-error retry around the data source
    # ------------------------------------------------------------------
    def next_batch(self, iterator):
        """iterator.next() with bounded exponential-backoff retry on
        transient errors. StopIteration propagates (not a fault);
        BaseExceptions (SimulatedCrash, KeyboardInterrupt) are never
        retried."""
        attempt = 0
        while True:
            try:
                return iterator.next()
            except StopIteration:
                raise
            except Exception as e:
                attempt += 1
                _m.count_retry("iterator")
                if attempt > self.max_retries:
                    log.error(
                        "data source still failing after %d retries: %s",
                        self.max_retries, e)
                    raise
                delay = min(self.backoff_s * (2 ** (attempt - 1)),
                            self.backoff_max_s)
                log.warning(
                    "transient data-source error (%s: %s) — retry %d/%d "
                    "in %.3fs", type(e).__name__, e, attempt,
                    self.max_retries, delay)
                time.sleep(delay)
