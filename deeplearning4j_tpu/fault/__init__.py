"""Fault tolerance: crash-safe checkpoints, auto-resume, training guards,
and a deterministic fault-injection harness.

The reference has *no* mid-job checkpoint/resume (Spark masters save
nothing — SURVEY.md §5); production training treats frequent verified
checkpoints as THE fault-tolerance primitive (Eisenman et al.,
*Check-N-Run*, NSDI 2022). Four pieces:

  * `atomic`    — temp-file + fsync + atomic-rename writes, sha256
                  manifests, directory COMMIT markers. Used by
                  `util/serializer.py` and `parallel/checkpoint.py`.
  * `resume`    — `CheckpointManager` (retained, verified zip checkpoints)
                  and `FitCheckpointer` (interval saves, resume
                  bookkeeping, SIGTERM snapshot) behind the
                  `checkpoint_dir= / checkpoint_every= / resume=` knobs on
                  every fit path.
  * `guard`     — `TrainingGuard`: isfinite check on every step's loss
                  with warn/skip_batch/rollback/halt policies, plus
                  bounded-backoff retry for transient iterator errors.
  * `injection` — `FaultyIterator` + `crash_at_write` crash points, plus
                  the ISSUE-19 process-level injectors (`kill_at_step`,
                  `hang_at_step`, `sigterm_at_step`,
                  `install_faults_from_env`) for the elastic kill/rejoin
                  drills — so every recovery path above is tested
                  deterministically.

Everything emits telemetry through the PR-2 registry
(`dl4j_fault_nonfinite_steps_total`, `dl4j_fault_retries_total`,
`dl4j_fault_rollbacks_total`, `dl4j_checkpoint_{save,restore}_seconds`,
`dl4j_elastic_*_total`, `dl4j_elastic_snapshot_seconds`).
"""
from .atomic import (COMMIT_MARKER, CorruptCheckpointError, atomic_replace,
                     read_commit_marker, sha256_hex, write_commit_marker)
from .guard import GuardPolicy, NonFiniteScoreError, TrainingGuard
from .injection import (FaultyIterator, SimulatedCrash, clear_crash_hooks,
                        crash_at_write, hang_at_step,
                        install_faults_from_env, kill_at_step,
                        sigterm_at_step)
from .resume import (CheckpointManager, FitCheckpointer,
                     maybe_fit_checkpointer, sharded_fit_checkpointer)

__all__ = [
    "COMMIT_MARKER", "CorruptCheckpointError", "atomic_replace",
    "read_commit_marker", "sha256_hex", "write_commit_marker",
    "GuardPolicy", "NonFiniteScoreError", "TrainingGuard",
    "FaultyIterator", "SimulatedCrash", "crash_at_write",
    "kill_at_step", "hang_at_step", "sigterm_at_step",
    "install_faults_from_env", "clear_crash_hooks",
    "CheckpointManager", "FitCheckpointer", "maybe_fit_checkpointer",
    "sharded_fit_checkpointer",
]
