"""Crash-safe durable writes: temp file + fsync + atomic rename, sha256
manifests, commit markers.

The invariant every writer in this package maintains (Eisenman et al.,
*Check-N-Run*, NSDI 2022 — frequent, **verified** checkpoints as the core
fault-tolerance primitive): at any kill point, the destination path either
holds the complete previous version or the complete new version — never a
torn write. `os.replace` on a same-directory temp file is the commit;
everything before it is invisible to readers.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, Optional

__all__ = ["CorruptCheckpointError", "atomic_replace", "sha256_hex",
           "write_commit_marker", "read_commit_marker", "COMMIT_MARKER"]

COMMIT_MARKER = "COMMIT"


class CorruptCheckpointError(RuntimeError):
    """A checkpoint failed manifest/commit verification (torn write, bit
    rot, or a crash between payload and commit)."""


def sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _fsync_dir(path: str):
    """fsync the containing directory so the rename itself is durable
    (best effort — not all filesystems support dir fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_replace(path: str, data: bytes, crash_point: Optional[str] = None):
    """Write `data` to `path` crash-safely: same-directory temp file,
    fsync, then `os.replace` (atomic on POSIX). A crash at ANY point
    leaves `path` either absent or holding its previous complete contents
    — never a torn write. `crash_point` names the injection hook fired
    after the temp bytes land (see fault/injection.py)."""
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(prefix=f".{os.path.basename(path)}.",
                               suffix=".tmp", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            if crash_point:
                from .injection import fire_crash_point
                fire_crash_point(crash_point, path=path, tmp=tmp,
                                 nbytes=len(data))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(d)
    except BaseException:
        # best-effort cleanup; a SimulatedCrash/SIGKILL that skips this
        # leaves only a .tmp file, which GC sweeps and readers ignore
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_commit_marker(step_dir: str, meta: Optional[Dict] = None):
    """Mark a checkpoint directory complete: the atomic appearance of
    `COMMIT` (written last, after all payload writes returned) is the
    directory-granular commit point readers trust."""
    payload = json.dumps(meta or {}, sort_keys=True).encode()
    atomic_replace(os.path.join(step_dir, COMMIT_MARKER), payload)


def read_commit_marker(step_dir: str) -> Optional[Dict]:
    """The commit metadata, or None if the directory never committed
    (crashed mid-save) or the marker is unreadable."""
    try:
        with open(os.path.join(step_dir, COMMIT_MARKER), "rb") as f:
            return json.loads(f.read().decode())
    except (OSError, ValueError):
        return None
