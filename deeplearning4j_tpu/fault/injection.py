"""Deterministic fault injection for testing every recovery path.

Two injection surfaces:

  * **Crash points** — named hook points compiled into the durable-write
    paths (`util/serializer.py`, `parallel/checkpoint.py`). A registered
    hook fires at the point; `crash_at_write` installs one that raises
    `SimulatedCrash` on the nth firing, so "the process died exactly
    between writing the payload and committing it" is a reproducible test
    case instead of a production incident. Points in use:
      - ``zip/temp_written``    after the temp file's bytes are written,
                                before fsync+atomic rename (ModelSerializer)
      - ``sharded/tree_written`` after orbax wrote the step dir, before the
                                COMMIT marker (ShardedCheckpoint.save)
  * **FaultyIterator** — a DataSetIterator wrapper injecting data-plane
    faults at exact global batch ordinals: transient/permanent raise,
    all-NaN feature batches (numerically poisoned data), stalls.

`SimulatedCrash` subclasses BaseException so it sails through the
`except Exception` retry/cleanup layers the way SIGKILL would — only test
harnesses catch it.
"""
from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, Optional

import numpy as np

from ..datasets.iterators import DataSet, DataSetIterator

__all__ = ["SimulatedCrash", "fire_crash_point", "crash_at_write",
           "FaultyIterator"]


class SimulatedCrash(BaseException):
    """Injected mid-write process death (BaseException on purpose: generic
    `except Exception` recovery code must not be able to swallow it)."""


_crash_hooks: Dict[str, Callable] = {}


def fire_crash_point(point: str, **info):
    """Called by durable-write paths at their commit boundaries. No-op
    (one dict lookup) unless a test installed a hook for `point`."""
    cb = _crash_hooks.get(point)
    if cb is not None:
        cb(point, info)


@contextlib.contextmanager
def crash_at_write(point: str = "zip/temp_written", nth: int = 1):
    """Install a crash hook: the `nth` firing of `point` raises
    SimulatedCrash. Yields a dict whose "fired" entry counts firings, so
    tests can assert the crash actually hit the intended write."""
    state = {"fired": 0}

    def cb(p, info):
        state["fired"] += 1
        if state["fired"] == nth:
            raise SimulatedCrash(
                f"injected crash at {p} (firing #{nth}; {info})")

    prev = _crash_hooks.get(point)
    _crash_hooks[point] = cb
    try:
        yield state
    finally:
        if prev is None:
            _crash_hooks.pop(point, None)
        else:
            _crash_hooks[point] = prev


class FaultyIterator(DataSetIterator):
    """Wrap a DataSetIterator with faults at exact **global** batch
    ordinals (0-based, counted across epochs — reset() does not reset the
    ordinal, so "the 7th batch ever served" is deterministic even
    mid-epoch-2).

      raise_at    next() raises `exc` when about to serve this ordinal.
      fail_times  how many consecutive next() calls fail there before the
                  batch is served (transient fault; default 1).
                  -1 = permanent (every call fails).
      exc         exception factory/class (default: ``OSError`` with an
                  "injected transient fault" message). Pass SimulatedCrash
                  to model a hard process death (not retryable).
      nan_at      serve this ordinal with all-NaN features (numerically
                  poisoned batch — exercises TrainingGuard policies).
      delay_at / delay_s   sleep before serving this ordinal (flaky/slow
                  source; exercises timeout/backoff behavior).
    """

    def __init__(self, base: DataSetIterator, *, raise_at: Optional[int] = None,
                 fail_times: int = 1, exc=None, nan_at: Optional[int] = None,
                 delay_at: Optional[int] = None, delay_s: float = 0.0):
        self.base = base
        self.raise_at = raise_at
        self.fail_times = fail_times
        self.exc = exc if exc is not None else OSError
        self.nan_at = nan_at
        self.delay_at = delay_at
        self.delay_s = float(delay_s)
        self._served = 0      # global ordinal of the NEXT batch
        self._failed = 0

    # -- iterator contract ------------------------------------------------
    def has_next(self) -> bool:
        return self.base.has_next()

    def next(self) -> DataSet:
        i = self._served
        if self.raise_at is not None and i == self.raise_at and (
                self.fail_times < 0 or self._failed < self.fail_times):
            self._failed += 1
            exc = self.exc
            raise (exc(f"injected transient fault at batch {i} "
                       f"(attempt {self._failed})")
                   if isinstance(exc, type) else exc)
        if self.delay_at is not None and i == self.delay_at and self.delay_s:
            time.sleep(self.delay_s)
        ds = self.base.next()
        self._served += 1
        if self.nan_at is not None and i == self.nan_at:
            feats = np.full_like(np.asarray(ds.features, np.float64), np.nan)
            ds = DataSet(feats.astype(np.asarray(ds.features).dtype),
                         ds.labels, ds.features_mask, ds.labels_mask)
        return ds

    def reset(self):
        self.base.reset()

    def batch(self) -> int:
        return self.base.batch()

    def set_epoch(self, epoch: int):
        """Forward checkpoint-resume epoch positioning to the base."""
        if hasattr(self.base, "set_epoch"):
            self.base.set_epoch(epoch)

    @property
    def async_supported(self) -> bool:
        # faults must fire on the consumer thread at deterministic points
        return False
