"""Deterministic fault injection for testing every recovery path.

Two injection surfaces:

  * **Crash points** — named hook points compiled into the durable-write
    paths (`util/serializer.py`, `parallel/checkpoint.py`). A registered
    hook fires at the point; `crash_at_write` installs one that raises
    `SimulatedCrash` on the nth firing, so "the process died exactly
    between writing the payload and committing it" is a reproducible test
    case instead of a production incident. Points in use:
      - ``zip/temp_written``    after the temp file's bytes are written,
                                before fsync+atomic rename (ModelSerializer)
      - ``sharded/tree_written`` after orbax wrote the step dir, before the
                                COMMIT marker (ShardedCheckpoint.save)
  * **FaultyIterator** — a DataSetIterator wrapper injecting data-plane
    faults at exact global batch ordinals: transient/permanent raise,
    all-NaN feature batches (numerically poisoned data), stalls.

`SimulatedCrash` subclasses BaseException so it sails through the
`except Exception` retry/cleanup layers the way SIGKILL would — only test
harnesses catch it.

**Process-level injectors** (ISSUE 19, the elastic drills): where
`crash_at_write` models a death *inside this interpreter* (an exception a
harness can observe), `kill_at_step`/`hang_at_step` model the death of a
whole WORKER in a multi-process world — `os._exit` (no teardown, the
userspace stand-in for SIGKILL/preemption) or an indefinite stall (the
lease-expiry path). They ride the ``elastic/step`` crash point the
`ElasticTrainer` supervision loop fires once per optimizer step, and
`install_faults_from_env` arms them (plus the write-boundary injectors)
from ``DL4J_*`` environment variables so `tests/_dist_child.py` children
can be killed at exact steps / exact two-phase-commit boundaries:
``elastic/shards_written`` (shard durable but unmarked),
``elastic/durable_marked`` (between the phases) and
``elastic/commit_marker`` (torn COMMIT marker — temp bytes written, never
renamed).

**Continual-plane points** (ISSUE 20): the `ContinualTrainer` loop fires
one point at every durable boundary of a train-to-serve cycle, so the
crash drill in tests/test_continual.py can kill the loop between ANY two
effects and assert recovery serves exactly the pre-crash committed
version: ``continual/stable_registered``, ``continual/window_consumed``,
``continual/window_trained``, ``continual/candidate_saved``,
``continual/window_record`` (window journaled — the train-once commit
point), ``continual/offset_committed``, ``continual/gate_record``,
``continual/canary_started``, ``continual/decision_record``
(promoted/rolled_back journaled — THE decision commit point, before the
registry flip) and ``continual/decision_applied``.
"""
from __future__ import annotations

import contextlib
import os
import signal
import time
from typing import Callable, Dict, Optional

import numpy as np

from ..datasets.iterators import DataSet, DataSetIterator

__all__ = ["SimulatedCrash", "fire_crash_point", "crash_at_write",
           "install_crash_at_write", "kill_at_step", "hang_at_step",
           "sigterm_at_step", "install_faults_from_env", "clear_crash_hooks",
           "FaultyIterator"]

#: the per-optimizer-step crash point the ElasticTrainer loop fires
#: (info carries step= and worker=) — the hook surface for kill/hang/
#: SIGTERM-at-step process-level injection
STEP_POINT = "elastic/step"


class SimulatedCrash(BaseException):
    """Injected mid-write process death (BaseException on purpose: generic
    `except Exception` recovery code must not be able to swallow it)."""


_crash_hooks: Dict[str, Callable] = {}


def fire_crash_point(point: str, **info):
    """Called by durable-write paths at their commit boundaries. No-op
    (one dict lookup) unless a test installed a hook for `point`."""
    cb = _crash_hooks.get(point)
    if cb is not None:
        cb(point, info)


@contextlib.contextmanager
def crash_at_write(point: str = "zip/temp_written", nth: int = 1):
    """Install a crash hook: the `nth` firing of `point` raises
    SimulatedCrash. Yields a dict whose "fired" entry counts firings, so
    tests can assert the crash actually hit the intended write."""
    state = {"fired": 0}

    def cb(p, info):
        state["fired"] += 1
        if state["fired"] == nth:
            raise SimulatedCrash(
                f"injected crash at {p} (firing #{nth}; {info})")

    prev = _crash_hooks.get(point)
    _crash_hooks[point] = cb
    try:
        yield state
    finally:
        if prev is None:
            _crash_hooks.pop(point, None)
        else:
            _crash_hooks[point] = prev


def install_crash_at_write(point: str, nth: int = 1):
    """Non-contextmanager `crash_at_write`: installs a persistent hook
    raising SimulatedCrash on the nth firing of `point`. For subprocess
    children (armed from env, die with the process) — in-process tests
    should prefer the scoped `crash_at_write`. Returns the firing-count
    state dict."""
    state = {"fired": 0}

    def cb(p, info):
        state["fired"] += 1
        if state["fired"] == nth:
            raise SimulatedCrash(
                f"injected crash at {p} (firing #{nth}; {info})")

    _crash_hooks[point] = cb
    return state


def _install_step_hook(n: int, action: Callable[[dict], None]):
    n = int(n)

    def cb(p, info):
        if int(info.get("step", -1)) == n:
            action(info)

    _crash_hooks[STEP_POINT] = cb


def kill_at_step(n: int, exit_code: int = 137):
    """Hard-kill this process when the elastic supervision loop reaches
    optimizer step `n`: `os._exit` skips every finally/atexit/flush — the
    closest userspace stand-in for SIGKILL/TPU preemption. Default exit
    code 137 (= 128+SIGKILL) so harnesses can tell an injected kill from
    an ordinary crash."""
    _install_step_hook(n, lambda info: os._exit(exit_code))


def hang_at_step(n: int, hang_s: float = 3600.0):
    """Stall this process at optimizer step `n` without exiting — the
    worker stops renewing its heartbeat lease while its peer keeps
    running, which is exactly the failure the lease TTL exists to detect
    (a wedged host looks identical to a dead one from the outside)."""
    _install_step_hook(n, lambda info: time.sleep(float(hang_s)))


def sigterm_at_step(n: int):
    """Deliver SIGTERM to OURSELVES at optimizer step `n` — deterministic
    preemption notice for drills: the elastic loop's handler defers it to
    the next superstep edge and requests a cross-process drain there."""
    _install_step_hook(
        n, lambda info: os.kill(os.getpid(), signal.SIGTERM))


def _exit_at_write(point: str, nth: int = 1, exit_code: int = 137):
    """Hard `os._exit` on the nth firing of a write-boundary crash point
    — the two-phase-commit kill drills use this to die exactly between
    a durable write and its marker with NO Python teardown."""
    state = {"fired": 0}

    def cb(p, info):
        state["fired"] += 1
        if state["fired"] == nth:
            os._exit(exit_code)

    _crash_hooks[point] = cb


def clear_crash_hooks():
    """Drop every installed crash hook (test teardown for the persistent
    `install_*` variants; the scoped `crash_at_write` cleans up itself)."""
    _crash_hooks.clear()


def install_faults_from_env(env=None):
    """Arm process-level injectors from environment variables — the
    subprocess injection surface for `tests/_dist_child.py` children
    (the parent can't reach into a child's interpreter, but it can set
    its env):

      DL4J_KILL_AT_STEP=n            kill_at_step(n)
      DL4J_HANG_AT_STEP=n[:secs]     hang_at_step(n, secs)
      DL4J_SIGTERM_AT_STEP=n         sigterm_at_step(n)
      DL4J_CRASH_AT_WRITE=point[:nth]  raise SimulatedCrash at the point
      DL4J_EXIT_AT_WRITE=point[:nth]   os._exit(137) at the point (the
                                       mid-commit kill drills)

    Returns the list of armed injector names (empty when none set)."""
    env = os.environ if env is None else env
    armed = []
    v = env.get("DL4J_KILL_AT_STEP")
    if v:
        kill_at_step(int(v))
        armed.append(f"kill_at_step({v})")
    v = env.get("DL4J_HANG_AT_STEP")
    if v:
        n, _, secs = v.partition(":")
        hang_at_step(int(n), float(secs) if secs else 3600.0)
        armed.append(f"hang_at_step({n})")
    v = env.get("DL4J_SIGTERM_AT_STEP")
    if v:
        sigterm_at_step(int(v))
        armed.append(f"sigterm_at_step({v})")
    v = env.get("DL4J_CRASH_AT_WRITE")
    if v:
        point, _, nth = v.partition(":")
        install_crash_at_write(point, int(nth) if nth else 1)
        armed.append(f"crash_at_write({point})")
    v = env.get("DL4J_EXIT_AT_WRITE")
    if v:
        point, _, nth = v.partition(":")
        _exit_at_write(point, int(nth) if nth else 1)
        armed.append(f"exit_at_write({point})")
    return armed


class FaultyIterator(DataSetIterator):
    """Wrap a DataSetIterator with faults at exact **global** batch
    ordinals (0-based, counted across epochs — reset() does not reset the
    ordinal, so "the 7th batch ever served" is deterministic even
    mid-epoch-2).

      raise_at    next() raises `exc` when about to serve this ordinal.
      fail_times  how many consecutive next() calls fail there before the
                  batch is served (transient fault; default 1).
                  -1 = permanent (every call fails).
      exc         exception factory/class (default: ``OSError`` with an
                  "injected transient fault" message). Pass SimulatedCrash
                  to model a hard process death (not retryable).
      nan_at      serve this ordinal with all-NaN features (numerically
                  poisoned batch — exercises TrainingGuard policies).
      delay_at / delay_s   sleep before serving this ordinal (flaky/slow
                  source; exercises timeout/backoff behavior).
    """

    def __init__(self, base: DataSetIterator, *, raise_at: Optional[int] = None,
                 fail_times: int = 1, exc=None, nan_at: Optional[int] = None,
                 delay_at: Optional[int] = None, delay_s: float = 0.0):
        self.base = base
        self.raise_at = raise_at
        self.fail_times = fail_times
        self.exc = exc if exc is not None else OSError
        self.nan_at = nan_at
        self.delay_at = delay_at
        self.delay_s = float(delay_s)
        self._served = 0      # global ordinal of the NEXT batch
        self._failed = 0

    # -- iterator contract ------------------------------------------------
    def has_next(self) -> bool:
        return self.base.has_next()

    def next(self) -> DataSet:
        i = self._served
        if self.raise_at is not None and i == self.raise_at and (
                self.fail_times < 0 or self._failed < self.fail_times):
            self._failed += 1
            exc = self.exc
            raise (exc(f"injected transient fault at batch {i} "
                       f"(attempt {self._failed})")
                   if isinstance(exc, type) else exc)
        if self.delay_at is not None and i == self.delay_at and self.delay_s:
            time.sleep(self.delay_s)
        ds = self.base.next()
        self._served += 1
        if self.nan_at is not None and i == self.nan_at:
            feats = np.full_like(np.asarray(ds.features, np.float64), np.nan)
            ds = DataSet(feats.astype(np.asarray(ds.features).dtype),
                         ds.labels, ds.features_mask, ds.labels_mask)
        return ds

    def reset(self):
        self.base.reset()

    def batch(self) -> int:
        return self.base.batch()

    def set_epoch(self, epoch: int):
        """Forward checkpoint-resume epoch positioning to the base."""
        if hasattr(self.base, "set_epoch"):
            self.base.set_epoch(epoch)

    @property
    def async_supported(self) -> bool:
        # faults must fire on the consumer thread at deterministic points
        return False
